"""Fleet observability: the cross-rank telemetry plane.

One slow rank sets the step time for every rank (tail-at-scale); before
the pod-scale serving leap we need to SEE the fleet, not infer it. This
module is the cross-rank counterpart of `registry`/`tracing`:

- **collective profiler** — `parallel/dist.py` host-level collectives
  (`allreduce`/`broadcast`/`barrier`/`exchange_objs`) are wall-timed per
  call through the `_PROF` hook armed here (same dead-branch discipline
  as `stages.py`: a module-global that stays ``None`` until `enable()`).
  The in-graph wrappers in `parallel/collectives.py` run INSIDE
  shard_map/pjit traced bodies where host timers would measure *trace*
  time, so they get a trace-time byte/call census (`_CENSUS` hook) plus
  `probe_collectives()`: an eager microbench that times each wrapped op
  in its own jitted shard_map program and reports achieved GB/s against
  the `PEAK_LINK_GBS` ICI roof (the comms sibling of
  `roofline.PEAK_HBM_GBS`).
- **barrier arrival skew** — `dist.barrier()` records its local arrival
  timestamp, exchanges arrivals over `dist.exchange_objs`, and feeds the
  spread into `mx_barrier_skew_seconds`; per-rank *lateness*
  (arrival − earliest arrival) is the direct straggler signal.
- **fleet aggregation** — `fleet_report()` ships every rank's registry
  snapshot over a chunked `exchange_objs` transport (`exchange_large`,
  which splits past the 4 KiB command-slot cap), merges per-rank and
  fleet-aggregate views, and names a straggler by signed z-score over
  per-rank step time and barrier lateness (`straggler_scores`), surfaced
  as `mx_fleet_straggler_rank` and a `monitor.check()` health hook
  (`install_health_check`).
- **trace stitching** — `estimate_clock_offsets()` runs an NTP-style
  barrier-bracketed timestamp exchange (offset = midpoint − rank 0's
  midpoint, uncertainty = half the exchange interval); `dump_rank_trace`
  writes a rank-stamped span dump and `stitch_traces` merges a directory
  of them into one Perfetto timeline, one process lane per rank, with
  `ts_us` rebased by the estimated offsets. Collective spans carry a
  `coll_seq` attribute (collectives are issued in the same order on
  every rank) so barrier #N can be matched across lanes.
- **flight-recorder fanout** — on an uncaught exception the crashing
  rank drops a `fleet_crash_rank*.marker` next to its (rank-stamped)
  flightrec; every surviving rank's atexit hook sees the marker and
  dumps a ``peer_crash`` flightrec too (shared-filesystem assumption —
  ranks must agree on `MXNET_FLIGHTREC_DIR`). `merge_flight_dumps`
  collects the per-rank dumps into one post-mortem
  (`tools/fleetwatch.py --postmortem` renders it).

Metric series (all registered lazily, per-rank local until aggregated):

==================================  =========  =========================
``mx_collective_seconds``           histogram  per-op wall time, labels
                                               ``op=``/``axis=`` ("host"
                                               for dist.*, the mesh axis
                                               for probed wrappers)
``mx_collective_bytes_total``       counter    payload bytes entering a
                                               wrapped collective (per
                                               call for dist.*, per
                                               TRACE for in-graph ops)
``mx_collective_gbs``               gauge      last achieved GB/s
``mx_collective_peak_frac``         gauge      achieved / PEAK_LINK_GBS
``mx_collective_trace_calls_total`` counter    census of wrapper calls
                                               seen at trace time
``mx_barrier_skew_seconds``         histogram  arrival spread at barrier
``mx_fleet_straggler_rank``         gauge      argmax straggler score
``mx_fleet_straggler_score``        gauge      its z-score
``mx_fleet_ranks``                  gauge      ranks in the last report
``mx_fleet_clock_offset_seconds``   gauge      this rank's clock offset
==================================  =========  =========================

Arming: `enable()` (or ``MXNET_TELEMETRY=1`` / ``MXNET_FLEET=1`` via
`util._apply_env_config`). Enable on EVERY rank or none — the skew and
report exchanges are collectives and a half-armed fleet would hang.
Knobs: ``MXNET_FLEET_SKEW_EVERY`` (sample every Nth barrier, 0=off),
``MXNET_FLEET_CHUNK_BYTES``, ``MXNET_FLEET_STRAGGLER_Z``,
``MXNET_FLEET_TRACE_DIR``.
"""
from __future__ import annotations

import atexit
import contextlib
import glob as _glob
import json
import math
import os
import pickle
import re
import socket
import sys
import threading
import time
import zlib

from . import registry, tracing
from .locks import tracked_lock

__all__ = [
    "enable", "disable", "is_enabled", "probe_collectives",
    "PEAK_LINK_GBS", "fleet_report", "straggler_scores", "exchange_large",
    "install_health_check", "estimate_clock_offsets", "dump_rank_trace",
    "stitch_traces", "merge_flight_dumps", "barrier_stats", "reset",
]

_PKG = __name__.rsplit(".", 2)[0]

_ENABLED = False
_LOCK = tracked_lock("telemetry.fleet", kind="lock")

# approximate aggregate ICI bandwidth per chip, GB/s one direction
# (vendor-published figures; the comms sibling of roofline.PEAK_HBM_GBS).
# CPU/GPU hosts have no entry — peak_frac is omitted there.
PEAK_LINK_GBS = {"v3": 100.0, "v4": 300.0, "v5e": 200.0, "v5p": 600.0,
                 "v6e": 448.0}

COLLECTIVE_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2,
                      0.1, 0.25, 1.0, 5.0)
SKEW_BUCKETS = (1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.5,
                1.0, 5.0)

_SEQ: dict = {}               # op -> issue sequence (matches across ranks)
_SEQ_LOCK = tracked_lock("telemetry.fleet.seq", kind="lock")

_BARRIER = {"count": 0, "lateness_sum": 0.0, "lateness_max": 0.0,
            "skew_sum": 0.0, "skew_max": 0.0}
_CLOCK: dict = {"offsets": None, "bound_s": None}
_FLEET_TRACE = {"id": None}   # rank 0's trace id, learned at a barrier
_LAST_REPORT = None
_FANOUT = {"armed": False, "prev_hook": None}


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------


def is_enabled():
    return _ENABLED


def enable():
    """Arm the fleet plane: dist-op profiling hook, in-graph census hook,
    flight-recorder rank stamp + crash fanout. Idempotent."""
    global _ENABLED
    with _LOCK:
        if _ENABLED:
            return
        _ENABLED = True
    _arm()
    tracing.register_flight_context("fleet", _flight_context)
    _arm_flight_fanout()


def disable():
    global _ENABLED
    with _LOCK:
        _ENABLED = False
    _arm()


def _arm():
    """(Re)point the hot hooks in parallel/dist.py and
    parallel/collectives.py — both modules also self-arm at import via
    `_rearm()` so enable/import order doesn't matter (the
    `injection._arm_hot_hooks` pattern)."""
    dist_mod = sys.modules.get(_PKG + ".parallel.dist")
    if dist_mod is not None:
        dist_mod._PROF = sys.modules[__name__] if _ENABLED else None
    coll_mod = sys.modules.get(_PKG + ".parallel.collectives")
    if coll_mod is not None:
        coll_mod._CENSUS = _census_record if _ENABLED else None


def _rank_hint():
    """Best-effort rank WITHOUT touching jax (usable from excepthooks and
    before dist.initialize): launch.py env first, live runtime second."""
    v = os.environ.get("PROCESS_ID") or os.environ.get("DMLC_RANK")
    if v is not None:
        try:
            return int(v)
        except ValueError:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:   # noqa: FL006 - no runtime yet: rank hint falls back to 0
            pass
    return 0


def _nprocs_hint():
    v = os.environ.get("NUM_PROCESSES") or os.environ.get("DMLC_NUM_WORKER")
    if v is not None:
        try:
            return int(v)
        except ValueError:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:   # noqa: FL006 - no runtime yet: world-size hint falls back to 1
            pass
    return 1


def _rank():
    try:
        from ..parallel import dist

        if dist.is_initialized():
            return dist.rank()
    except Exception:   # noqa: FL006 - telemetry never breaks the caller: hint fallback
        pass
    return _rank_hint()


def reset():
    """Forget per-run fleet state (tests)."""
    global _LAST_REPORT
    with _SEQ_LOCK:
        _SEQ.clear()
    with _LOCK:
        # the flight-recorder fanout reads these from the crash thread
        # (racecheck RC001): update under the module lock
        _BARRIER.update(count=0, lateness_sum=0.0, lateness_max=0.0,
                        skew_sum=0.0, skew_max=0.0)
        _CLOCK.update(offsets=None, bound_s=None)
    _FLEET_TRACE["id"] = None
    _LAST_REPORT = None


def barrier_stats():
    with _LOCK:
        b = dict(_BARRIER)
    n = b.pop("count")
    return {"count": n,
            "lateness_mean": (b["lateness_sum"] / n) if n else 0.0,
            "lateness_max": b["lateness_max"],
            "skew_mean": (b["skew_sum"] / n) if n else 0.0,
            "skew_max": b["skew_max"]}


# ---------------------------------------------------------------------------
# collective profiler: dist.* hook + in-graph census
# ---------------------------------------------------------------------------


def _next_seq(op):
    with _SEQ_LOCK:
        _SEQ[op] = _SEQ.get(op, 0) + 1
        return _SEQ[op]


def _observe(op, axis, nbytes, seconds, link_bytes=None, peak=None):
    labels = {"op": op, "axis": axis}
    registry.histogram("mx_collective_seconds",
                       "wall time per wrapped collective",
                       labels=labels,
                       buckets=COLLECTIVE_BUCKETS).observe(seconds)
    if nbytes:
        registry.counter("mx_collective_bytes_total",
                         "payload bytes entering wrapped collectives",
                         labels=labels).inc(int(nbytes))
    moved = link_bytes if link_bytes is not None else nbytes
    if moved and seconds > 0:
        gbs = moved / seconds / 1e9
        registry.gauge("mx_collective_gbs",
                       "last achieved collective GB/s",
                       labels=labels).set(gbs)
        if peak:
            registry.gauge("mx_collective_peak_frac",
                           "achieved GB/s / PEAK_LINK_GBS",
                           labels=labels).set(gbs / peak)


@contextlib.contextmanager
def dist_op(op, nbytes, **attrs):
    """Context manager `parallel/dist.py` wraps its eager collectives in
    (via the `_PROF` hook — dist.py itself stays free of ad-hoc `time.*`,
    which lint FL014 enforces)."""
    seq = _next_seq(op)
    t0 = time.perf_counter()
    with tracing.span("dist." + op, lane="dist", op=op,
                      nbytes=int(nbytes), coll_seq=seq, **attrs):
        try:
            yield
        finally:
            _observe(op, "host", nbytes, time.perf_counter() - t0)


def barrier_probe(tag, run):
    """Time `run()` (the barrier allreduce) and — every
    ``MXNET_FLEET_SKEW_EVERY``-th barrier — exchange local arrival
    timestamps to measure the fleet's arrival spread. All ranks must be
    armed identically: the skew exchange is itself a collective."""
    from ..parallel import dist

    seq = _next_seq("barrier")
    t_arrive = time.time()
    with tracing.span("dist.barrier", lane="dist", op="barrier", tag=tag,
                      coll_seq=seq):
        t0 = time.perf_counter()
        run()
        _observe("barrier", "host", 4, time.perf_counter() - t0)
        every = _env_int("MXNET_FLEET_SKEW_EVERY", 1)
        if every > 0 and seq % every == 0:
            _exchange_arrival(dist, t_arrive)


def _exchange_arrival(dist, t_arrive):
    me = dist.rank()
    try:
        got = dist.exchange_objs({"rank": me, "t": t_arrive,
                                  "trace": tracing.current_trace_id()})
    except Exception:
        return
    arrivals = {}
    for g in got:
        if isinstance(g, dict) and "t" in g:
            arrivals[int(g["rank"])] = float(g["t"])
            if int(g["rank"]) == 0 and g.get("trace"):
                # rank 0's ambient trace id is the fleet correlation id
                _FLEET_TRACE["id"] = g["trace"]
    if len(arrivals) < 2:
        return
    offs = _CLOCK.get("offsets")
    if offs:
        arrivals = {r: t - offs[r] if r < len(offs) else t
                    for r, t in arrivals.items()}
    lo = min(arrivals.values())
    skew = max(arrivals.values()) - lo
    lateness = arrivals.get(me, lo) - lo
    registry.histogram("mx_barrier_skew_seconds",
                       "arrival spread at dist.barrier",
                       buckets=SKEW_BUCKETS).observe(skew)
    with _LOCK:
        # guarded: the crash-fanout flight context snapshots these from
        # another thread (racecheck RC001)
        _BARRIER["count"] += 1
        _BARRIER["lateness_sum"] += lateness
        _BARRIER["lateness_max"] = max(_BARRIER["lateness_max"], lateness)
        _BARRIER["skew_sum"] += skew
        _BARRIER["skew_max"] = max(_BARRIER["skew_max"], skew)
    tracing.annotate(skew_s=round(skew, 6), lateness_s=round(lateness, 6),
                     fleet_trace=_FLEET_TRACE["id"])


def _census_record(op, axis_name, v):
    """Trace-time census for the in-graph wrappers: counts calls and
    payload bytes once per TRACE (tracers expose shape/dtype; host wall
    time in a traced body would be meaningless — `probe_collectives`
    owns honest seconds for these ops)."""
    try:
        labels = {"op": op, "axis": str(axis_name)}
        registry.counter("mx_collective_trace_calls_total",
                         "wrapped collective call sites seen at trace "
                         "time", labels=labels).inc()
        size = getattr(v, "size", None)
        dtype = getattr(v, "dtype", None)
        if size is not None and dtype is not None:
            import numpy as onp

            nbytes = int(size) * onp.dtype(dtype).itemsize
            if nbytes:
                registry.counter(
                    "mx_collective_bytes_total",
                    "payload bytes entering wrapped collectives",
                    labels=labels).inc(nbytes)
    except Exception:   # noqa: FL006 - census in a traced body must never break the trace
        pass


# ---------------------------------------------------------------------------
# eager collective microbench (honest seconds for the in-graph wrappers)
# ---------------------------------------------------------------------------


def _device_key(dev):
    m = re.search(r"v\d+[a-z]*", str(getattr(dev, "device_kind", "")).lower())
    return m.group(0) if m else None


def probe_collectives(mesh=None, axis=None, nbytes=1 << 16, iters=3):
    """Time every `parallel/collectives.py` wrapper in its own jitted
    shard_map program over `mesh` (default: the active mesh, else a
    1-axis mesh over every visible device) and emit
    ``mx_collective_seconds{op=,axis=}`` / ``mx_collective_gbs`` /
    ``mx_collective_peak_frac`` per op. `nbytes` sizes the global
    payload; best-of-`iters` wall time with `block_until_ready`.

    Returns ``{op: {seconds, payload_bytes, link_bytes, gbs, peak_frac}}``
    plus a ``_meta`` row. `link_bytes` models per-device ICI traffic with
    the standard ring-algorithm factors, so `gbs` is comparable to
    `PEAK_LINK_GBS` (no entry for this platform → `peak_frac` None)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax.sharding import PartitionSpec as P

    from ..parallel import collectives
    from .compiles import ledgered_jit

    if mesh is None:
        from ..parallel.mesh import current_mesh

        mesh = current_mesh()
    if mesh is None:
        mesh = jax.sharding.Mesh(onp.array(jax.devices()), ("fleet",))
    axis = axis or mesh.axis_names[0]
    n = int(mesh.shape[axis])
    # per-shard element count, divisible by n (reduce_scatter needs it)
    m = n * max(1, int(nbytes) // 4 // max(n * n, 1))
    s = m * 4                              # per-shard payload bytes
    ax = axis

    ops = {
        "all_reduce": (lambda v: collectives.all_reduce(v, ax),
                       P(ax), P(), (n * m,), 2 * (n - 1) * s),
        "all_gather": (lambda v: collectives.all_gather(v, ax),
                       P(ax), P(), (n * m,), (n - 1) * s),
        "reduce_scatter": (lambda v: collectives.reduce_scatter(v, ax),
                           P(ax), P(ax), (n * m,), (n - 1) * s // n),
        "broadcast": (lambda v: collectives.broadcast(v, ax, 0),
                      P(ax), P(), (n * m,), 2 * (n - 1) * s),
        "ring_permute": (lambda v: collectives.ring_permute(v, ax, 1),
                         P(ax), P(ax), (n * m,), s),
        "all_to_all": (lambda v: collectives.all_to_all(v, ax, 0, 1),
                       P(ax), P(ax), (n * n, m), (n - 1) * s // n),
    }
    dev0 = jax.devices()[0]
    peak = PEAK_LINK_GBS.get(_device_key(dev0) or "")
    out = {"_meta": {"axis": axis, "n": n, "per_shard_bytes": s,
                     "device": str(getattr(dev0, "device_kind", dev0)),
                     "peak_gbs": peak}}
    for op, (fn, in_spec, out_spec, shape, link_bytes) in ops.items():
        x = jnp.zeros(shape, jnp.float32)
        try:
            from jax.experimental.shard_map import shard_map

            jfn = ledgered_jit(
                shard_map(fn, mesh=mesh, in_specs=(in_spec,),
                          out_specs=out_spec, check_rep=False),
                family="fleet.probe_" + op)
            jfn(x).block_until_ready()     # compile outside the timing
            best = float("inf")
            for _ in range(max(1, iters)):
                t0 = time.perf_counter()
                jfn(x).block_until_ready()
                best = min(best, time.perf_counter() - t0)
        except Exception as e:               # pragma: no cover - platform
            out[op] = {"error": f"{type(e).__name__}: {e}"}
            continue
        payload = int(onp.prod(shape)) * 4
        _observe(op, str(axis), payload, best, link_bytes=link_bytes,
                 peak=peak)
        gbs = (link_bytes / best / 1e9) if (link_bytes and best > 0) else None
        out[op] = {"seconds": best, "payload_bytes": payload,
                   "link_bytes": link_bytes,
                   "gbs": round(gbs, 3) if gbs else None,
                   "peak_frac": round(gbs / peak, 4) if (gbs and peak)
                   else None}
    return out


# ---------------------------------------------------------------------------
# chunked snapshot transport + fleet report
# ---------------------------------------------------------------------------


def exchange_large(obj, chunk=None, _exchange=None):
    """`dist.exchange_objs` for objects past the 4 KiB command slot: the
    compressed pickle is split into `chunk`-byte pieces, one metadata
    round ships per-rank piece counts, then max(counts) piece rounds
    reassemble every rank's payload. `_exchange` injects a transport for
    unit tests."""
    from ..parallel import dist

    exchange = _exchange or dist.exchange_objs
    if _exchange is None and (not dist.is_initialized()
                              or dist.num_processes() == 1):
        return [obj]
    chunk = chunk or _env_int("MXNET_FLEET_CHUNK_BYTES", 3000)
    blob = zlib.compress(pickle.dumps(obj), 6)
    pieces = [blob[i:i + chunk] for i in range(0, len(blob), chunk)] or [b""]
    counts = [int(c) for c in exchange(len(pieces))]
    parts = [[] for _ in counts]
    for i in range(max(counts)):
        got = exchange(pieces[i] if i < len(pieces) else b"")
        for r, g in enumerate(got):
            parts[r].append(g if isinstance(g, (bytes, bytearray)) else b"")
    out = []
    for r, p in enumerate(parts):
        try:
            out.append(pickle.loads(zlib.decompress(b"".join(p[:counts[r]]))))
        except Exception:
            out.append(None)
    return out


def straggler_scores(samples):
    """Straggler score per rank: the max SIGNED z-score over the
    per-rank signals (population std) — a slow rank sits ABOVE the mean
    on step time and barrier lateness, so its z is positive and wins the
    argmax. Signals missing on some ranks, present on <2 ranks, or with
    ~zero spread contribute 0.

    `samples`: ``{rank: {signal_name: value-or-None}}`` →
    ``{rank: score}``."""
    scores = {r: 0.0 for r in samples}
    signals = set()
    for s in samples.values():
        signals.update(s)
    for sig in signals:
        vals = {r: float(s[sig]) for r, s in samples.items()
                if isinstance(s.get(sig), (int, float))}
        if len(vals) < 2:
            continue
        mu = sum(vals.values()) / len(vals)
        sd = math.sqrt(sum((v - mu) ** 2 for v in vals.values()) / len(vals))
        if sd <= 1e-12:
            continue
        for r, v in vals.items():
            scores[r] = max(scores[r], (v - mu) / sd)
    return scores


def _hist_mean(report, name):
    cell = report.get(name)
    if isinstance(cell, dict) and cell.get("count"):
        return cell["sum"] / cell["count"]
    return None


def _local_snapshot():
    from ..fault import injection
    from . import goodput

    # close the goodput ledger's open interval so the counters in this
    # registry snapshot are current to the instant of the exchange
    goodput.goodput_frac()
    return {"rank": _rank(), "host": socket.gethostname(),
            "pid": os.getpid(), "wall_time": time.time(),
            "registry": registry.report(),
            "barrier": barrier_stats(),
            "faults": injection.schedule_info(),
            "clock_offset_s": _my_offset()}


def _my_offset():
    offs = _CLOCK.get("offsets") or []
    r = _rank()
    return float(offs[r]) if r < len(offs) else 0.0


def _aggregate_registries(reports):
    """Fleet-aggregate view: counters sum, histograms pool
    count/sum/min/max, gauges keep per-value min/mean/max."""
    agg: dict = {}
    for rep in reports:
        for key, cell in (rep or {}).items():
            if not isinstance(cell, dict):
                continue
            t = cell.get("type")
            a = agg.setdefault(key, {"type": t, "ranks": 0})
            a["ranks"] += 1
            if t == "counter":
                a["value"] = a.get("value", 0) + cell.get("value", 0)
            elif t == "gauge":
                v = cell.get("value")
                if v is None:       # never-set gauge cell
                    continue
                a["min"] = min(a["min"], v) if "min" in a else v
                a["max"] = max(a["max"], v) if "max" in a else v
                a["_sum"] = a.get("_sum", 0.0) + v
                a["_n"] = a.get("_n", 0) + 1
            elif t == "histogram":
                a["count"] = a.get("count", 0) + cell.get("count", 0)
                a["sum"] = a.get("sum", 0.0) + cell.get("sum", 0.0)
                for k, red in (("min", min), ("max", max)):
                    if cell.get(k) is not None:
                        a[k] = (cell[k] if a.get(k) is None
                                else red(a[k], cell[k]))
    for a in agg.values():
        if a["type"] == "gauge" and "_sum" in a:
            a["mean"] = a.pop("_sum") / max(1, a.pop("_n", 1))
        elif a["type"] == "histogram" and a.get("count"):
            a["mean"] = a["sum"] / a["count"]
    return agg


def _goodput_view(ranks):
    """Fleet goodput rollup from each rank's
    ``mx_goodput_seconds_total{state=}`` counters: per-rank state seconds
    + goodput fraction, fleet-summed states, and the rank losing the most
    time to data_wait (a straggling input pipeline's usual signature).
    None when no rank has leased any goodput time yet."""
    per_rank = {}
    fleet: dict = {}
    for r, s in ranks.items():
        states = {}
        for key, cell in (s.get("registry") or {}).items():
            if not key.startswith("mx_goodput_seconds_total{"):
                continue
            m = re.search(r'state="([^"]+)"', key)
            if m and isinstance(cell, dict):
                states[m.group(1)] = float(cell.get("value") or 0.0)
        if not states:
            continue
        wall = sum(states.values())
        per_rank[r] = {
            "states": states, "wall_s": wall,
            "goodput_frac": ((states.get("compute", 0.0) / wall)
                             if wall > 0 else 0.0)}
        for st, v in states.items():
            fleet[st] = fleet.get(st, 0.0) + v
    if not per_rank:
        return None
    tot = sum(fleet.values())
    worst = max(per_rank,
                key=lambda r: per_rank[r]["states"].get("data_wait", 0.0))
    return {"per_rank": per_rank, "fleet_states": fleet,
            "fleet_goodput_frac": ((fleet.get("compute", 0.0) / tot)
                                   if tot > 0 else 0.0),
            "worst_data_wait_rank": int(worst)}


def _capacity_view(ranks):
    """Fleet capacity-ledger rollup from each rank's ``mx_capacity_*``
    series: per-tenant/per-model cost rows summed across ranks (tokens,
    prefill/decode device-seconds, KV page-seconds, queue-wait). None
    when no rank has charged any cost yet."""
    from . import capacity as _capacity

    fleet: dict = {}
    for s in ranks.values():
        view = _capacity.capacity_view(s.get("registry") or {})
        for tenant, per_model in view.items():
            for model, row in per_model.items():
                agg = fleet.setdefault(tenant, {}).setdefault(
                    model, {"tokens": 0, "device_s": {},
                            "kv_page_s": 0.0, "queue_wait_s": 0.0})
                agg["tokens"] += row["tokens"]
                agg["kv_page_s"] += row["kv_page_s"]
                agg["queue_wait_s"] += row["queue_wait_s"]
                for phase, v in row["device_s"].items():
                    agg["device_s"][phase] = \
                        agg["device_s"].get(phase, 0.0) + v
    return fleet or None


def fleet_report():
    """Gather every rank's snapshot (registry report + barrier stats +
    fault schedule) into per-rank and fleet-aggregate views, score the
    straggler, refresh the `mx_fleet_*` gauges, and roll up the per-rank
    goodput ledgers (``report["goodput"]``) and capacity cost ledgers
    (``report["capacity"]``). Collective: every rank must
    call it (each gets the same report). Single-process: a 1-rank report
    over the local registry."""
    global _LAST_REPORT

    snaps = exchange_large(_local_snapshot())
    ranks = {int(s["rank"]): s for s in snaps
             if isinstance(s, dict) and "rank" in s}
    samples = {
        r: {"step_time_mean": _hist_mean(s.get("registry") or {},
                                         "mx_step_time_seconds"),
            "barrier_lateness_mean":
                (s.get("barrier") or {}).get("lateness_mean")}
        for r, s in ranks.items()}
    scores = straggler_scores(samples)
    if scores:
        srank = max(scores, key=lambda r: scores[r])
        sscore = scores[srank]
    else:
        srank, sscore = _rank(), 0.0
    registry.gauge("mx_fleet_straggler_rank",
                   "rank with the worst straggler z-score").set(float(srank))
    registry.gauge("mx_fleet_straggler_score",
                   "straggler z-score of that rank").set(float(sscore))
    registry.gauge("mx_fleet_ranks",
                   "ranks seen by the last fleet_report").set(
                       float(len(ranks)))
    rep = {"n_ranks": len(ranks), "rank": _rank(),
           "wall_time": time.time(),
           "ranks": ranks,
           "aggregate": _aggregate_registries(
               [s.get("registry") for s in ranks.values()]),
           "straggler": {"rank": int(srank), "score": round(sscore, 4),
                         "scores": {int(r): round(v, 4)
                                    for r, v in scores.items()},
                         "signals": samples},
           "goodput": _goodput_view(ranks),
           "capacity": _capacity_view(ranks),
           "clock": {"offsets": _CLOCK.get("offsets"),
                     "bound_s": _CLOCK.get("bound_s")}}
    _LAST_REPORT = rep
    return rep


def last_report():
    return _LAST_REPORT


def install_health_check(threshold=None):
    """Route the straggler score into `monitor.check()`: after that, a
    rank whose score exceeds `threshold` (default
    ``MXNET_FLEET_STRAGGLER_Z``, 2.5) in the LAST `fleet_report()` makes
    `monitor.check()` raise, exactly like a pending NaN finding.
    Idempotent."""
    from . import monitor

    def _fleet_straggler_check():
        rep = _LAST_REPORT
        if not rep:
            return
        thr = (threshold if threshold is not None
               else _env_float("MXNET_FLEET_STRAGGLER_Z", 2.5))
        s = rep["straggler"]
        if s["score"] > thr:
            from ..base import MXNetError

            raise MXNetError(
                f"fleet straggler: rank {s['rank']} z-score "
                f"{s['score']:.2f} exceeds {thr:.2f} "
                f"(signals: {s['signals'].get(s['rank'])})")

    monitor.add_health_check(_fleet_straggler_check, name="fleet_straggler")
    return _fleet_straggler_check


# ---------------------------------------------------------------------------
# clock offsets + trace stitching
# ---------------------------------------------------------------------------


def estimate_clock_offsets(rounds=3):
    """NTP-style offset estimate: after a barrier, every rank brackets
    the same exchange collective with local wall timestamps (t0, t1);
    the collective completes at one global instant, so rank r reads it
    as midpoint (t0_r+t1_r)/2 ± (t1_r−t0_r)/2. offset_r = midpoint_r −
    midpoint_0 (rank 0 is the reference clock); the bound adds rank r's
    and rank 0's half-intervals. Best (smallest-bound) of `rounds`.
    Single-process: zeros."""
    from ..parallel import dist

    if not dist.is_initialized() or dist.num_processes() == 1:
        _CLOCK.update(offsets=[0.0], bound_s=0.0)
        return dict(_CLOCK, rounds=0)
    me = dist.rank()
    nproc = dist.num_processes()
    best = None
    for _ in range(max(1, rounds)):
        dist.barrier(tag="clock_sync")
        t0 = time.time()
        t0s = dist.exchange_objs(("clk0", me, t0))
        t1 = time.time()
        t1s = dist.exchange_objs(("clk1", me, t1))
        try:
            pairs = [(float(t0s[r][2]), float(t1s[r][2]))
                     for r in range(nproc)]
        except (TypeError, IndexError):
            continue
        mid = [(a + b) / 2.0 for a, b in pairs]
        half = [(b - a) / 2.0 for a, b in pairs]
        bound = max(half) + half[0]
        if best is None or bound < best[1]:
            best = ([m - mid[0] for m in mid], bound)
    if best is not None:
        _CLOCK["offsets"], _CLOCK["bound_s"] = best
        registry.gauge("mx_fleet_clock_offset_seconds",
                       "this rank's estimated clock offset vs rank 0"
                       ).set(best[0][me])
    return dict(_CLOCK, rounds=rounds)


def dump_rank_trace(out_dir=None):
    """Write this rank's finished spans (+ clock offset) as
    ``fleet_spans_rank<R>.json`` for `stitch_traces` /
    ``trace_timeline.py --fleet``. Returns the path."""
    out_dir = (out_dir or os.environ.get("MXNET_FLEET_TRACE_DIR")
               or tracing._flight_dir())
    os.makedirs(out_dir, exist_ok=True)
    r = _rank()
    payload = {"rank": r, "n_ranks": _nprocs_hint(),
               "host": socket.gethostname(), "pid": os.getpid(),
               "clock_offset_s": _my_offset(),
               "offset_bound_s": float(_CLOCK.get("bound_s") or 0.0),
               "fleet_trace": _FLEET_TRACE["id"],
               "barrier": barrier_stats(),
               "spans": [s.to_dict() for s in tracing.finished_spans()]}
    path = os.path.join(out_dir, f"fleet_spans_rank{r:03d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.replace(tmp, path)
    return path


def stitch_traces(span_dir):
    """Merge a directory of per-rank `dump_rank_trace` files into one
    Perfetto/chrome trace: one process lane per rank (pid 3000+rank),
    span timestamps rebased by each rank's estimated clock offset so
    matching `coll_seq` barrier spans line up within the offset bound
    (reported under the ``fleet`` key)."""
    files = sorted(_glob.glob(os.path.join(span_dir,
                                           "fleet_spans_rank*.json")))
    if not files:
        raise FileNotFoundError(
            f"no fleet_spans_rank*.json under {span_dir!r} "
            "(run telemetry.fleet.dump_rank_trace on every rank)")
    events = []
    n_ranks, bound, n_spans = 0, 0.0, 0
    for f in files:
        with open(f) as fh:
            payload = json.load(fh)
        rank = int(payload.get("rank", 0))
        n_ranks = max(n_ranks, rank + 1)
        off_us = float(payload.get("clock_offset_s", 0.0)) * 1e6
        bound = max(bound, float(payload.get("offset_bound_s", 0.0)))
        pid = 3000 + rank
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": (
                           f"rank {rank} ({payload.get('host', '?')}"
                           f" pid {payload.get('pid', '?')})")}})
        tids: dict = {}
        for sd in payload.get("spans", []):
            lane = str(sd.get("lane") or sd.get("thread") or "main")
            if lane not in tids:
                tids[lane] = len(tids)
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tids[lane],
                               "args": {"name": lane}})
            args = dict(sd.get("attrs") or {})
            args["rank"] = rank
            args["trace_id"] = sd.get("trace_id")
            events.append({"ph": "X", "name": sd.get("name", "?"),
                           "pid": pid, "tid": tids[lane],
                           "ts": float(sd.get("ts_us", 0)) - off_us,
                           "dur": max(float(sd.get("dur_us") or 0), 1.0),
                           "args": args})
            n_spans += 1
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "fleet": {"n_ranks": n_ranks, "files": len(files),
                      "n_spans": n_spans, "offset_bound_s": bound}}


# ---------------------------------------------------------------------------
# flight-recorder fanout + post-mortem merge
# ---------------------------------------------------------------------------


def _marker_path(rank):
    return os.path.join(tracing._flight_dir(),
                        f"fleet_crash_rank{rank:03d}.marker")


def _flight_context():
    return {"rank": _rank(), "n_ranks": _nprocs_hint(),
            "host": socket.gethostname(),
            "clock_offset_s": _my_offset(),
            "barrier": barrier_stats()}


def _fanout_excepthook(exc_type, exc, tb):
    try:
        if _ENABLED and _nprocs_hint() > 1:
            with open(_marker_path(_rank()), "w") as fh:
                json.dump({"rank": _rank(), "pid": os.getpid(),
                           "error": f"{exc_type.__name__}: {exc}",
                           "wall_time": time.time()}, fh)
    except Exception:   # noqa: FL006 - a crash hook must never mask the original exception
        pass
    prev = _FANOUT["prev_hook"] or sys.__excepthook__
    prev(exc_type, exc, tb)


def _atexit_peer_check():
    """Surviving ranks dump a ``peer_crash`` flightrec when another
    rank's crash marker exists (shared flightrec dir)."""
    if not _ENABLED or _nprocs_hint() <= 1:
        return
    try:
        mine = _marker_path(_rank())
        peers = [m for m in _glob.glob(os.path.join(
            tracing._flight_dir(), "fleet_crash_rank*.marker"))
            if os.path.abspath(m) != os.path.abspath(mine)]
        if peers and not os.path.exists(mine) and tracing.is_enabled():
            tracing.flight_dump("peer_crash")
    except Exception:   # noqa: FL006 - atexit fanout is best-effort on a dying process
        pass


def _sigterm_to_exit(signum, frame):  # noqa: ARG001 — signal handler signature
    sys.exit(128 + signum)


def _arm_flight_fanout():
    if _FANOUT["armed"]:
        return
    _FANOUT["armed"] = True
    _FANOUT["prev_hook"] = sys.excepthook
    sys.excepthook = _fanout_excepthook
    atexit.register(_atexit_peer_check)
    if _nprocs_hint() > 1:
        tracing._RANK_STAMP = _rank_hint()
        try:                       # stale marker from a previous run
            os.remove(_marker_path(_rank_hint()))
        except OSError:
            pass
        # launch.py's fail-fast SIGTERMs the surviving ranks when one
        # crashes; the default handler skips atexit, which would kill
        # the peer_crash dump this fanout exists for. Convert to a
        # clean SystemExit (only where the default action was in place).
        import signal

        try:
            if (threading.current_thread() is threading.main_thread()
                    and signal.getsignal(signal.SIGTERM) == signal.SIG_DFL):
                signal.signal(signal.SIGTERM, _sigterm_to_exit)
        except (ValueError, OSError):   # non-main interpreter contexts
            pass


def merge_flight_dumps(dump_dir):
    """Collect every rank's flightrec (+ crash markers) under `dump_dir`
    into one post-mortem: ``{n_ranks, ranks: {rank: [summaries]},
    markers, dumps}``. Rank comes from the dump's ``context.fleet``
    block, the rank-stamped filename, or (last resort) the pid."""
    merged: dict = {"n_dumps": 0, "ranks": {}, "markers": [], "dumps": []}
    for f in sorted(_glob.glob(os.path.join(dump_dir, "flightrec_*.json"))):
        try:
            with open(f) as fh:
                payload = json.load(fh)
        except (OSError, ValueError):
            continue
        ctx = (payload.get("context") or {}).get("fleet") or {}
        rank = ctx.get("rank")
        if rank is None:
            m = re.search(r"rank(\d+)", os.path.basename(f))
            rank = int(m.group(1)) if m else payload.get("pid", -1)
        merged["ranks"].setdefault(str(int(rank)), []).append(
            {"path": os.path.basename(f),
             "reason": payload.get("reason"),
             "error": payload.get("error"),
             "pid": payload.get("pid"),
             "n_spans": len(payload.get("spans") or []),
             "wall_time_us": payload.get("wall_time_us")})
        merged["dumps"].append(payload)
        merged["n_dumps"] += 1
    for mk in sorted(_glob.glob(os.path.join(dump_dir,
                                             "fleet_crash_rank*.marker"))):
        try:
            with open(mk) as fh:
                merged["markers"].append(json.load(fh))
        except (OSError, ValueError):
            pass
    merged["n_ranks"] = len(merged["ranks"])
    return merged
