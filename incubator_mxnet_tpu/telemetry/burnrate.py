"""Multi-window multi-burn-rate alerting over the SLO trackers.

`slo.py` publishes the instantaneous error-budget burn
(``mx_slo_error_budget_burn{slo=}``); paging on the instantaneous value
is the classic flappy alert. The SRE-workbook answer is **multi-window
multi-burn-rate**: fire only when BOTH a fast window (catches sudden
budget incineration) and a slow window (proves it is not a blip) show
burn above their factor — the default pair is the workbook's page
threshold, 5 minutes @ 14.4× AND 1 hour @ 6× — and clear with
**hysteresis**: the alert must observe ``clear_holds`` consecutive
evaluations with every window below ``clear_ratio ×`` its factor
before it stops firing, so a trace hovering at the threshold never
flaps.

Windowed burn comes from the `timeseries` history layer
(``avg_over_time`` of the burn gauge), so both that layer and the SLO
evaluation loop must be live for alerts to see data; no data keeps an
alert in its current state (an observatory outage is not a page, and
not an all-clear either).

Firing state surfaces three ways: ``mx_alert_firing{alert=}`` gauges,
``burnrate.fire`` / ``burnrate.clear`` span events on every transition,
and a flight-recorder block (`tracing.register_flight_context`) so a
crash dump names what was firing.

Knob: ``MXNET_BURN_WINDOWS`` — ``"<window_s>@<factor>,..."`` (e.g.
``"300@14.4,3600@6"``) overrides the default pair for `add` /
`arm_default` callers that don't pass ``windows=``.
"""
from __future__ import annotations

import os

from . import registry, timeseries, tracing
from .locks import tracked_lock

__all__ = ["BurnRateAlert", "add", "remove", "alerts", "firing",
           "evaluate_all", "arm_default", "clear", "parse_windows",
           "DEFAULT_WINDOWS"]

# (window_s, burn factor): fast 5m @ 14.4x AND slow 1h @ 6x — the SRE
# workbook's page-severity pair (14.4x burns a 30d budget in 2 days)
DEFAULT_WINDOWS = ((300.0, 14.4), (3600.0, 6.0))

_LOCK = tracked_lock("telemetry.burnrate", kind="lock")
_ALERTS: dict = {}            # name -> BurnRateAlert
_FLIGHT_ARMED = False


def parse_windows(spec):
    """Parse ``"300@14.4,3600@6"`` into ((300.0, 14.4), (3600.0, 6.0)).
    None/empty → the default pair; a malformed spec raises ValueError
    (a silently-ignored alert config is worse than a loud one)."""
    if not spec:
        return DEFAULT_WINDOWS
    out = []
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            w, _, f = part.partition("@")
            out.append((float(w), float(f)))
        except ValueError:
            raise ValueError(
                f"MXNET_BURN_WINDOWS: bad entry {part!r} "
                "(want <window_s>@<factor>, e.g. 300@14.4)") from None
    if not out:
        return DEFAULT_WINDOWS
    return tuple(out)


class BurnRateAlert:
    """One multi-window burn alert bound to one SLO's burn series.

    ``windows`` is ((window_s, factor), ...): each pair is an
    INDEPENDENT condition (the SRE fast/slow split — the short window
    catches a flash burst long before the slow average moves; the long
    window catches a slow leak the short one averages away). The alert
    FIRES when ANY window's average burn reaches its factor, and
    CLEARS only after ``clear_holds`` consecutive evaluations with
    EVERY known window below ``clear_ratio × factor`` (hysteresis — no
    flapping at the boundary). Windows with no history yet are skipped
    for firing; with NO window known at all the state freezes (an
    observatory outage must never clear an alert)."""

    __slots__ = ("name", "slo", "windows", "clear_ratio", "clear_holds",
                 "firing", "_below", "last_burns", "fired_at",
                 "transitions")

    def __init__(self, name, slo, windows=None, clear_ratio=0.9,
                 clear_holds=2):
        self.name = str(name)
        self.slo = str(slo)
        if windows is None:
            windows = parse_windows(os.environ.get("MXNET_BURN_WINDOWS"))
        self.windows = tuple((float(w), float(f)) for w, f in windows)
        if not self.windows:
            raise ValueError(f"alert {name!r}: no windows")
        self.clear_ratio = float(clear_ratio)
        self.clear_holds = int(clear_holds)
        self.firing = False
        self._below = 0           # consecutive all-below evaluations
        self.last_burns = {}      # window_s -> last windowed burn
        self.fired_at = None
        self.transitions = 0

    @property
    def series(self):
        return f'mx_slo_error_budget_burn{{slo="{self.slo}"}}'

    def _gauge(self):
        return registry.gauge(
            "mx_alert_firing",
            "1 while a multi-window burn-rate alert fires",
            labels={"alert": self.name})

    def evaluate(self, now=None):
        """One evaluation against the timeseries layer; returns the
        state dict (also what the flight recorder snapshots)."""
        burns = {}
        for w, _f in self.windows:
            burns[w] = timeseries.avg_over_time(self.series, w, now=now)
        self.last_burns = burns
        known = [(w, f, burns[w]) for w, f in self.windows
                 if burns[w] is not None]
        if known:
            exceeded = any(b >= f for _w, f, b in known)
            below = all(b < self.clear_ratio * f for _w, f, b in known)
            if not self.firing:
                if exceeded:
                    self.firing = True
                    self.fired_at = now
                    self.transitions += 1
                    self._below = 0
                    tracing.event("burnrate.fire", alert=self.name,
                                  slo=self.slo,
                                  burns={str(int(w)): round(b, 3)
                                         for w, _f, b in known})
            else:
                if below:
                    self._below += 1
                    if self._below >= self.clear_holds:
                        self.firing = False
                        self.transitions += 1
                        self._below = 0
                        tracing.event("burnrate.clear", alert=self.name,
                                      slo=self.slo)
                else:
                    self._below = 0
        self._gauge().set(1 if self.firing else 0)
        return self.state()

    def state(self):
        return {"alert": self.name, "slo": self.slo,
                "firing": self.firing,
                "windows": [{"window_s": w, "factor": f,
                             "burn": self.last_burns.get(w)}
                            for w, f in self.windows],
                "transitions": self.transitions}


def _arm_flight_context():
    global _FLIGHT_ARMED
    if _FLIGHT_ARMED:
        return
    _FLIGHT_ARMED = True

    def _flight():
        with _LOCK:
            alist = list(_ALERTS.values())
        return {"alerts": [a.state() for a in alist]} if alist else None
    tracing.register_flight_context("burnrate", _flight)


def add(name, slo, windows=None, clear_ratio=0.9, clear_holds=2):
    """Register one alert over `slo`'s burn series. Loud on a duplicate
    name."""
    a = BurnRateAlert(name, slo, windows=windows, clear_ratio=clear_ratio,
                      clear_holds=clear_holds)
    with _LOCK:
        if a.name in _ALERTS:
            raise ValueError(f"burn alert {a.name!r} already registered")
        _ALERTS[a.name] = a
    _arm_flight_context()
    return a


def remove(name):
    with _LOCK:
        _ALERTS.pop(name, None)


def alerts():
    with _LOCK:
        return list(_ALERTS.values())


def firing():
    """Names of currently-firing alerts (what the advisor reads)."""
    with _LOCK:
        return sorted(a.name for a in _ALERTS.values() if a.firing)


def evaluate_all(now=None):
    """Evaluate every registered alert; returns {name: state dict}."""
    return {a.name: a.evaluate(now=now) for a in alerts()}


def arm_default(windows=None, clear_ratio=0.9, clear_holds=2):
    """One burn alert per SLO already registered with the default
    `slo.tracker()` (named ``burn_<slo>``; existing alert names are
    kept). Returns the list of alerts added."""
    from . import slo as slo_mod

    added = []
    for s in slo_mod.tracker().slos():
        name = f"burn_{s.name}"
        with _LOCK:
            exists = name in _ALERTS
        if not exists:
            added.append(add(name, s.name, windows=windows,
                             clear_ratio=clear_ratio,
                             clear_holds=clear_holds))
    return added


def clear():
    """Drop every alert and zero the firing gauges (tests)."""
    with _LOCK:
        alist = list(_ALERTS.values())
        _ALERTS.clear()
    for a in alist:
        a._gauge().set(0)
