"""Instrumented-lock registry: the racecheck *runtime tier*.

The static tier (`analysis/racecheck.py`) proves properties about lock
use it can see in the source; this module witnesses the orders that
actually happen. `tracked_lock(name)` hands out locks that record, per
thread, the stack of locks currently held — every acquisition of B while
holding A adds the edge A→B to a process-wide lock-order graph, and a
cycle in that graph is a **witnessed order inversion** (rule RC005):
two threads that have each taken the same pair of locks in opposite
orders are one unlucky preemption away from deadlock, even if this run
never hung (the classic witness/Goodlock observation — the *order* is
the defect, not the hang).

Contention telemetry rides the same hooks:

- ``mx_lock_wait_seconds{lock=}``  — time blocked in acquire
- ``mx_lock_held_seconds{lock=}``  — critical-section length
- ``mx_lock_order_inversions_total{pair=}`` — RC005 witnesses
- a one-shot warning when a lock is held longer than
  ``MXNET_RACECHECK_HOLD_S`` (default 1.0s)

Off-path contract (the usual telemetry dead-branch discipline, pushed
one step further): a Python-level per-acquire enabled check would cost
more than the raw ``lock.acquire()`` it guards, so the dead branch lives
in the **factory** — with telemetry off, ``tracked_lock(name)`` returns
the raw ``threading`` primitive itself (the name is still reserved in
the registry). Off-path overhead is therefore zero by construction; the
committed gate in tests/test_racecheck.py measures it anyway (<3%).
Locks created while disarmed stay raw — arm via ``MXNET_TELEMETRY=1``
(read at import, like the rest of the telemetry plane) or call
`enable()` before constructing the engines you want witnessed.

This module is the one place in telemetry/ allowed to construct raw
``threading`` locks (FL018 exempts it): the tracked locks' own registry
cannot be built out of tracked locks.
"""
from __future__ import annotations

import logging
import os
import threading
import time
import traceback

__all__ = ["tracked_lock", "enable", "disable", "is_enabled",
           "order_graph", "inversions", "contention_table",
           "known_locks", "reset", "TrackedLock", "TrackedCondition"]

log = logging.getLogger("incubator_mxnet_tpu.telemetry.locks")

_ENABLED = False

# -- global witness state (guarded by _G, itself a raw lock) ---------------
_G = threading.Lock()
_NAMES: dict = {}          # name -> count handed out (for #2 suffixing)
_EDGES: dict = {}          # (a, b) -> {"stack": [...], "thread": str,
                           #            "line": "file:ln in fn", "count": n}
_INVERSIONS: list = []     # RC005 records (dicts; see _check_cycle)
_SEEN_CYCLES: set = set()  # frozenset(edge names) dedup
_WARNED_HOLDS: set = set()

# per-thread stack of currently-held tracked locks (acquisition order)
_TLS = threading.local()

# lazily-created metric handles (None until first enabled acquisition —
# keeps import light and avoids registry work when disarmed)
_METRICS = None


def _hold_warn_s():
    try:
        return float(os.environ.get("MXNET_RACECHECK_HOLD_S", "1.0"))
    except ValueError:
        return 1.0


def _held():
    h = getattr(_TLS, "held", None)
    if h is None:
        h = []
        _TLS.held = h
    return h


def _metrics_for(name):
    global _METRICS
    if _METRICS is None:
        _METRICS = {}
    h = _METRICS.get(name)
    if h is None:
        from . import registry

        # sub-ms-biased buckets: lock waits live in the µs..ms range
        buckets = (1e-6, 1e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2,
                   0.1, 0.5, 1.0, 5.0)
        h = (registry.histogram("mx_lock_wait_seconds",
                                "time blocked acquiring a tracked lock",
                                labels={"lock": name}, buckets=buckets),
             registry.histogram("mx_lock_held_seconds",
                                "tracked-lock critical-section length",
                                labels={"lock": name}, buckets=buckets))
        _METRICS[name] = h
    return h


def _site():
    """One-line acquisition site (skip this module's own frames)."""
    for f in reversed(traceback.extract_stack(limit=12)):
        if not f.filename.endswith("locks.py"):
            return f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
    return "?"


def _stack_summary():
    return [f"{os.path.basename(f.filename)}:{f.lineno} in {f.name}"
            for f in traceback.extract_stack(limit=12)
            if not f.filename.endswith("locks.py")][-6:]


def _find_path(src, dst):
    """Edge-name path src→…→dst over _EDGES (caller holds _G)."""
    stack = [(src, (src,))]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for (a, b) in _EDGES:
            if a != node or b in seen:
                continue
            if b == dst:
                return path + (b,)
            seen.add(b)
            stack.append((b, path + (b,)))
    return None


def _note_edges(new_lock):
    """Record held→new edges; a path new→…→held closes a cycle = RC005."""
    held = _held()
    if not held:
        return
    nb = new_lock._tl_name
    tname = threading.current_thread().name
    for h in held:
        na = h._tl_name
        if na == nb:
            continue
        with _G:
            rec = _EDGES.get((na, nb))
            if rec is not None:
                rec["count"] += 1
                continue
            # new edge: remember its first witness, then look for the
            # reverse path that makes (na, nb) an inversion
            _EDGES[(na, nb)] = {"stack": _stack_summary(),
                                "thread": tname, "line": _site(),
                                "count": 1}
            back = _find_path(nb, na)
            if back is None:
                continue
            cycle = frozenset(zip(back, back[1:])) | {(na, nb)}
            if cycle in _SEEN_CYCLES:
                continue
            _SEEN_CYCLES.add(cycle)
            fwd = _EDGES[(na, nb)]
            rev = _EDGES.get((back[0], back[1]))
            inv = {
                "rule": "RC005",
                "pair": f"{na}<->{nb}",
                "cycle": list(back) + [nb],
                "witness_fwd": {"order": f"{na} -> {nb}",
                                "thread": fwd["thread"],
                                "line": fwd["line"],
                                "stack": fwd["stack"]},
                "witness_rev": {"order": " -> ".join(back),
                                "thread": rev["thread"] if rev else "?",
                                "line": rev["line"] if rev else "?",
                                "stack": rev["stack"] if rev else []},
            }
            _INVERSIONS.append(inv)
        # warn + count outside _G (registry takes its own lock)
        log.warning(
            "RC005 lock-order inversion witnessed: %s taken after %s "
            "(%s, thread %s) but the reverse order %s was seen earlier "
            "(%s) — deadlock possible under preemption",
            nb, na, inv["witness_fwd"]["line"], tname,
            inv["witness_rev"]["order"], inv["witness_rev"]["line"])
        from . import registry

        registry.counter("mx_lock_order_inversions_total",
                         "witnessed lock-order inversions (RC005)",
                         labels={"pair": inv["pair"]}).inc()


class TrackedLock:
    """Instrumented Lock/RLock: order witness + contention telemetry.

    Only handed out while the registry is enabled; the disarmed factory
    returns raw primitives instead (see module docstring).
    """

    _tl_kind = "lock"

    def __init__(self, name, reentrant=False):
        self._tl_name = name
        self._tl_reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._t_acquired = 0.0

    # -- core protocol ----------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        held = _held()
        reentry = self._tl_reentrant and self in held
        t0 = time.perf_counter()
        got = self._inner.acquire(blocking, timeout)
        if not got:
            return False
        waited = time.perf_counter() - t0
        if not reentry:
            _note_edges(self)
            self._t_acquired = time.perf_counter()
            wait_h, _ = _metrics_for(self._tl_name)
            wait_h.observe(waited)
        held.append(self)
        return True

    def release(self):
        held = _held()
        try:
            # pop the most recent occurrence (reentrant releases unwind)
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
            if self not in held:             # outermost release
                dt = time.perf_counter() - self._t_acquired
                _, held_h = _metrics_for(self._tl_name)
                held_h.observe(dt)
                warn_s = _hold_warn_s()
                if dt > warn_s and self._tl_name not in _WARNED_HOLDS:
                    _WARNED_HOLDS.add(self._tl_name)
                    log.warning(
                        "tracked lock %r held %.3fs (> %.1fs) at %s — "
                        "long critical section blocks every peer thread",
                        self._tl_name, dt, warn_s, _site())
        finally:
            self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        if self._tl_reentrant:
            raise AttributeError("RLock has no locked()")
        return self._inner.locked()

    def __repr__(self):
        kind = "rlock" if self._tl_reentrant else "lock"
        return f"<TrackedLock {self._tl_name!r} ({kind})>"


class TrackedCondition:
    """Instrumented Condition over a TrackedLock. ``wait()`` releases the
    lock, so the held stack drops it for the duration and the reacquire
    re-witnesses order edges."""

    _tl_kind = "condition"

    def __init__(self, name):
        self._tl_lock = TrackedLock(name, reentrant=True)
        self._inner = threading.Condition(self._tl_lock._inner)

    @property
    def _tl_name(self):
        return self._tl_lock._tl_name

    def acquire(self, *a, **kw):
        return self._tl_lock.acquire(*a, **kw)

    def release(self):
        self._tl_lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout=None):
        held = _held()
        if self._tl_lock in held:
            held.remove(self._tl_lock)
        try:
            return self._inner.wait(timeout)
        finally:
            _note_edges(self._tl_lock)
            held.append(self._tl_lock)

    def wait_for(self, predicate, timeout=None):
        end = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None if end is None else end - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n=1):
        self._inner.notify(n)

    def notify_all(self):
        self._inner.notify_all()


def tracked_lock(name, kind="rlock"):
    """Return a named lock for cross-thread control-plane state.

    ``kind``: ``"lock"`` | ``"rlock"`` | ``"condition"``. While the
    registry is disarmed this returns the raw ``threading`` primitive
    (zero off-path cost — the dead branch is the factory itself); armed,
    it returns the instrumented wrapper feeding the order witness and
    the ``mx_lock_*`` contention series.
    """
    with _G:
        n = _NAMES.get(name, 0)
        _NAMES[name] = n + 1
    if n:
        name = f"{name}#{n + 1}"
    if not _ENABLED:
        if kind == "lock":
            return threading.Lock()
        if kind == "rlock":
            return threading.RLock()
        if kind == "condition":
            return threading.Condition()
        raise ValueError(f"tracked_lock kind {kind!r} "
                         "(expected lock|rlock|condition)")
    if kind == "lock":
        return TrackedLock(name)
    if kind == "rlock":
        return TrackedLock(name, reentrant=True)
    if kind == "condition":
        return TrackedCondition(name)
    raise ValueError(f"tracked_lock kind {kind!r} "
                     "(expected lock|rlock|condition)")


# -- lifecycle --------------------------------------------------------------

def enable():
    """Arm the witness: locks created *from now on* are instrumented."""
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled():
    return _ENABLED


def reset():
    """Drop all witnessed state (tests). Existing locks keep recording."""
    with _G:
        _EDGES.clear()
        _INVERSIONS.clear()
        _SEEN_CYCLES.clear()
        _WARNED_HOLDS.clear()
        _NAMES.clear()


# -- reading ----------------------------------------------------------------

def order_graph():
    """{(a, b): first-witness record} — the runtime lock-order edges."""
    with _G:
        return {k: dict(v) for k, v in _EDGES.items()}


def inversions():
    """List of RC005 witness records (see `_note_edges`)."""
    with _G:
        return [dict(i) for i in _INVERSIONS]


def known_locks():
    with _G:
        return sorted(_NAMES)


def contention_table():
    """Per-lock contention rows from the ``mx_lock_*`` histograms:
    {lock: {acquisitions, wait_sum_s, wait_max_s, held_sum_s,
    held_max_s}} — the `tools/racecheck.py --live` table."""
    if not _METRICS:
        return {}
    rows = {}
    for name, (wait_h, held_h) in sorted(_METRICS.items()):
        w, h = wait_h.snapshot(), held_h.snapshot()
        rows[name] = {
            "acquisitions": w["count"],
            "wait_sum_s": w["sum"], "wait_max_s": w["max"] or 0.0,
            "held_sum_s": h["sum"], "held_max_s": h["max"] or 0.0,
        }
    return rows


# self-arm with the rest of the telemetry plane: this module is imported
# (via the telemetry package) before any engine constructs its locks, so
# reading the knob here means MXNET_TELEMETRY=1 witnesses everything
if os.environ.get("MXNET_TELEMETRY", "0") not in ("0", ""):
    _ENABLED = True
