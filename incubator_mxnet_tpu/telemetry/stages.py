"""Funnel stage-tracing: where do the eager-dispatch microseconds go?

VERDICT r5 Weak #3: the eager funnel costs 1.18x raw jax per op and there
was "no committed breakdown of where the remaining Python-side
microseconds go". This module owns that breakdown.

`apply_op` / `apply_op_flat` (`ndarray/ndarray.py`) carry per-stage
`perf_counter_ns` probes behind a single module-global hook
(`ndarray._STAGE_HOOK`). The contract with the hot path:

- **off** (`_STAGE_HOOK is None`, the default): each probe site is one
  global load + `is not None` compare — no call, no allocation, no
  import. This is the "compiles to a no-op" form of the MXNET_TELEMETRY
  knob: the timed branches are dead.
- **on** (`enable()`): the hook is ``_record(stage, t_start_ns) -> now_ns``
  — it accumulates `now - t_start` into a per-stage (count, total_ns)
  cell and returns `now`, so consecutive stages chain off one clock read.

Stages (in funnel order):

=============  ==========================================================
``prologue``   arg scan: tensor/static split, parent + value collection
``amp_lookup`` AMP participation lookup for the op name
``cache_key``  op-call jit cache key build (`apply_op_flat` only)
``dispatch``   the jax call itself (jit-cache hit or eager trace+dispatch)
``wrap``       NDArray wrapping of outputs
``tape``       autograd tape-node attach (only when recording)
=============  ==========================================================

`stage_report()` merges the counters into per-stage µs; the committed
artifact lives at `benchmark/funnel_breakdown.md` (regenerate with
`python tools/funnel_profile.py`).
"""
from __future__ import annotations

import threading
import time

from collections import defaultdict

from .locks import tracked_lock

__all__ = ["enable", "disable", "is_enabled", "stage_report", "reset",
           "STAGE_ORDER"]

STAGE_ORDER = ("prologue", "amp_lookup", "cache_key", "dispatch", "wrap",
               "tape")

_LOCK = tracked_lock("telemetry.stages", kind="lock")
_STATS = defaultdict(lambda: [0, 0])     # stage -> [count, total_ns]
_ENABLED = False


def _record(stage, t0_ns):
    """The installed hook: accumulate one stage interval, return 'now' so
    the caller can chain the next stage off a single clock read."""
    now = time.perf_counter_ns()
    cell = _STATS[stage]
    cell[0] += 1
    cell[1] += now - t0_ns
    return now


def enable():
    """Install the stage hook into the op funnel (idempotent)."""
    global _ENABLED
    from ..ndarray import ndarray as nd_mod

    with _LOCK:
        nd_mod._STAGE_HOOK = _record
        _ENABLED = True


def disable():
    """Remove the hook — the funnel probes go back to dead branches."""
    global _ENABLED
    from ..ndarray import ndarray as nd_mod

    with _LOCK:
        nd_mod._STAGE_HOOK = None
        _ENABLED = False


def is_enabled():
    return _ENABLED


def reset():
    with _LOCK:
        _STATS.clear()


def stage_report():
    """Per-stage accounting: {stage: {count, total_us, mean_us}} plus a
    ``total`` row summing every stage (the funnel's Python-side tax per
    op is total.mean_us over the ops measured)."""
    with _LOCK:
        snap = {k: (v[0], v[1]) for k, v in _STATS.items()}
    out = {}
    grand_ns, grand_calls = 0, 0
    for stage in STAGE_ORDER:
        if stage not in snap:
            continue
        count, total_ns = snap[stage]
        out[stage] = {"count": count, "total_us": total_ns / 1e3,
                      "mean_us": (total_ns / count / 1e3) if count else 0.0}
        grand_ns += total_ns
        grand_calls = max(grand_calls, count)
    out["total"] = {"count": grand_calls, "total_us": grand_ns / 1e3,
                    "mean_us": (grand_ns / grand_calls / 1e3)
                    if grand_calls else 0.0}
    return out


def format_report(report=None):
    """Markdown table of `stage_report()` (what funnel_profile commits)."""
    report = report or stage_report()
    lines = ["| stage | calls | total µs | µs/op |",
             "|---|---:|---:|---:|"]
    for stage in (*STAGE_ORDER, "total"):
        if stage not in report:
            continue
        r = report[stage]
        bold = "**" if stage == "total" else ""
        lines.append(f"| {bold}{stage}{bold} | {r['count']} | "
                     f"{r['total_us']:.1f} | {r['mean_us']:.3f} |")
    return "\n".join(lines)
