"""Training goodput ledger: every wall second attributed to one state.

Per the goodput methodology of large-fleet training systems (PAPERS.md:
MegaScale-style production observability; Pathways-style multi-controller
accounting), the metric that matters at pod scale is the fraction of
wall-clock seconds spent in productive compute — attributed *by cause*
when it isn't. This module is that ledger: a stack of `lease(state)`
context managers rides the seams the stack already has (estimator step
spans, dataloader batch waits, the checkpoint write/resume seams, the
`ElasticController` transition phases) and attributes every interval of
wall time to exactly one of:

``compute``     inside the estimator's fit_batch/trainer.step body
``data_wait``   blocked on the dataloader for the next batch
``checkpoint``  writing a checkpoint (periodic, drain, or departure)
``reshard``     rebuilding trainer/sampler onto a new topology
``drain``       waiting at the rendezvous for the fleet to quiesce
``recovery``    resuming state after a crash or a topology change
``idle``        none of the above (the honest remainder)

Leases nest innermost-wins: the `checkpoint` lease inside an elastic
transition takes its own interval and hands the surrounding time back to
the transition's `reshard`/`drain` lease. Because ``idle`` is itself a
state, the states always sum to measured wall time — `report()` exposes
``accounted_frac`` (non-idle fraction) so "the ledger accounts for X% of
the run" is a real claim, not an artifact of the bookkeeping.

Off by default (`_ENABLED` dead branch — `lease()` returns a shared null
context manager). Armed by `MXNET_TELEMETRY=1` with the rest of the
telemetry plane. Exported as ``mx_goodput_seconds_total{state=}``
counters + a ``mx_goodput_frac`` pull gauge, so `fleet.fleet_report()`
aggregates the per-rank ledgers for free; a dedicated goodput section in
that report names the rank with the worst data_wait. A flight-context
block carries the last snapshot into every flight record (elastic
transitions dump one).
"""
from __future__ import annotations

import threading
import time

from .locks import tracked_lock

from . import registry, tracing

__all__ = ["STATES", "lease", "report", "goodput_frac", "format_waterfall",
           "enable", "disable", "is_enabled", "reset"]

# exactly-one-of states; idle is the honest remainder, not a leak bucket
STATES = ("compute", "data_wait", "checkpoint", "reshard", "drain",
          "recovery", "idle")

_ENABLED = False
_LOCK = tracked_lock("telemetry.goodput", kind="lock")
_SECONDS: dict = {}          # state -> attributed seconds
_STACK: list = []            # active lease states, innermost last
_T_BEGIN = None              # perf_counter at first lease (ledger epoch)
_MARK = None                 # perf_counter of the last attribution boundary
_COUNTERS: dict = {}         # state -> registry Counter (cached)


def _counter(state):
    c = _COUNTERS.get(state)
    if c is None:
        c = registry.counter(
            "mx_goodput_seconds_total",
            "wall seconds attributed to a goodput state",
            labels={"state": state})
        _COUNTERS[state] = c
    return c


def _attribute(now):
    """Close the open interval [_MARK, now) into the current top state
    (idle when no lease is active). Caller holds _LOCK."""
    global _MARK
    if _MARK is None:
        _MARK = now
        return
    dt = now - _MARK
    _MARK = now
    if dt <= 0.0:
        return
    state = _STACK[-1] if _STACK else "idle"
    _SECONDS[state] = _SECONDS.get(state, 0.0) + dt
    _counter(state).inc(dt)


class _NullLease:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_LEASE = _NullLease()


class _Lease:
    __slots__ = ("state",)

    def __init__(self, state):
        self.state = state

    def __enter__(self):
        global _T_BEGIN, _MARK
        now = time.perf_counter()
        with _LOCK:
            if _T_BEGIN is None:
                _T_BEGIN = now       # ledger epoch: first lease arms it
                _MARK = now
            _attribute(now)
            _STACK.append(self.state)
        return self

    def __exit__(self, *exc):
        now = time.perf_counter()
        with _LOCK:
            _attribute(now)
            if _STACK and _STACK[-1] == self.state:
                _STACK.pop()
            elif self.state in _STACK:   # tolerate out-of-order exits
                _STACK.remove(self.state)
        return False


def lease(state):
    """Context manager attributing the enclosed wall time to ``state``
    (one of `STATES`). Nesting wins innermost: a ``checkpoint`` lease
    inside a ``reshard`` transition takes its own interval and hands the
    surrounding time back to reshard. Returns a shared null context when
    the ledger is off — the instrumented seams stay dead branches."""
    if not _ENABLED:
        return _NULL_LEASE
    if state not in STATES:
        raise ValueError(f"unknown goodput state {state!r}; "
                         f"one of {STATES}")
    return _Lease(state)


def report():
    """Snapshot: per-state seconds, wall seconds since the first lease,
    non-idle ``accounted_s``/``accounted_frac``, and ``goodput_frac``
    (compute / wall). Reading the report closes the open interval, so
    the states sum to wall time exactly at every snapshot."""
    now = time.perf_counter()
    with _LOCK:
        if _T_BEGIN is not None:
            _attribute(now)
        secs = {s: _SECONDS.get(s, 0.0) for s in STATES}
        wall = (now - _T_BEGIN) if _T_BEGIN is not None else 0.0
        active = _STACK[-1] if _STACK else None
    accounted = sum(v for s, v in secs.items() if s != "idle")
    return {"enabled": _ENABLED, "wall_s": wall, "states": secs,
            "accounted_s": accounted,
            "accounted_frac": (accounted / wall) if wall > 0 else 0.0,
            "goodput_frac": (secs["compute"] / wall) if wall > 0 else 0.0,
            "active_lease": active}


def goodput_frac():
    """compute seconds / wall seconds, or None before the first lease
    (the `mx_goodput_frac` pull-gauge probe)."""
    with _LOCK:
        if _T_BEGIN is None:
            return None
        _attribute(time.perf_counter())
        wall = _MARK - _T_BEGIN
        compute = _SECONDS.get("compute", 0.0)
    return (compute / wall) if wall > 0 else 0.0


def format_waterfall(rep=None, width=40):
    """Text waterfall of a `report()` snapshot — one bar per state,
    widths proportional to wall share (kernelscope's rendering)."""
    rep = rep or report()
    wall = rep["wall_s"]
    lines = [f"goodput waterfall — wall {wall:.3f}s, "
             f"goodput {rep['goodput_frac'] * 100:.1f}%, "
             f"accounted {rep['accounted_frac'] * 100:.1f}%"]
    for state in STATES:
        s = rep["states"].get(state, 0.0)
        frac = (s / wall) if wall > 0 else 0.0
        bar = "#" * max(0, round(frac * width))
        lines.append(f"  {state:<10} {s:>9.3f}s {frac * 100:>6.1f}% {bar}")
    return "\n".join(lines)


def _flight_probe():
    with _LOCK:
        if _T_BEGIN is None:
            return None
    return report()


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled():
    return _ENABLED


def reset():
    """Forget all attribution and the ledger epoch (tests). Open leases
    held across a reset are dropped; their exits are tolerated."""
    global _T_BEGIN, _MARK
    with _LOCK:
        _SECONDS.clear()
        del _STACK[:]
        _T_BEGIN = None
        _MARK = None


registry.register_pull_gauge(
    "mx_goodput_frac", goodput_frac,
    "fraction of wall seconds attributed to productive compute")
tracing.register_flight_context("goodput", _flight_probe)
