"""Runtime telemetry: structured metrics, funnel stage-tracing, roofline
analysis, and a training-health monitor (see TELEMETRY.md).

Four connected parts:

- `registry`  — process-wide counters/gauges/histograms (lock-free
  thread-shard fast path), `report()`/`dump()`/`exposition()`, built-in
  step/compile/jit-cache/transfer series;
- `stages`    — per-stage µs accounting inside the `apply_op` funnel
  behind the MXNET_TELEMETRY knob (dead branches when off);
- `roofline`  — post-process the profiler's XPlane device trace into
  per-phase bytes vs time vs peak-HBM-bandwidth tables;
- `monitor`   — reference-parity `Monitor` (per-tensor health stats,
  batched host sync), `install_nan_hook()` non-finite guard (eager +
  compiled via jax.debug.callback), per-rank aggregation at kvstore sync
  points, and the estimator `TelemetryHandler`.

Env knobs (registered in `util._ENV_KNOBS`): ``MXNET_TELEMETRY``
(``1`` = stage tracing on, ``raise`` = + NaN guard raising at the first
non-finite output, ``0``/unset = off — zero per-op cost),
``MXNET_TELEMETRY_INTERVAL`` (batches between estimator registry logs).
"""
from __future__ import annotations

from . import registry  # noqa: F401
from . import roofline  # noqa: F401
from . import stages  # noqa: F401
from . import monitor  # noqa: F401
from .monitor import Monitor, install_nan_hook  # noqa: F401

# arm the host->device byte inlet (a counter inc per transfer — rare
# events, so always on once telemetry is imported)
from ..ndarray import ndarray as _nd_mod

_nd_mod._H2D_HOOK = registry.add_h2d_bytes

__all__ = ["registry", "stages", "roofline", "monitor", "Monitor",
           "install_nan_hook"]
