"""Runtime telemetry: structured metrics, funnel stage-tracing, span
tracing + flight recorder, SLO tracking, roofline analysis, and a
training-health monitor (see TELEMETRY.md).

Six connected parts:

- `registry`  — process-wide counters/gauges/histograms (lock-free
  thread-shard fast path), `report()`/`dump()`/`exposition()`, built-in
  step/compile/jit-cache/transfer series; ``MXNET_TELEMETRY_DUMP``
  periodic Prometheus-textfile snapshots;
- `stages`    — per-stage µs accounting inside the `apply_op` funnel
  behind the MXNET_TELEMETRY knob (dead branches when off);
- `tracing`   — Dapper-style span tracer (trace/correlation IDs, ambient
  context, per-thread rings) threaded through serve requests, estimator
  steps, dataloader fetches, kvstore syncs, and checkpoint I/O; flight
  recorder dumping the last spans on crash/injected fault; Chrome-trace
  export sharing the profiler's clock base (same off-path dead-branch
  discipline as `stages`);
- `slo`       — declarative objectives over registry series with
  error-budget burn as ``mx_slo_*`` gauges and a loud `monitor.check()`
  hook;
- `roofline`  — post-process the profiler's XPlane device trace into
  per-phase bytes vs time vs peak-HBM-bandwidth tables;
- `monitor`   — reference-parity `Monitor` (per-tensor health stats,
  batched host sync), `install_nan_hook()` non-finite guard (eager +
  compiled via jax.debug.callback), per-rank aggregation at kvstore sync
  points, pluggable health checks, and the estimator `TelemetryHandler`;
- `compiles`  — per-program XLA compile ledger (cost/memory analysis,
  HLO fingerprints) with recompile forensics naming the offending
  argument (``mx_jit_recompiles_total{program=,cause=}``);
- `hbm`       — subsystem-attributed live-buffer census over
  ``jax.live_arrays()``, growth watchdog (``MXNET_MEMWATCH_INTERVAL``),
  and the RESOURCE_EXHAUSTED post-mortem (``MXNET_OOM_POSTMORTEM``);
- `fleet`     — the cross-rank plane: collective profiler over
  `parallel/dist.py` + `parallel/collectives.py` (``mx_collective_*``,
  barrier-arrival skew), `fleet_report()` per-rank/aggregate registry
  views with a straggler z-score, clock-offset estimation + stitched
  multi-rank timelines (``tools/trace_timeline.py --fleet``), and the
  crash-fanout flight recorder merged by ``tools/fleetwatch.py``;
- `kernels`   — per-HLO kernel census over the profiler's device trace,
  roofline placement per kernel (``bound_by`` with honest unknown-bytes
  coverage), compile-ledger join, and `diff_census` fusion forensics
  (``mx_kernel_fusion_delta``; rendered by ``tools/kernelscope.py``);
- `goodput`   — training goodput ledger attributing every wall second to
  compute / data_wait / checkpoint / reshard / drain / recovery / idle
  via `lease()` seams in the estimator, dataloader, checkpointer, and
  `ElasticController` (``mx_goodput_seconds_total{state=}``,
  ``mx_goodput_frac``; fleet-aggregated in `fleet_report()`);
- `timeseries` — opt-in ring-buffer history over every registry series
  (``MXNET_TS_INTERVAL``/``MXNET_TS_SAMPLES``) with windowed queries
  (`rate`/`delta`/`percentile_over_time`/`window_frac`) — the signal
  layer the burn-rate alerter and autoscale advisor read;
- `burnrate`  — SRE-style multi-window multi-burn-rate alerts over the
  SLO burn gauges (``mx_alert_firing{alert=}``, hysteresis so steady
  traces never flap; ``MXNET_BURN_WINDOWS``);
- `capacity`  — per-tenant/per-model cost ledger at the serving seams
  (tokens, prefill/decode device-seconds, KV page-seconds, queue-wait
  as ``mx_capacity_*``; rolled up in `fleet_report()`);
- `anatomy`   — per-request latency anatomy (request wall decomposed
  into queue_wait / preempted / prefill_wait / prefill_compute /
  handoff_migration / decode_compute / spec_overhead, sum-to-wall per
  request), per-replica role residency
  (``mx_replica_residency_seconds_total{replica=,role=,state=}``), and
  the tail-sampled request archive (``MXNET_ANATOMY_SAMPLE`` /
  ``MXNET_ANATOMY_RING``; rendered by ``tools/reqscope.py``).

Env knobs (registered in `util._ENV_KNOBS`): ``MXNET_TELEMETRY``
(``1`` = stage + span tracing on, ``raise`` = + NaN guard raising at the
first non-finite output, ``0``/unset = off — zero per-op cost),
``MXNET_TELEMETRY_INTERVAL`` (batches between estimator registry logs),
``MXNET_TELEMETRY_DUMP=<path>[:interval_s]`` (periodic exposition
snapshots for node-exporter textfile scraping).
"""
from __future__ import annotations

from . import locks  # noqa: F401  (first: tracked_lock feeds the rest)
from . import registry  # noqa: F401
from . import roofline  # noqa: F401
from . import stages  # noqa: F401
from . import tracing  # noqa: F401
from . import slo  # noqa: F401
from . import monitor  # noqa: F401
from . import compiles  # noqa: F401
from . import hbm  # noqa: F401
from . import fleet  # noqa: F401
from . import kernels  # noqa: F401
from . import goodput  # noqa: F401
from . import timeseries  # noqa: F401
from . import burnrate  # noqa: F401
from . import capacity  # noqa: F401
from . import anatomy  # noqa: F401
from .monitor import Monitor, install_nan_hook  # noqa: F401

# arm the host->device byte inlet (a counter inc per transfer — rare
# events, so always on once telemetry is imported)
from ..ndarray import ndarray as _nd_mod

_nd_mod._H2D_HOOK = registry.add_h2d_bytes

__all__ = ["registry", "stages", "tracing", "slo", "roofline", "monitor",
           "compiles", "hbm", "fleet", "kernels", "goodput", "locks",
           "timeseries", "burnrate", "capacity", "anatomy",
           "Monitor", "install_nan_hook"]
