"""Per-HLO kernel observatory: census, roofline placement, fusion diff.

`telemetry/roofline.py` classifies device time into eight coarse phases;
closing a measured perf gap needs the *individual HLO kernels* named.
This module turns the device trace `profiler.py` captures into that
table:

- `census()`: one row per kernel name — occurrences, device time, bytes
  accessed / FLOPs where the XPlane stats carry them (via the shared
  `profiler.event_stat_bytes`/`event_stat_flops` extraction path), an
  achieved-GB/s and achieved-TFLOP/s placement against the chip roofs
  (`roofline.PEAK_HBM_GBS`, `PEAK_TFLOPS` here), and a ``bound_by``
  verdict. Coverage is honest by construction: a kernel without a bytes
  stat reads as *unknown*, never *fast*, and the census reports both the
  attributed-time fraction (named kernels vs total device time) and the
  byte-stat coverage fraction.
- the PR 9 compile ledger JOIN: pass ``ledger=compiles.ledger_report()``
  and every program family gets a cost-model roofline placement
  (arithmetic intensity from cost_analysis flops / bytes_accessed vs the
  machine balance point) next to the trace-measured rows; `program_mfu()`
  converts ledger FLOPs + measured device seconds into a trace-measured
  MFU that `bench.py` cross-checks against its hand-derived formula.
- fusion forensics: `diff_census(before, after)` names the kernels that
  appeared / vanished / split / merged between two configs (e.g. int8
  quantize boundaries fused vs standalone), emits the verdict as
  ``mx_kernel_fusion_delta{kind=}`` counters, and parks the last diff in
  a flight-context block so the evidence rides every flight record.

`tools/kernelscope.py` renders all of it from a live run or a committed
trace.
"""
from __future__ import annotations

import re

from . import registry, tracing
from .roofline import DEFAULT_EXCLUDE, PEAK_HBM_GBS, _device_lane_pids

__all__ = ["census", "from_profiler", "diff_census", "top_bandwidth_bound",
           "program_mfu", "format_census", "format_diff", "PEAK_TFLOPS",
           "last_census", "last_diff", "reset"]

# peak dense bf16 TFLOP/s per chip generation (vendor-published figures;
# pass peak_tflops= explicitly for other parts / dtypes)
PEAK_TFLOPS = {"v3": 123.0, "v4": 275.0, "v5e": 197.0, "v5p": 459.0,
               "v6e": 918.0}

_LAST_CENSUS = None     # meta summary of the last census (flight context)
_LAST_DIFF = None       # last diff_census result (flight context)


def _roofs(device, peak_gbs, peak_tflops):
    if device is not None:
        key = str(device).lower()
        if peak_gbs is None:
            peak_gbs = PEAK_HBM_GBS.get(key)
        if peak_tflops is None:
            peak_tflops = PEAK_TFLOPS.get(key)
    return peak_gbs, peak_tflops


def _bound_by(bytes_known, achieved_gbs, achieved_tflops,
              peak_gbs, peak_tflops):
    """Roofline verdict for one kernel. No bytes stat -> *unknown* (the
    honesty rule: a thin trace must not read as compute-bound-and-fast).
    With bytes, the kernel is bound by whichever roof it sits closer to;
    without a FLOPs stat the memory verdict stands on bytes alone."""
    if not bytes_known or peak_gbs is None or achieved_gbs is None:
        return "unknown"
    hbm_frac = achieved_gbs / peak_gbs
    flops_frac = ((achieved_tflops / peak_tflops)
                  if (achieved_tflops is not None and peak_tflops)
                  else 0.0)
    return "compute" if flops_frac > hbm_frac else "memory"


def census(events=None, ledger=None, device=None, peak_gbs=None,
           peak_tflops=None, exclude=DEFAULT_EXCLUDE):
    """Per-HLO-kernel census over chrome-trace device events (default:
    `profiler.device_events()` from the last trace).

    Returns ``{"rows", "programs", "meta"}``: each row is ``{name, count,
    time_us, bytes, flops, bytes_known, flops_known, achieved_gbs,
    achieved_tflops, hbm_frac, flops_frac, bound_by}`` sorted by device
    time; ``achieved_gbs`` divides known bytes by the kernel's FULL
    device time, so missing byte stats bias it LOW (conservative).
    ``meta`` carries ``attributed_frac`` (named-kernel time over total
    device time including excluded runtime/interpreter events) and
    ``bytes_coverage`` (fraction of named events carrying a bytes stat).
    ``ledger`` (a `compiles.ledger_report()` dict) adds a ``programs``
    section: per family, cost-model arithmetic intensity and bound-by
    against the machine balance point."""
    global _LAST_CENSUS
    if events is None:
        from .. import profiler

        events = profiler.device_events()
    peak_gbs, peak_tflops = _roofs(device, peak_gbs, peak_tflops)
    rx_excl = re.compile(exclude) if exclude else None
    lane_pids = _device_lane_pids(events)
    from .. import profiler as _prof

    agg: dict = {}      # name -> [count, time_us, bytes, flops, bk, fk]
    total_us = 0.0      # ALL complete device-lane events, excluded or not
    for e in events:
        if e.get("ph") != "X":
            continue
        if lane_pids and e.get("pid") not in lane_pids:
            continue
        dur = float(e.get("dur", 0.0))
        total_us += dur
        name = str(e.get("name", "?"))
        if rx_excl is not None and rx_excl.search(name.lower()):
            continue
        row = agg.setdefault(name, [0, 0.0, 0, 0, 0, 0])
        row[0] += 1
        row[1] += dur
        b = _prof.event_stat_bytes(e)
        if b is not None:
            row[2] += b
            row[4] += 1
        fl = _prof.event_stat_flops(e)
        if fl is not None:
            row[3] += fl
            row[5] += 1
    rows = []
    for name, (n, us, nbytes, nflops, bk, fk) in agg.items():
        secs = us * 1e-6
        gbs = (nbytes / secs / 1e9) if secs > 0 and nbytes else None
        tfl = (nflops / secs / 1e12) if secs > 0 and nflops else None
        rows.append({
            "name": name, "count": n, "time_us": us,
            "bytes": nbytes, "flops": nflops,
            "bytes_known": bk, "flops_known": fk,
            "achieved_gbs": gbs, "achieved_tflops": tfl,
            "hbm_frac": (gbs / peak_gbs) if (gbs and peak_gbs) else None,
            "flops_frac": ((tfl / peak_tflops)
                           if (tfl and peak_tflops) else None),
            "bound_by": _bound_by(bk, gbs, tfl, peak_gbs, peak_tflops),
        })
    rows.sort(key=lambda r: -r["time_us"])
    named_us = sum(r["time_us"] for r in rows)
    named_ev = sum(r["count"] for r in rows)
    known_ev = sum(r["bytes_known"] for r in rows)
    meta = {
        "device": device, "peak_gbs": peak_gbs, "peak_tflops": peak_tflops,
        "total_device_us": total_us, "named_us": named_us,
        "n_kernels": len(rows),
        "attributed_frac": (named_us / total_us) if total_us > 0 else 0.0,
        "bytes_coverage": (known_ev / named_ev) if named_ev else 0.0,
    }
    out = {"rows": rows, "programs": _join_ledger(
        ledger, peak_gbs, peak_tflops), "meta": meta}
    _LAST_CENSUS = dict(meta)
    _LAST_CENSUS["top"] = [
        {"name": r["name"], "time_us": r["time_us"],
         "bound_by": r["bound_by"]} for r in rows[:5]]
    return out


def _join_ledger(ledger, peak_gbs, peak_tflops):
    """Cost-model roofline placement per compile-ledger program family:
    arithmetic intensity (flops / bytes_accessed from XLA cost_analysis)
    vs the machine balance point (peak FLOP/s over peak HBM B/s)."""
    if not ledger:
        return {}
    balance = ((peak_tflops * 1e12) / (peak_gbs * 1e9)
               if peak_tflops and peak_gbs else None)
    progs = {}
    for fam, rec in ledger.items():
        if not isinstance(rec, dict):
            continue
        flops = rec.get("flops")
        nbytes = rec.get("bytes_accessed")
        ai = (flops / nbytes) if flops and nbytes else None
        bound = "unknown"
        if ai is not None and balance is not None:
            bound = "compute" if ai > balance else "memory"
        progs[fam] = {"flops": flops, "bytes_accessed": nbytes,
                      "arith_intensity": ai, "balance_flops_per_byte":
                      balance, "bound_by": bound,
                      "compiles": rec.get("compiles")}
    return progs


def from_profiler(**kwargs):
    """Census over the device trace captured by the last
    `profiler.stop()`."""
    return census(**kwargs)


def program_mfu(flops_per_execution, executions, device_seconds,
                peak_tflops=None, device=None):
    """Trace-measured MFU for one program family: cost-model FLOPs per
    execution x executions over measured device seconds, against the
    chip's peak. Returns None when any input is missing — the honesty
    rule again: no trace, no MFU claim."""
    _, peak_tflops = _roofs(device, None, peak_tflops)
    if (not flops_per_execution or not executions or not device_seconds
            or device_seconds <= 0 or not peak_tflops):
        return None
    return (float(flops_per_execution) * executions
            / device_seconds / (peak_tflops * 1e12))


def top_bandwidth_bound(result, n=10):
    """The top-``n`` memory-bound kernels by device time — the
    optimization targets a fusion pass should chase. Kernels with
    unknown bytes are excluded (never ranked as fast OR as slow)."""
    return [r for r in result["rows"] if r["bound_by"] == "memory"][:n]


def _base_name(name):
    # strip the trailing fusion/instruction index: "fusion.123" -> "fusion"
    return re.sub(r"\.\d+$", "", name)


def diff_census(before, after):
    """Fusion forensics between two censuses (or bare row lists): which
    kernel names appeared, vanished, split (same base name, more
    variants), or merged. The verdict calls the delta ``fused`` when
    names only vanished/merged, ``split`` when they only appeared/split,
    else ``mixed`` (``unchanged`` when nothing moved). Emits
    ``mx_kernel_fusion_delta{kind=}`` counters and parks the result for
    the flight-context block."""
    global _LAST_DIFF
    b_rows = before["rows"] if isinstance(before, dict) else before
    a_rows = after["rows"] if isinstance(after, dict) else after
    b_names = {r["name"] for r in b_rows}
    a_names = {r["name"] for r in a_rows}
    appeared = sorted(a_names - b_names)
    vanished = sorted(b_names - a_names)
    b_bases: dict = {}
    a_bases: dict = {}
    for n in b_names:
        b_bases[_base_name(n)] = b_bases.get(_base_name(n), 0) + 1
    for n in a_names:
        a_bases[_base_name(n)] = a_bases.get(_base_name(n), 0) + 1
    split = sorted(b for b in a_bases
                   if b in b_bases and a_bases[b] > b_bases[b])
    merged = sorted(b for b in b_bases
                    if b in a_bases and b_bases[b] > a_bases[b])
    t_before = sum(r["time_us"] for r in b_rows)
    t_after = sum(r["time_us"] for r in a_rows)
    if (vanished or merged) and not (appeared or split):
        verdict = "fused"
    elif (appeared or split) and not (vanished or merged):
        verdict = "split"
    elif vanished or merged or appeared or split:
        verdict = "mixed"
    else:
        verdict = "unchanged"
    diff = {"appeared": appeared, "vanished": vanished, "split": split,
            "merged": merged, "verdict": verdict,
            "time_before_us": t_before, "time_after_us": t_after,
            "time_delta_us": t_after - t_before}
    for kind, names in (("appeared", appeared), ("vanished", vanished),
                        ("split", split), ("merged", merged)):
        if names:
            registry.counter(
                "mx_kernel_fusion_delta",
                "kernel names changed between two census configs",
                labels={"kind": kind}).inc(len(names))
    _LAST_DIFF = diff
    return diff


def _fmt(v, unit="", nd=1):
    return "-" if v is None else f"{v:.{nd}f}{unit}"


def format_census(result, top=20):
    """Markdown top-``top`` kernel table of a `census()` result."""
    meta = result["meta"]
    lines = ["| kernel | n | time µs | GB/s | TFLOP/s | % HBM roof | "
             "bound by |",
             "|---|---:|---:|---:|---:|---:|---|"]
    for r in result["rows"][:top]:
        lines.append(
            f"| {r['name'][:48]} | {r['count']} | {r['time_us']:.1f} | "
            f"{_fmt(r['achieved_gbs'])} | {_fmt(r['achieved_tflops'], nd=2)}"
            f" | {_fmt(r['hbm_frac'] * 100 if r['hbm_frac'] is not None else None)}"
            f" | {r['bound_by']} |")
    lines.append("")
    lines.append(
        f"{meta['n_kernels']} kernels; "
        f"{meta['attributed_frac'] * 100:.1f}% of device time attributed "
        f"to named kernels; byte-stat coverage "
        f"{meta['bytes_coverage'] * 100:.0f}% of named events (kernels "
        "without a bytes stat read as *unknown*, never *fast*)")
    if meta.get("peak_gbs"):
        lines.append(f"roofs: {meta['peak_gbs']:.0f} GB/s HBM, "
                     f"{_fmt(meta.get('peak_tflops'), ' TFLOP/s bf16')} "
                     f"({meta.get('device') or 'explicit'})")
    for fam, p in (result.get("programs") or {}).items():
        lines.append(
            f"program `{fam}`: cost-model AI "
            f"{_fmt(p['arith_intensity'], ' flop/B')} vs balance "
            f"{_fmt(p['balance_flops_per_byte'], ' flop/B')} -> "
            f"{p['bound_by']}-bound")
    return "\n".join(lines)


def format_diff(diff):
    """Text rendering of a `diff_census()` result."""
    lines = [f"fusion delta: {diff['verdict']} "
             f"(device time {diff['time_before_us']:.1f} -> "
             f"{diff['time_after_us']:.1f} µs, "
             f"{diff['time_delta_us']:+.1f})"]
    for kind in ("appeared", "vanished", "split", "merged"):
        if diff[kind]:
            lines.append(f"  {kind}: {', '.join(diff[kind])}")
    return "\n".join(lines)


def last_census():
    return _LAST_CENSUS


def last_diff():
    return _LAST_DIFF


def _flight_probe():
    if _LAST_CENSUS is None and _LAST_DIFF is None:
        return None
    return {"census": _LAST_CENSUS, "fusion_delta": _LAST_DIFF}


def reset():
    global _LAST_CENSUS, _LAST_DIFF
    _LAST_CENSUS = None
    _LAST_DIFF = None


tracing.register_flight_context("kernels", _flight_probe)
