"""Training-health monitor (reference: `python/mxnet/monitor.py` —
`Monitor(interval, stat_func, pattern, sort)` printing per-layer output
stats; the NaN watcher role of `tests/python/unittest/test_monitor.py`).

TPU-native differences from the reference:

- the tap point is the op funnel (`ndarray.apply_op`), not executor
  output arrays — every eager op whose name matches ``pattern`` is
  observed, hybridized interiors are covered by the NaN hook below;
- stats (l2 norm, mean, max|.|, NaN count, Inf count) are computed
  ON-DEVICE as 0-dim jax arrays and the host sync is BATCHED: nothing
  blocks until `toc()` pulls the whole collected batch in one
  `device_get` (the reference syncs per-array via asnumpy).

NaN hook (`install_nan_hook`): catches the FIRST non-finite op output.

- eager op: the finite-flag is synced per op (a debugging mode — the cost
  is the point) and `mode="raise"` raises `MXNetError` at the faulting op;
- under jit (hybridized blocks): the check is embedded into the traced
  program via `jax.debug.callback`, so compiled replays keep the guard;
  the callback records the finding asynchronously and the next funnel
  entry (or an explicit `check()` / `nan_findings()`) surfaces it.
  Blocks hybridized BEFORE the hook was installed keep their compiled
  program — re-hybridize (or install the hook first) to instrument them.

`MXNET_TELEMETRY=raise` installs the raising hook at import
(`util._apply_env_config`).

Per-rank aggregation: `queue_rank_stats()` + `sync_rank_stats()` exchange
each rank's scalar summary at kvstore sync points (kvstore.barrier rides
the same collective channel as `profiler.sync_remote_commands`) and
`rank_aggregate()` exposes min/max/mean across ranks. The 1-process path
degenerates to the local summary.
"""
from __future__ import annotations

import json
import os
import re
import time

from ..base import MXNetError
from ..gluon.contrib.estimator.event_handler import (BatchBegin, BatchEnd,
                                                     EpochEnd, TrainBegin)
from . import registry

__all__ = ["Monitor", "install_nan_hook", "uninstall_nan_hook",
           "nan_findings", "clear_nan_findings", "check",
           "add_health_check", "remove_health_check",
           "queue_rank_stats", "sync_rank_stats", "rank_aggregate",
           "TelemetryHandler"]

_ACTIVE_MONITORS: list = []
_NAN_MODE = None                 # None | "warn" | "raise"
_NAN_FINDINGS: list = []


def _jnp():
    import jax.numpy as jnp

    return jnp


def _install_funnel_hook():
    from ..ndarray import ndarray as nd_mod

    nd_mod._MONITOR_HOOK = _observe if (_ACTIVE_MONITORS or _NAN_MODE) \
        else None


def default_stats(x):
    """Per-tensor health stats as 0-dim device arrays (no host sync)."""
    jnp = _jnp()
    xf = x.astype(jnp.float32) if x.dtype != jnp.float32 else x
    return {"norm": jnp.sqrt((xf * xf).sum()),
            "mean": xf.mean(),
            "max_abs": jnp.abs(xf).max(),
            "nan": jnp.isnan(xf).sum(),
            "inf": jnp.isinf(xf).sum()}


class Monitor:
    """Observe matching op outputs between `tic()` and `toc()`.

    Parameters mirror the reference: `interval` (observe every N-th
    tic/toc cycle), `stat_func` (array -> 0-dim device array or dict of
    them; default `default_stats`), `pattern` (op-name regex), `sort`
    (sort `toc()` results by name). `callback` additionally receives the
    synced `(step, name, stat, value)` rows at each `toc()`.
    """

    def __init__(self, interval=1, stat_func=None, pattern=".*", sort=False,
                 callback=None):
        self.interval = max(1, int(interval))
        self.stat_func = stat_func or default_stats
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.callback = callback
        self.step = 0
        self.activated = False
        self.queue: list = []            # (step, op name, stat, device val)

    # -- reference surface -------------------------------------------------
    def install(self, block=None):  # noqa: ARG002 - funnel-level tap
        """Reference parity shim: the funnel tap needs no per-executor
        install; accepted so reference scripts run unchanged."""
        return self

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
            if self not in _ACTIVE_MONITORS:
                _ACTIVE_MONITORS.append(self)
                _install_funnel_hook()
        self.step += 1

    def toc(self):
        """Deactivate and return `[(step, name, stat, value), ...]` with
        ONE batched host sync for everything observed since `tic()`."""
        if not self.activated:
            return []
        self.activated = False
        if self in _ACTIVE_MONITORS:
            _ACTIVE_MONITORS.remove(self)
            _install_funnel_hook()
        queue, self.queue = self.queue, []
        import jax

        values = jax.device_get([v for (_, _, _, v) in queue])
        rows = [(step, name, stat, float(v))
                for (step, name, stat, _), v in zip(queue, values)]
        if self.sort:
            rows.sort(key=lambda r: (r[1], r[2]))
        if self.callback is not None:
            self.callback(rows)
        return rows

    def toc_print(self):
        for step, name, stat, value in self.toc():
            print(f"Batch: {step:7d} {name + '_' + stat:30s} {value:.6g}")

    def __enter__(self):
        self.tic()
        return self

    def __exit__(self, *exc):
        self.toc_print()
        return False

    # -- funnel side -------------------------------------------------------
    def _observe(self, name, out_vals):
        if not self.activated or not self.re_pattern.search(name):
            return
        for val in out_vals:
            stats = self.stat_func(val)
            if not isinstance(stats, dict):
                stats = {"stat": stats}
            for stat, v in stats.items():
                self.queue.append((self.step - 1, name, stat, v))


# ---------------------------------------------------------------------------
# funnel hook (shared by monitors and the NaN guard)
# ---------------------------------------------------------------------------

def _is_tracer(x):
    import jax

    return isinstance(x, jax.core.Tracer)


def _record_finding(name, where):
    _NAN_FINDINGS.append({"op": name, "where": where,
                          "time": time.time()})


def _observe(name, out_vals):
    """Installed as `ndarray._MONITOR_HOOK`; receives every funnel op's
    name and raw output values (jax arrays, or tracers inside a jit
    trace)."""
    if _NAN_FINDINGS and _NAN_MODE == "raise":
        # async finding from a compiled program's debug callback: surface
        # it at the next op instead of losing it in the runtime thread
        f = _NAN_FINDINGS[0]
        raise MXNetError(
            f"non-finite output detected at op '{f['op']}' ({f['where']}) "
            "— raising at the next funnel entry (MXNET_TELEMETRY=raise)")
    jnp = _jnp()
    if _NAN_MODE is not None:
        for val in out_vals:
            if not hasattr(val, "dtype") or \
                    not jnp.issubdtype(val.dtype, jnp.inexact):
                continue
            if _is_tracer(val):
                import jax
                from functools import partial

                jax.debug.callback(partial(_nan_callback, name),
                                   jnp.isfinite(val).all())
            else:
                if not bool(jnp.isfinite(val).all()):
                    _record_finding(name, "eager")
                    if _NAN_MODE == "raise":
                        raise MXNetError(
                            f"non-finite output detected at op '{name}' "
                            "(eager, MXNET_TELEMETRY=raise)")
    tracer_free = None
    for mon in list(_ACTIVE_MONITORS):
        if tracer_free is None:
            tracer_free = not any(_is_tracer(v) for v in out_vals)
        if tracer_free:       # monitors observe the eager funnel only
            mon._observe(name, out_vals)


def _nan_callback(name, finite):
    """Runs at EXECUTION time inside compiled programs (jax.debug.callback)
    — `finite` is the concrete all-finite flag for one op output."""
    try:
        ok = bool(finite)
    except Exception:
        ok = True
    if not ok:
        _record_finding(name, "jit")


def install_nan_hook(mode="raise"):
    """Arm the non-finite guard on every funnel op output. `mode="raise"`
    raises `MXNetError` at the first finding (eager: at the faulting op;
    jit: at the next funnel entry after the async callback lands);
    `mode="warn"` only records into `nan_findings()`."""
    global _NAN_MODE
    if mode not in ("warn", "raise"):
        raise ValueError(f"mode must be 'warn' or 'raise', got {mode!r}")
    _NAN_MODE = mode
    _install_funnel_hook()


def uninstall_nan_hook():
    global _NAN_MODE
    _NAN_MODE = None
    _install_funnel_hook()


def nan_findings():
    return list(_NAN_FINDINGS)


def clear_nan_findings():
    del _NAN_FINDINGS[:]


_HEALTH_CHECKS: dict = {}     # name -> callable raising on violation


def add_health_check(fn, name=None):
    """Register an extra health probe run by `check()` — `fn()` raises
    on violation (e.g. `telemetry.slo.install_health_check()` routes the
    SLO tracker's burned-budget check here). Re-registering a name
    replaces the previous probe (idempotent installs)."""
    _HEALTH_CHECKS[name or getattr(fn, "__name__", repr(fn))] = fn
    return fn


def remove_health_check(name):
    _HEALTH_CHECKS.pop(name, None)


def check():
    """Raise if any non-finite finding is pending (call after a sync point
    — e.g. `mx.waitall()` — to surface async jit-path findings), then run
    every registered health probe (`add_health_check`) — SLO budget burns
    surface here too."""
    if _NAN_FINDINGS:
        f = _NAN_FINDINGS[0]
        raise MXNetError(
            f"non-finite output detected at op '{f['op']}' ({f['where']})")
    for fn in list(_HEALTH_CHECKS.values()):
        fn()


# ---------------------------------------------------------------------------
# per-rank aggregation (kvstore sync-point channel)
# ---------------------------------------------------------------------------

_RANK_SUMMARY: dict = {}
_RANK_AGGREGATE: dict = {}


def queue_rank_stats(stats):
    """Queue this rank's scalar summary ({name: float}) for the next
    kvstore sync point. Keep it small: the exchange rides the 4 KiB
    command slot of `dist.exchange_objs`."""
    for k, v in stats.items():
        _RANK_SUMMARY[str(k)] = float(v)


def sync_rank_stats():
    """Collective min/max/mean of queued rank summaries — called from
    `kvstore.barrier()` on EVERY rank (same channel as
    `profiler.sync_remote_commands`). Single-process degenerates to the
    local summary. Returns the aggregate and caches it for
    `rank_aggregate()`."""
    global _RANK_SUMMARY
    mine, _RANK_SUMMARY = _RANK_SUMMARY, {}
    from ..parallel import dist

    if dist.is_initialized():
        all_stats = [s or {} for s in dist.exchange_objs(mine)]
    else:
        all_stats = [mine]
    merged = {}
    for stats in all_stats:
        for k, v in stats.items():
            merged.setdefault(k, []).append(v)
    _RANK_AGGREGATE.clear()
    for k, vals in merged.items():
        _RANK_AGGREGATE[k] = {"min": min(vals), "max": max(vals),
                              "mean": sum(vals) / len(vals),
                              "ranks": len(vals)}
    return dict(_RANK_AGGREGATE)


def rank_aggregate():
    """Last synced cross-rank aggregate: {name: {min, max, mean, ranks}}."""
    return dict(_RANK_AGGREGATE)


# ---------------------------------------------------------------------------
# estimator integration
# ---------------------------------------------------------------------------

class TelemetryHandler(TrainBegin, BatchBegin, BatchEnd, EpochEnd):
    """Estimator event handler feeding the metrics registry: per-batch
    step time + example counts into `mx_step_time_seconds` /
    `mx_examples_total`, and a registry report logged at every epoch end
    (plus every MXNET_TELEMETRY_INTERVAL batches when that knob is set)."""

    def __init__(self, interval=None, priority=-100):
        if interval is None:
            try:
                interval = int(os.environ.get("MXNET_TELEMETRY_INTERVAL",
                                              "0"))
            except ValueError:
                interval = 0
        self.interval = interval          # batches between log lines
        self.priority = priority
        self._t0 = None
        self._batches = 0

    def train_begin(self, estimator, *args, **kwargs):
        self._batches = 0

    def batch_begin(self, estimator, *args, **kwargs):
        self._t0 = time.perf_counter()

    def batch_end(self, estimator, *args, **kwargs):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        batch = kwargs.get("batch")
        n = 0
        try:
            n = int(batch[0].shape[0])
        except (TypeError, AttributeError, IndexError, KeyError):
            pass                      # batch without a leading array leaf
        registry.step(dt, examples=n)
        self._batches += 1
        if self.interval and self._batches % self.interval == 0:
            estimator.logger.info("telemetry[batch %d]: %s", self._batches,
                                  json.dumps(registry.report(),
                                             sort_keys=True, default=str))

    def epoch_end(self, estimator, *args, **kwargs):
        estimator.logger.info("telemetry: %s",
                              json.dumps(registry.report(), sort_keys=True,
                                         default=str))
