"""Per-request latency anatomy + per-replica role residency for the
serving plane (TELEMETRY.md §request anatomy).

PR 13's goodput ledger proved the sum-to-wall discipline for training:
every wall second attributed to exactly one state, idle as the honest
remainder. This module applies the same invariant PER REQUEST on the
serving side. A gateway request's wall (submit → finish) is decomposed
into

    {queue_wait, preempted, prefill_wait, prefill_compute,
     handoff_migration, decode_compute, spec_overhead}

by a per-record state machine driven from the EXISTING serving seams —
the gateway's dispatch/preempt/finish paths, the scheduler's
prefill/decode/spec capacity seams (no new timers fire on a hot path
that did not already read a perf_counter), and the disagg migration
plane. Ambient phases (queue_wait, preempted, prefill_wait,
handoff_migration, decode_compute) partition the timeline; compute
charges (prefill_compute, spec_overhead) are carved out of the ambient
phase they occur in, so the states sum to the request's wall by
construction (clock-resolution residual only; the committed gate holds
it ≤ 2%).

ROLE RESIDENCY: every replica's wall is attributed to
{prefill, decode, migration, warmup, idle} from the same seam deltas —
exported as ``mx_replica_residency_seconds_total{replica=,role=,state=}``
plus ``mx_replica_residency_frac{replica=,state=}`` pull gauges. The
compute deltas are the SAME values `telemetry.capacity` banks once via
`split_device_seconds`, so the residency plane audits against
``capacity.measured_wall_s()`` (``report()["device_audit"]``). This is
the evidence the role-aware autoscale advisor reads
(`serve.advisor`: ``scale_up_prefill`` vs ``scale_up_decode``).

TAIL-SAMPLED ARCHIVE: completed anatomy records land in a bounded ring
that ALWAYS retains the interesting tail — SLO-violating, preempted,
migrated, and crash-resumed requests — and keeps a deterministic
``MXNET_ANATOMY_SAMPLE`` fraction of normal ones (``MXNET_ANATOMY_RING``
bounds each ring). Surfaced as a flight-recorder context block and by
``tools/reqscope.py`` (percentile waterfalls per tier/tenant/model).

Off-path contract: disarmed, every seam pays a single None-check (the
per-request handle is None and the module flag is False); matching
every prior telemetry layer, the <3% gate is priced by
``bench_gpt_serve_anatomy``. Arms with the rest of the telemetry plane
(``MXNET_TELEMETRY=1`` at import) or via `enable()`.
"""
from __future__ import annotations

import collections
import os
import time

from . import registry, tracing
from .locks import tracked_lock

__all__ = ["enable", "disable", "is_enabled", "reset", "STATES",
           "RESIDENCY_STATES", "begin", "complete", "RequestAnatomy",
           "on_prefill_chunk", "on_decode_step", "on_migration",
           "warmup_begin", "warmup_end", "charge_replica",
           "residency_report", "archive", "report", "format_waterfall",
           "set_sample", "set_ring", "sample_rate"]

STATES = ("queue_wait", "preempted", "prefill_wait", "prefill_compute",
          "handoff_migration", "decode_compute", "spec_overhead")

# ambient phases partition the timeline; the other two are carved
_PHASES = ("queue_wait", "preempted", "prefill_wait",
           "handoff_migration", "decode_compute")

RESIDENCY_STATES = ("prefill", "decode", "migration", "warmup", "idle")

_ENABLED = False
_LOCK = tracked_lock("telemetry.anatomy", kind="lock")


def _env_float(name, default):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name, default):
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


_SAMPLE = min(max(_env_float("MXNET_ANATOMY_SAMPLE", 0.05), 0.0), 1.0)
_RING = max(_env_int("MXNET_ANATOMY_RING", 256), 1)

# always-keep ring (SLO violators / preempted / migrated / crash-resumed)
_TAIL = collections.deque(maxlen=_RING)
# deterministically sampled normal completions
_SAMPLED = collections.deque(maxlen=_RING)
_NORMAL_SEEN = [0]
_COMPLETED = [0]
_STATE_TOTALS = {s: 0.0 for s in STATES}


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled():
    return _ENABLED


def set_sample(rate):
    """Override the normal-completion sampling rate (tests, demo)."""
    global _SAMPLE
    _SAMPLE = min(max(float(rate), 0.0), 1.0)


def sample_rate():
    return _SAMPLE


def set_ring(n):
    """Resize both archive rings (drops current contents)."""
    global _RING, _TAIL, _SAMPLED
    with _LOCK:
        _RING = max(int(n), 1)
        _TAIL = collections.deque(maxlen=_RING)
        _SAMPLED = collections.deque(maxlen=_RING)


def reset():
    """Drop every record and residency ledger (tests). The mx_* series
    live in the registry and reset with `registry.reset()`."""
    with _LOCK:
        _TAIL.clear()
        _SAMPLED.clear()
        _NORMAL_SEEN[0] = 0
        _COMPLETED[0] = 0
        for s in STATES:
            _STATE_TOTALS[s] = 0.0
        _REPLICAS.clear()


# ---------------------------------------------------------------------------
# per-request anatomy records
# ---------------------------------------------------------------------------

class RequestAnatomy:
    """One request's wall-time decomposition. Ambient phase transitions
    take a ``time.monotonic()`` timestamp from the calling seam; compute
    carves take perf_counter deltas measured by the same seam that feeds
    the capacity ledger. Never constructed while the plane is disarmed —
    the gateway holds ``None`` instead, so the off path is one
    None-check."""

    __slots__ = ("req_id", "tenant", "model", "tier", "submit_t",
                 "finish_t", "deadline", "states", "flags", "replica",
                 "tokens", "outcome", "resumes", "owner", "_t", "_phase",
                 "_carve")

    def __init__(self, req_id, tenant, model, tier, now, deadline=None):
        self.req_id = req_id
        # which plane completes this record: None = the gateway (its
        # GatewayRequest choke points), "engine" = a standalone
        # ServeEngine request (the engine Request's _finish/_fail) —
        # gateway segments carry gateway-owned records through the same
        # scheduler, so the engine seams must not double-complete them
        self.owner = None
        self.tenant = str(tenant) if tenant else "anon"
        self.model = str(model)
        self.tier = str(tier)
        self.submit_t = float(now)
        self.finish_t = None
        self.deadline = deadline          # absolute monotonic, or None
        self.states = {s: 0.0 for s in STATES}
        self.flags = set()
        self.replica = None
        self.tokens = 0
        self.outcome = None
        self.resumes = 0
        self._t = float(now)
        self._phase = "queue_wait"
        self._carve = 0.0

    # -- the state machine -------------------------------------------------

    def _transition(self, now, phase):
        """Close the current ambient phase at `now` (charging its wall
        minus any carved compute) and enter `phase` (None = final)."""
        dur = float(now) - self._t
        if dur < 0.0:
            dur = 0.0
        amb = dur - self._carve
        if amb < 0.0:
            amb = 0.0
        if self._phase is not None:
            self.states[self._phase] += amb
        self._t = float(now)
        self._carve = 0.0
        self._phase = phase

    def carve(self, state, seconds):
        """Charge `seconds` of compute to `state`, carved out of the
        ambient phase it occurred in (keeps the sum-to-wall invariant)."""
        seconds = float(seconds)
        if seconds <= 0.0:
            return
        self.states[state] += seconds
        self._carve += seconds

    # -- seam surface (gateway / scheduler / disagg / elastic) --------------

    def dispatched(self, now, replica=None):
        """First dispatch closes ``queue_wait``; a resumed dispatch
        closes ``preempted`` — the wall a request spends RE-queued after
        preemption / migration fallback / crash resume is attributed,
        never dropped."""
        self._transition(now, "prefill_wait")
        if replica is not None:
            self.replica = replica

    def requeued(self, now, flag):
        """Back into the gateway queue (``flag`` ∈ preempted /
        migration_fallback / crash_resume) — subsequent wall charges to
        the ``preempted`` state until re-dispatch."""
        self._transition(now, "preempted")
        self.flags.add(str(flag))
        self.resumes += 1

    def prefill_done(self, now, handoff=False):
        """The final prefill chunk sampled the first token: a disagg
        handoff segment parks in ``handoff_migration`` (waiting for the
        migration plane), everything else enters ``decode_compute``."""
        self._transition(
            now, "handoff_migration" if handoff else "decode_compute")

    def adopted(self, now, migrated=True):
        """The decode side owns the request (page migration done, or
        fallback co-location on the prefill replica)."""
        self._transition(now, "decode_compute")
        if migrated:
            self.flags.add("migrated")

    def close(self, now, outcome, tokens=0):
        self._transition(now, None)
        self.finish_t = float(now)
        self.outcome = str(outcome)
        self.tokens = int(tokens)
        if outcome != "ok" or (self.deadline is not None
                               and self.finish_t > self.deadline):
            self.flags.add("slo_violation")

    # -- reading -----------------------------------------------------------

    @property
    def wall_s(self):
        end = self.finish_t if self.finish_t is not None else self._t
        return max(end - self.submit_t, 0.0)

    @property
    def residual_s(self):
        """states sum minus wall — the invariant's error term."""
        return sum(self.states.values()) - self.wall_s

    def snapshot(self):
        return {"id": self.req_id, "tenant": self.tenant,
                "model": self.model, "tier": self.tier,
                "replica": self.replica, "submit_t": self.submit_t,
                "finish_t": self.finish_t, "wall_s": self.wall_s,
                "states": dict(self.states),
                "residual_s": self.residual_s,
                "outcome": self.outcome, "flags": sorted(self.flags),
                "tokens": self.tokens, "resumes": self.resumes}


def begin(req_id, tenant, model, tier, now, deadline=None):
    """Open a record at gateway submit. Returns None while disarmed —
    the caller stores it on the request and every later seam is a single
    ``is not None`` check."""
    if not _ENABLED:
        return None
    return RequestAnatomy(req_id, tenant, model, tier, now,
                          deadline=deadline)


# the always-keep retention predicate: anything that made the request's
# life interesting (tail-latency forensics must never lose these)
_KEEP_FLAGS = ("slo_violation", "preempted", "migration_fallback",
               "crash_resume", "migrated")


def complete(rec, now, outcome, tokens=0):
    """Close `rec` and archive it: interesting records always retained,
    normal ones deterministically sampled at `MXNET_ANATOMY_SAMPLE`."""
    if rec is None or not _ENABLED:
        return
    rec.close(now, outcome, tokens=tokens)
    snap = rec.snapshot()
    with _LOCK:
        _COMPLETED[0] += 1
        for s, v in rec.states.items():
            _STATE_TOTALS[s] += v
    for s, v in rec.states.items():
        if v > 0.0:
            registry.counter(
                "mx_request_anatomy_seconds_total",
                "request wall seconds attributed per anatomy state "
                "(sum-to-wall per request)",
                labels={"state": s}).inc(v)
    registry.counter(
        "mx_request_anatomy_requests_total",
        "completed gateway requests folded into the anatomy archive",
        labels={"outcome": rec.outcome}).inc()
    if any(f in rec.flags for f in _KEEP_FLAGS):
        with _LOCK:
            _TAIL.append(snap)
        return
    with _LOCK:
        n = _NORMAL_SEEN[0]
        _NORMAL_SEEN[0] = n + 1
        # deterministic rate sampling: keep when the accumulator
        # crosses an integer (rate 1.0 keeps all, 0.0 none)
        if int((n + 1) * _SAMPLE) > int(n * _SAMPLE):
            _SAMPLED.append(snap)


def archive():
    """Completed records (always-keep tail + sampled normals), oldest →
    newest by finish time."""
    with _LOCK:
        out = list(_TAIL) + list(_SAMPLED)
    return sorted(out, key=lambda r: (r["finish_t"] or 0.0, r["id"]))


# ---------------------------------------------------------------------------
# per-replica role residency
# ---------------------------------------------------------------------------

class _ReplicaLedger:
    __slots__ = ("label", "role", "start_t", "last_t", "states",
                 "idle_banked")

    def __init__(self, label, role, now):
        self.label = label
        self.role = role
        self.start_t = now
        self.last_t = now
        self.states = {s: 0.0 for s in RESIDENCY_STATES if s != "idle"}
        self.idle_banked = 0.0


_REPLICAS = {}
_PULL_REGISTERED = set()


def _replica_frac_probe(label, state):
    def probe():
        led = _REPLICAS.get(label)
        if led is None:
            return None
        wall = max(led.last_t - led.start_t, 0.0)
        if wall <= 0.0:
            return None
        active = sum(led.states.values())
        if state == "idle":
            return max(wall - active, 0.0) / wall
        return min(led.states[state] / wall, 1.0)
    return probe


def charge_replica(label, role, state, seconds, now=None):
    """Attribute `seconds` of replica wall to a residency state. `now`
    (monotonic) advances the replica's wall horizon; the seams pass the
    timestamp they already read, virtual-clock harnesses pass theirs."""
    if not _ENABLED:
        return
    seconds = float(seconds)
    if seconds < 0.0:
        seconds = 0.0
    if now is None:
        now = time.monotonic()
    with _LOCK:
        led = _REPLICAS.get(label)
        fresh = led is None
        if fresh:
            led = _REPLICAS[label] = _ReplicaLedger(str(label), str(role),
                                                    float(now) - seconds)
        led.states[state] = led.states.get(state, 0.0) + seconds
        if now > led.last_t:
            led.last_t = float(now)
    if fresh and label not in _PULL_REGISTERED:
        # once per label EVER (registry collectors survive both
        # registry.reset() and anatomy.reset(); the probe returns None
        # for a label with no live ledger, omitting the series)
        _PULL_REGISTERED.add(label)
        for s in RESIDENCY_STATES:
            registry.register_pull_gauge(
                "mx_replica_residency_frac",
                _replica_frac_probe(str(label), s),
                "fraction of a serving replica's wall in each residency "
                "state (idle = honest remainder)",
                labels={"replica": str(label), "state": s})
    registry.counter(
        "mx_replica_residency_seconds_total",
        "serving replica wall seconds attributed per residency state "
        "(prefill / decode / migration / warmup; idle banked at report)",
        labels={"replica": str(label), "role": str(role),
                "state": str(state)}).inc(seconds)


def _sched_replica(sched):
    info = getattr(sched, "anatomy_replica", None)
    if info is not None:
        return info
    return (str(getattr(sched, "capacity_model", None) or "engine"),
            "both")


def on_prefill_chunk(sched, req, t0, t1, now=None):
    """One prefill chunk ran on `sched` for `req` over the perf_counter
    window ``[t0, t1]`` — the same window the capacity ledger splits.
    Charges the replica's ``prefill`` residency and carves the request's
    ``prefill_compute`` out of its ambient phase."""
    if not _ENABLED:
        return
    dt = float(t1) - float(t0)
    label, role = _sched_replica(sched)
    charge_replica(label, role, "prefill", dt, now=now)
    rec = getattr(req, "anatomy", None)
    if rec is not None:
        rec.carve("prefill_compute", dt)


def on_decode_step(sched, t0, t1, now=None):
    """One batched decode (or spec draft+verify) program ran over
    ``[t0, t1]`` — charges the replica's ``decode`` residency and
    returns the delta (the spec seam shares it out as overhead)."""
    if not _ENABLED:
        return 0.0
    dt = float(t1) - float(t0)
    label, role = _sched_replica(sched)
    charge_replica(label, role, "decode", dt, now=now)
    return dt


def on_migration(sched, t0, t1, now=None):
    """A KV page migration window ``[t0, t1]`` on the adopting
    (decode-side) replica."""
    if not _ENABLED:
        return
    label, role = _sched_replica(sched)
    charge_replica(label, role, "migration", float(t1) - float(t0),
                   now=now)


def warmup_begin(sched):
    """Open a warmup window on `sched`'s replica. Returns an opaque
    token (None while disarmed); close with `warmup_end`. The window's
    charge is the wall MINUS whatever the decode/prefill seams already
    attributed inside it, so warm steps are never double-counted."""
    if not _ENABLED:
        return None
    label, _role = _sched_replica(sched)
    with _LOCK:
        led = _REPLICAS.get(label)
        seam_s = sum(led.states.values()) if led is not None else 0.0
    return (time.perf_counter(), seam_s)


def warmup_end(sched, token):
    if token is None or not _ENABLED:
        return
    t0, seam_before = token
    label, role = _sched_replica(sched)
    with _LOCK:
        led = _REPLICAS.get(label)
        seam_s = sum(led.states.values()) if led is not None else 0.0
    dt = (time.perf_counter() - t0) - (seam_s - seam_before)
    charge_replica(label, role, "warmup", max(dt, 0.0))


def residency_report(now=None):
    """{label: {"role", "wall_s", "states" (idle included),
    "frac"}} — idle is the honest remainder of each replica's observed
    wall, banked into the counter series as a side effect."""
    out = {}
    with _LOCK:
        items = [(label, led.role, led.start_t, led.last_t,
                  dict(led.states), led.idle_banked)
                 for label, led in _REPLICAS.items()]
    for label, role, start_t, last_t, states, idle_banked in items:
        horizon = last_t if now is None else max(float(now), last_t)
        wall = max(horizon - start_t, 0.0)
        active = sum(states.values())
        idle = max(wall - active, 0.0)
        grow = idle - idle_banked
        if grow > 0.0:
            registry.counter(
                "mx_replica_residency_seconds_total",
                "serving replica wall seconds attributed per residency "
                "state (prefill / decode / migration / warmup; idle "
                "banked at report)",
                labels={"replica": label, "role": role,
                        "state": "idle"}).inc(grow)
            with _LOCK:
                led = _REPLICAS.get(label)
                if led is not None:
                    led.idle_banked = idle
        full = dict(states)
        full["idle"] = idle
        frac = {s: (v / wall if wall > 0.0 else 0.0)
                for s, v in full.items()}
        out[label] = {"role": role, "wall_s": wall, "states": full,
                      "frac": frac}
    return out


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def report(now=None):
    """The full anatomy ledger: aggregate state seconds, the archive,
    per-replica residency, and the device audit against the capacity
    ledger's measured wall (the residency prefill+decode charges are
    the SAME seam deltas `split_device_seconds` banks once)."""
    from . import capacity

    residency = residency_report(now=now)
    device_s = sum(r["states"].get("prefill", 0.0)
                   + r["states"].get("decode", 0.0)
                   for r in residency.values())
    with _LOCK:
        totals = dict(_STATE_TOTALS)
        completed = _COMPLETED[0]
        normal_seen = _NORMAL_SEEN[0]
        tail_n, sampled_n = len(_TAIL), len(_SAMPLED)
    return {
        "enabled": _ENABLED,
        "requests_completed": completed,
        "states": totals,
        "archive": archive(),
        "archive_depth": {"tail": tail_n, "sampled": sampled_n},
        "normal_seen": normal_seen,
        "sample_rate": _SAMPLE,
        "replicas": residency,
        "device_audit": {
            "residency_device_s": device_s,
            "capacity_wall_s": capacity.measured_wall_s(),
        },
    }


_BAR = "█"


def format_waterfall(rec, width=40):
    """One archived record (a `snapshot()` dict) as a text waterfall."""
    wall = rec.get("wall_s") or 0.0
    lines = [f"request {rec['id']} [{rec['model']}/{rec['tenant']}"
             f"/tier {rec['tier']}] wall {wall * 1e3:.1f} ms "
             f"outcome={rec['outcome']}"
             + (f" flags={','.join(rec['flags'])}" if rec["flags"]
                else "")]
    for s in STATES:
        v = rec["states"].get(s, 0.0)
        if v <= 0.0:
            continue
        frac = v / wall if wall > 0.0 else 0.0
        bar = _BAR * max(int(round(frac * width)), 1)
        lines.append(f"  {s:<18} {v * 1e3:9.2f} ms {frac:6.1%} {bar}")
    return "\n".join(lines)


def _flight_probe():
    with _LOCK:
        tail = list(_TAIL)[-8:]
        return {"requests_completed": _COMPLETED[0],
                "archive_tail": tail,
                "state_totals": dict(_STATE_TOTALS)}


registry.register_pull_gauge(
    "mx_request_archive_depth",
    lambda: float(len(_TAIL) + len(_SAMPLED)),
    "completed anatomy records currently retained (always-keep tail "
    "ring + sampled-normal ring)")

tracing.register_flight_context("anatomy", _flight_probe)

# arm with the rest of the telemetry plane (the serving seams check the
# flag once per already-timed window — disarmed, one None-check)
if os.environ.get("MXNET_TELEMETRY", "0") not in ("0", ""):
    _ENABLED = True
