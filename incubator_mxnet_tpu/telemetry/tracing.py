"""Request/step-level span tracing + the crash flight recorder.

The registry (PR 2) answers *aggregate* questions — counters, histograms,
stage totals. This module answers the question the serving engine made
acute: "where did THIS request (or THIS step) spend its time?" It is a
Dapper-style tracer (Sigelman et al., 2010): every unit of work is a
**span** with a trace id shared by everything belonging to the same
request/step, a span id, and a parent id — so one slow TTFT p99 sample in
`bench_gpt_serve` decomposes into its queue wait, prefill, and per-step
decode segments instead of being one opaque number.

Design contract (same discipline as `stages.py`):

- **off** (`MXNET_TELEMETRY` unset, the default): every probe —
  ``span()``, ``open_span()``, ``event()``, ``annotate()`` — is one
  module-global ``_ENABLED`` check returning a shared no-op singleton.
  No allocation, no clock read, no lock. The measured off-path cost is
  <3% of one funnel op (`tests/test_tracing.py`).
- **on** (`enable()` or any truthy ``MXNET_TELEMETRY``): spans record
  ``perf_counter_ns`` durations and an epoch-µs start timestamp — the
  SAME clock base `profiler.py` rebases the XLA device trace onto, so
  host spans and device slices merge into one Chrome-trace/Perfetto
  timeline (`chrome_events()` / `tools/trace_timeline.py`).
- **host-side only**: spans are never created inside jitted bodies
  (lint FL008) and never captured by a trace — the serving engine's
  zero-steady-state-recompile guarantee is untouched.

Three ways to open a span:

- ``with tracing.span("serve.prefill", request=rid):`` — the blessed
  context-manager form (ambient: nested spans parent automatically via a
  thread-local stack);
- ``Tracer.start_span(...)`` — same semantics on an explicit tracer;
  MUST be used with ``with`` (lint FL008 flags a bare call);
- ``open_span(...)`` / ``Span.close()`` — explicit lifecycle for spans
  that cross function/thread boundaries (a serve request's root span is
  opened at submit on the client thread and closed at retire on the
  driver thread). Not ambient: an open_span never enters the TLS stack.

Finished spans land in per-thread ring buffers (bounded; merged on
read), so steady-state tracing is allocation-bounded and lock-free on
the hot path — exactly the registry's shard trick applied to spans.

Flight recorder: `flight_dump(reason)` snapshots the rings (recent
finished spans + still-open spans + orphan events + the armed chaos
schedule) into ``benchmark/flightrec_<reason>_<pid>.json`` so a crash
postmortem carries the last N spans of context. `ResilienceHandler`,
the serve driver thread, and the installed `sys.excepthook` all call
`maybe_flight_dump` — a no-op while tracing is off.
"""
from __future__ import annotations

import json
import os
import random
import sys
import threading
import time

from collections import deque

from .locks import tracked_lock

__all__ = ["Span", "Tracer", "enable", "disable", "is_enabled", "span",
           "open_span", "event", "annotate", "current_span",
           "current_trace_id", "new_trace_id", "finished_spans",
           "open_spans", "reset", "chrome_events", "chrome_trace",
           "dump_chrome", "flight_dump", "maybe_flight_dump",
           "register_flight_context", "RING_CAPACITY"]

RING_CAPACITY = 4096          # finished spans kept per writer thread
_FLIGHT_SPANS = 256           # most-recent spans a flight dump carries

_ENABLED = False
_LOCK = tracked_lock("telemetry.tracing", kind="lock")
_RINGS: list = []             # one deque per writer thread (merged reads)
_OPEN: dict = {}              # span_id -> still-open Span (flight recorder)
_ORPHAN_EVENTS: deque = deque(maxlen=512)   # events with no current span
_TLS = threading.local()
_IDS = random.Random()        # span/trace id entropy (host-side only)
_PREV_EXCEPTHOOK = None


def new_trace_id():
    """Fresh 64-bit correlation id (hex). One per request/step trace."""
    return f"{_IDS.getrandbits(64):016x}"


def _new_span_id():
    return f"{_IDS.getrandbits(32):08x}"


class _NullSpan:
    """Shared no-op span: what every probe returns while tracing is off
    (and what nested calls receive so call sites never branch)."""

    __slots__ = ()
    trace_id = None
    span_id = None
    name = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self

    def event(self, name, **attrs):  # noqa: ARG002
        return self

    def close(self, error=None):  # noqa: ARG002
        return self

    def __bool__(self):
        return False


_NULL_SPAN = _NullSpan()


class Span:
    """One timed unit of work. Created via `span()` (ambient context
    manager) or `open_span()` (explicit lifecycle); never construct
    directly."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs",
                 "events", "t0_us", "t0_ns", "dur_ns", "thread", "lane",
                 "_ambient")

    def __init__(self, name, trace_id, parent_id, attrs, lane, ambient):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self.events: list = []
        self.t0_us = time.time() * 1e6       # epoch µs: profiler clock base
        self.t0_ns = time.perf_counter_ns()  # monotonic: duration source
        self.dur_ns = None
        self.thread = threading.current_thread().name
        self.lane = lane
        self._ambient = ambient
        with _LOCK:
            _OPEN[self.span_id] = self

    # -- context-manager (ambient) form -------------------------------------

    def __enter__(self):
        if self._ambient:
            stack = getattr(_TLS, "stack", None)
            if stack is None:
                stack = _TLS.stack = []
            stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):  # noqa: ARG002
        if self._ambient:
            stack = getattr(_TLS, "stack", None)
            if stack and stack[-1] is self:
                stack.pop()
        self.close(error=exc)
        return False

    # -- shared surface ------------------------------------------------------

    @property
    def duration_s(self):
        """Span duration in seconds (None while still open)."""
        return None if self.dur_ns is None else self.dur_ns / 1e9

    def annotate(self, **attrs):
        self.attrs.update(attrs)
        return self

    def event(self, name, **attrs):
        """Point-in-time marker inside this span (Chrome 'instant')."""
        self.events.append((name, time.time() * 1e6, attrs))
        return self

    def close(self, error=None):
        """Stamp the duration and move the span to the finished ring.
        Idempotent (a double close keeps the first duration)."""
        if self.dur_ns is not None:
            return self
        self.dur_ns = time.perf_counter_ns() - self.t0_ns
        if error is not None:
            self.attrs.setdefault("error", type(error).__name__)
            self.attrs.setdefault("error_msg", str(error)[:200])
        with _LOCK:
            _OPEN.pop(self.span_id, None)
        ring = getattr(_TLS, "ring", None)
        if ring is None:
            ring = _TLS.ring = deque(maxlen=RING_CAPACITY)
            with _LOCK:
                _RINGS.append(ring)
        ring.append(self)
        return self

    def to_dict(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "ts_us": self.t0_us,
                "dur_us": None if self.dur_ns is None else self.dur_ns / 1e3,
                "thread": self.thread, "lane": self.lane,
                "attrs": dict(self.attrs),
                "events": [{"name": n, "ts_us": t, "attrs": a}
                           for n, t, a in self.events]}

    def __repr__(self):
        state = "open" if self.dur_ns is None \
            else f"{self.dur_ns / 1e3:.1f}us"
        return (f"<Span {self.name} trace={self.trace_id} "
                f"id={self.span_id} {state}>")


# ---------------------------------------------------------------------------
# probes (module surface — every call a dead branch while off)
# ---------------------------------------------------------------------------

def span(name, parent=None, trace_id=None, lane=None, **attrs):
    """Open an ambient span as a context manager::

        with tracing.span("estimator.step", step=i):
            ...

    Nested calls parent automatically (thread-local stack). `parent`
    (a Span) or `trace_id` override the ambient parent — that is how
    work done on another thread joins a request's trace. Returns the
    shared no-op span while tracing is off."""
    if not _ENABLED:
        return _NULL_SPAN
    return _make_span(name, parent, trace_id, lane, attrs, ambient=True)


def open_span(name, parent=None, trace_id=None, lane=None, **attrs):
    """Open a span with EXPLICIT lifecycle — the caller must `close()`
    it. Never enters the ambient stack (safe to close from another
    thread). Use for spans that outlive a lexical scope, e.g. a serve
    request's root span (submit → retire)."""
    if not _ENABLED:
        return _NULL_SPAN
    return _make_span(name, parent, trace_id, lane, attrs, ambient=False)


def _make_span(name, parent, trace_id, lane, attrs, ambient):
    if parent is None and trace_id is None:
        stack = getattr(_TLS, "stack", None)
        if stack:
            parent = stack[-1]
    if parent is not None and parent.trace_id is not None:
        trace_id = parent.trace_id
        parent_id = parent.span_id
        if lane is None:
            lane = parent.lane
    else:
        parent_id = None
        if trace_id is None:
            trace_id = new_trace_id()
    return Span(name, trace_id, parent_id, attrs, lane, ambient)


def event(name, **attrs):
    """Record a point-in-time event on the CURRENT ambient span (or the
    orphan ring when no span is open — flight dumps still carry it)."""
    if not _ENABLED:
        return
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1].event(name, **attrs)
    else:
        _ORPHAN_EVENTS.append((name, time.time() * 1e6, attrs))


def annotate(**attrs):
    """Attach attributes to the current ambient span (no-op without
    one — annotations never raise from instrumentation sites)."""
    if not _ENABLED:
        return
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack[-1].annotate(**attrs)


def current_span():
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def current_trace_id():
    s = current_span()
    return s.trace_id if s is not None else None


class Tracer:
    """Object façade over the module tracer (reference-style handle for
    code that wants an injectable tracer). `start_span` is the
    context-manager API — lint FL008 flags calling it without `with`."""

    def start_span(self, name, parent=None, trace_id=None, lane=None,
                   **attrs):
        return span(name, parent=parent, trace_id=trace_id, lane=lane,
                    **attrs)

    def open_span(self, name, parent=None, trace_id=None, lane=None,
                  **attrs):
        return open_span(name, parent=parent, trace_id=trace_id,
                         lane=lane, **attrs)

    @property
    def enabled(self):
        return _ENABLED


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def enable():
    """Arm span recording (idempotent) and install the crash excepthook
    so an unhandled exception leaves a flight-recorder dump behind."""
    global _ENABLED, _PREV_EXCEPTHOOK
    with _LOCK:
        already = _ENABLED
        _ENABLED = True
    if not already and _PREV_EXCEPTHOOK is None:
        _PREV_EXCEPTHOOK = sys.excepthook
        sys.excepthook = _crash_excepthook


def disable():
    """Disarm: every probe goes back to one `_ENABLED` check. Recorded
    spans stay readable until `reset()`."""
    global _ENABLED, _PREV_EXCEPTHOOK
    with _LOCK:
        _ENABLED = False
    if _PREV_EXCEPTHOOK is not None:
        sys.excepthook = _PREV_EXCEPTHOOK
        _PREV_EXCEPTHOOK = None


def is_enabled():
    return _ENABLED


def reset():
    """Drop every recorded span/event (tests)."""
    with _LOCK:
        rings = list(_RINGS)
        _OPEN.clear()
    for r in rings:
        r.clear()
    _ORPHAN_EVENTS.clear()


def finished_spans(trace_id=None):
    """Merged finished spans across all threads, start-ordered; filter
    by `trace_id` to reconstruct one request/step."""
    with _LOCK:
        rings = list(_RINGS)
    out = []
    for r in rings:
        out.extend(list(r))
    if trace_id is not None:
        out = [s for s in out if s.trace_id == trace_id]
    out.sort(key=lambda s: s.t0_us)
    return out


def open_spans():
    """Spans still open right now (crash context: the work that was
    in flight)."""
    with _LOCK:
        return list(_OPEN.values())


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export (shared clock base with profiler.py)
# ---------------------------------------------------------------------------

_SPAN_PID = 2                 # host op dispatch owns pid 0, device 1000+


def chrome_events(spans=None):
    """Chrome-trace events for `spans` (default: every finished span).

    Lanes: spans carrying a ``lane`` (e.g. serve requests get
    ``"req <id>"``) each get their own tid with a thread_name metadata
    row — one horizontal lane per request in Perfetto; unlaned spans
    share a lane per OS thread. Timestamps are epoch-µs (``time.time``),
    the same base `profiler._ingest_device_trace` rebases XLA device
    events onto — so the two sources line up in one timeline."""
    if spans is None:
        spans = finished_spans()
    lanes: dict = {}

    def lane_tid(s):
        key = s.lane if s.lane is not None else f"thread {s.thread}"
        if key not in lanes:
            lanes[key] = len(lanes) + 1
        return lanes[key]

    events = []
    for s in spans:
        tid = lane_tid(s)
        args = {"trace_id": s.trace_id, "span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        args.update({k: str(v)[:120] for k, v in s.attrs.items()})
        events.append({"name": s.name, "ph": "X", "pid": _SPAN_PID,
                       "tid": tid, "ts": s.t0_us,
                       "dur": (s.dur_ns or 0) / 1e3, "args": args})
        for name, ts, attrs in s.events:
            events.append({"name": name, "ph": "i", "s": "t",
                           "pid": _SPAN_PID, "tid": tid, "ts": ts,
                           "args": {k: str(v)[:120]
                                    for k, v in attrs.items()}})
    meta = [{"name": "process_name", "ph": "M", "pid": _SPAN_PID,
             "args": {"name": "host: spans"}}]
    for key, tid in lanes.items():
        meta.append({"name": "thread_name", "ph": "M", "pid": _SPAN_PID,
                     "tid": tid, "args": {"name": str(key)}})
    return meta + events


def chrome_trace(include_device=True, spans=None):
    """One Chrome-trace payload: host spans (+ their instant events)
    merged with the XLA device lanes `profiler.py` captured on the last
    `profiler.stop()`. Both sides share the epoch-µs clock base, so
    request spans sit directly above the device slices they caused."""
    events = chrome_events(spans)
    if include_device:
        from .. import profiler

        events = events + profiler.device_events()
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome(path, include_device=True):
    """Write `chrome_trace()` as JSON (open in Perfetto:
    https://ui.perfetto.dev → Open trace file). Returns the path."""
    with open(path, "w") as f:
        json.dump(chrome_trace(include_device=include_device), f)
    return path


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def _flight_dir():
    d = os.environ.get("MXNET_FLIGHTREC_DIR")
    if d:
        return d
    return "benchmark" if os.path.isdir("benchmark") else "."


_FLIGHT_CONTEXT = {}          # name -> probe() returning a JSON-able dict
_RANK_STAMP = None            # set by telemetry.fleet on multi-rank runs:
                              # rank-stamps default flightrec filenames so
                              # a shared dir keeps every rank's dump apart


def register_flight_context(name, probe):
    """Attach a subsystem state probe to every flight dump: ``probe()``
    returns a JSON-able dict (or None to skip — the weakly-bound-source
    idiom) snapshotted into ``payload["context"][name]`` at crash time.
    The serving gateway registers its queue/slot state here so a crash
    dump shows WHAT was queued where, not just which spans were open.
    Re-registering a name replaces the previous probe."""
    _FLIGHT_CONTEXT[str(name)] = probe


def _flight_context():
    out = {}
    for name, probe in list(_FLIGHT_CONTEXT.items()):
        try:
            state = probe()
        except Exception as e:  # noqa: FL006 — best-effort context, never mask the dump
            state = {"probe_error": f"{type(e).__name__}: {e}"[:200]}
        if state is not None:
            out[name] = state
    return out


def flight_dump(reason, exc=None, path=None):
    """Snapshot the last `_FLIGHT_SPANS` finished spans, every still-open
    span (the in-flight work at crash time), orphan events, and the armed
    chaos schedule into ``flightrec_<reason>_<pid>.json``. Returns the
    written path. The file is overwritten per (reason, pid) — bounded
    artifacts, the LAST crash wins."""
    spans = finished_spans()[-_FLIGHT_SPANS:]
    payload = {
        "reason": reason,
        "pid": os.getpid(),
        "wall_time_us": time.time() * 1e6,
        "error": None if exc is None else {
            "type": type(exc).__name__, "message": str(exc)[:500]},
        "open_spans": [s.to_dict() for s in open_spans()],
        "spans": [s.to_dict() for s in spans],
        "orphan_events": [{"name": n, "ts_us": t, "attrs": a}
                          for n, t, a in list(_ORPHAN_EVENTS)],
        "context": _flight_context(),
    }
    try:
        from ..fault.injection import schedule_info

        payload["fault_schedule"] = schedule_info()
    except Exception:  # noqa: FL006 — best-effort context, never mask the dump
        payload["fault_schedule"] = {}
    if path is None:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in str(reason))[:60]
        stamp = (f"rank{_RANK_STAMP:03d}_" if _RANK_STAMP is not None
                 else "")
        path = os.path.join(_flight_dir(),
                            f"flightrec_{safe}_{stamp}{os.getpid()}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, path)
    import logging

    logging.getLogger("incubator_mxnet_tpu.telemetry").warning(
        "flight recorder: dumped %d spans (+%d open) to %s (reason: %s)",
        len(spans), len(payload["open_spans"]), path, reason)
    return path


def maybe_flight_dump(reason, exc=None):
    """The hook form: dump only when tracing is armed (a disabled tracer
    has nothing to record and must stay zero-cost). Never raises — a
    broken dump must not mask the crash it documents."""
    if not _ENABLED:
        return None
    try:
        return flight_dump(reason, exc=exc)
    except Exception as e:
        from ..fault.retry import suppressed

        suppressed("tracing.flight_dump", e)
        return None


def _crash_excepthook(exc_type, exc, tb):
    maybe_flight_dump("crash", exc=exc)
    if _PREV_EXCEPTHOOK is not None:
        _PREV_EXCEPTHOOK(exc_type, exc, tb)
    else:  # pragma: no cover - excepthook replaced underneath us
        sys.__excepthook__(exc_type, exc, tb)
