"""SLO tracker: declarative service-level objectives over registry series.

The registry records what happened; this module says whether that is
*acceptable*. An :class:`SLO` binds an existing series to an objective —
"99% of TTFTs under 250 ms", "serve throughput ≥ 500 tokens/s", "queue
depth ≤ 64" — and every `evaluate()` computes:

- **compliance**: the fraction of good observations (latency SLOs read
  the histogram's bucket counts; throughput SLOs rate the counter delta
  between evaluations; gauge SLOs threshold the last value);
- **error-budget burn**: ``bad_fraction / (1 - target)`` — the standard
  SRE burn statistic. burn < 1 means the objective holds with budget to
  spare; burn ≥ 1 means the budget is exhausted and the SLO is violated.

Results surface three ways, loudest last:

1. gauges in the registry (Prometheus-scrapable, same pipeline as every
   other series): ``mx_slo_compliance{slo=...}``,
   ``mx_slo_error_budget_burn{slo=...}``, ``mx_slo_ok{slo=...}``;
2. `violations()` → the violated subset with numbers attached;
3. the health-monitor hook: `install_health_check()` registers the
   default tracker with `telemetry.monitor`, so `monitor.check()` — the
   call sites that already guard NaNs — ALSO raises `MXNetError` on a
   burned error budget. Observability that can't page is decoration.

Latency compliance is computed conservatively from histogram buckets:
observations are counted good only up to the largest bucket boundary
≤ threshold (a threshold between boundaries under-counts good, never
over-counts). Pick thresholds on bucket boundaries for exact math — the
default registry buckets are log-spaced 100 µs…2 min.
"""
from __future__ import annotations

import threading
import time

from .locks import tracked_lock

from . import registry

__all__ = ["SLO", "SLOTracker", "tracker", "latency", "throughput",
           "gauge_max", "evaluate", "violations", "check",
           "install_health_check", "serve_ttft", "serve_throughput",
           "step_time", "gateway_ttft"]


class SLO:
    """One objective. Subclasses implement `_measure()` returning
    ``(compliance, detail)`` where compliance ∈ [0, 1] or None (no data
    yet — not a violation)."""

    kind = "abstract"

    def __init__(self, name, series, target):
        self.name = str(name)
        self.series = str(series)
        self.target = float(target)
        if not 0.0 < self.target <= 1.0:
            raise ValueError(
                f"SLO {name!r}: target must be in (0, 1], got {target}")

    # -- evaluation ----------------------------------------------------------

    def evaluate(self):
        """Measure, publish the mx_slo_* gauges, return the result dict."""
        compliance, detail = self._measure()
        if compliance is None:
            burn = None
            ok = True                 # no data is not a violation
        else:
            budget = 1.0 - self.target
            bad = 1.0 - compliance
            if budget <= 0.0:         # target == 1.0: any badness burns ∞
                burn = 0.0 if bad <= 0.0 else float("inf")
            else:
                burn = bad / budget
            ok = burn < 1.0
        labels = {"slo": self.name}
        registry.gauge("mx_slo_compliance",
                       "good-observation fraction per SLO",
                       labels=labels).set(compliance)
        registry.gauge("mx_slo_error_budget_burn",
                       "bad fraction / allowed bad fraction (≥1 = violated)",
                       labels=labels).set(
                           None if burn is None else min(burn, 1e9))
        registry.gauge("mx_slo_ok", "1 while the error budget holds",
                       labels=labels).set(1 if ok else 0)
        return {"slo": self.name, "kind": self.kind, "series": self.series,
                "target": self.target, "compliance": compliance,
                "burn": burn, "ok": ok, "detail": detail}

    def _measure(self):  # pragma: no cover - abstract
        raise NotImplementedError


class LatencySLO(SLO):
    """`target` fraction of `series` (a histogram) observations must be
    ≤ `threshold_s`. ``labels`` selects ONE labeled series (e.g. the
    gateway's per-tier TTFT view) instead of the unlabeled aggregate."""

    kind = "latency"

    def __init__(self, name, series, threshold_s, target=0.99,
                 labels=None):
        super().__init__(name, series, target)
        self.threshold_s = float(threshold_s)
        self.labels = dict(labels) if labels else None

    def _measure(self):
        h = registry.histogram(self.series, labels=self.labels)
        snap = h.snapshot()
        total = snap["count"]
        if not total:
            return None, {"observations": 0}
        good = 0
        for b in sorted(snap["buckets"]):
            if b <= self.threshold_s:
                good += snap["buckets"][b]
        return good / total, {"observations": total, "good": good,
                              "threshold_s": self.threshold_s}


class ThroughputSLO(SLO):
    """Counter-rate objective: the `series` counter must advance at
    ≥ `min_rate`/s, measured between consecutive `evaluate()` calls.
    Compliance is the fraction of measured windows that met the rate
    (`target` of them must)."""

    kind = "throughput"

    def __init__(self, name, series, min_rate, target=0.99):
        super().__init__(name, series, target)
        self.min_rate = float(min_rate)
        self._last_value = None
        self._last_t = None
        self._windows = 0
        self._good_windows = 0

    def observe_window(self, now=None):
        """Advance one measurement window; returns the window's rate
        (None on the priming call)."""
        now = time.monotonic() if now is None else now
        value = registry.counter(self.series).value
        rate = None
        if self._last_t is not None and now > self._last_t:
            rate = (value - self._last_value) / (now - self._last_t)
            self._windows += 1
            if rate >= self.min_rate:
                self._good_windows += 1
        self._last_value = value
        self._last_t = now
        return rate

    def _measure(self):
        self.observe_window()
        if not self._windows:
            return None, {"windows": 0}
        return (self._good_windows / self._windows,
                {"windows": self._windows, "good": self._good_windows,
                 "min_rate": self.min_rate})


class GaugeSLO(SLO):
    """Gauge-threshold objective: the `series` gauge's last value must be
    ≤ `max_value` (windowed like ThroughputSLO: each evaluate() is one
    observation)."""

    kind = "gauge"

    def __init__(self, name, series, max_value, target=0.99):
        super().__init__(name, series, target)
        self.max_value = float(max_value)
        self._windows = 0
        self._good_windows = 0

    def _measure(self):
        v = registry.gauge(self.series).value
        if v is None:
            if not self._windows:
                return None, {"windows": 0}
        else:
            self._windows += 1
            if float(v) <= self.max_value:
                self._good_windows += 1
        return (self._good_windows / self._windows,
                {"windows": self._windows, "good": self._good_windows,
                 "max_value": self.max_value, "last": v})


class SLOTracker:
    """A set of SLOs evaluated together (the default module tracker is
    what the health hook and the MXNET_TELEMETRY_DUMP snapshot use)."""

    def __init__(self):
        self._slos: list = []
        self._lock = tracked_lock("telemetry.slo", kind="lock")

    def add(self, slo):
        with self._lock:
            if any(s.name == slo.name for s in self._slos):
                raise ValueError(f"SLO {slo.name!r} already registered")
            self._slos.append(slo)
        return slo

    def remove(self, name):
        with self._lock:
            self._slos = [s for s in self._slos if s.name != name]

    def clear(self):
        with self._lock:
            self._slos = []

    def slos(self):
        with self._lock:
            return list(self._slos)

    # -- constructors --------------------------------------------------------

    def latency(self, name, series, threshold_s, target=0.99, labels=None):
        return self.add(LatencySLO(name, series, threshold_s, target,
                                   labels=labels))

    def throughput(self, name, series, min_rate, target=0.99):
        return self.add(ThroughputSLO(name, series, min_rate, target))

    def gauge_max(self, name, series, max_value, target=0.99):
        return self.add(GaugeSLO(name, series, max_value, target))

    # -- evaluation ----------------------------------------------------------

    def evaluate(self):
        """Evaluate every SLO, refresh the mx_slo_* gauges, return the
        list of result dicts."""
        return [s.evaluate() for s in self.slos()]

    def violations(self):
        return [r for r in self.evaluate() if not r["ok"]]

    def check(self):
        """Loud form: raise `MXNetError` naming every SLO whose error
        budget is burned. The health-monitor hook routes here."""
        bad = self.violations()
        if bad:
            from ..base import MXNetError

            lines = [
                f"{r['slo']}: burn={r['burn']:.2f} "
                f"(compliance {r['compliance']:.4f} < target "
                f"{r['target']:.4f} over {r['series']})" for r in bad]
            raise MXNetError(
                "SLO error budget burned:\n  " + "\n  ".join(lines))


_DEFAULT = SLOTracker()


def tracker():
    """The process-default tracker (what the module-level helpers and
    the monitor health hook operate on)."""
    return _DEFAULT


def latency(name, series, threshold_s, target=0.99, labels=None):
    return _DEFAULT.latency(name, series, threshold_s, target,
                            labels=labels)


def throughput(name, series, min_rate, target=0.99):
    return _DEFAULT.throughput(name, series, min_rate, target)


def gauge_max(name, series, max_value, target=0.99):
    return _DEFAULT.gauge_max(name, series, max_value, target)


def evaluate():
    return _DEFAULT.evaluate()


def violations():
    return _DEFAULT.violations()


def check():
    return _DEFAULT.check()


def install_health_check():
    """Register the default tracker with `telemetry.monitor`: from now
    on `monitor.check()` raises on a burned SLO budget exactly like it
    raises on a pending NaN finding. Idempotent; returns the tracker."""
    from . import monitor

    monitor.add_health_check(_DEFAULT.check, name="slo")
    return _DEFAULT


# -- presets over the built-in series ---------------------------------------

def serve_ttft(threshold_s=0.25, target=0.99, name="serve_ttft"):
    """TTFT objective over the serving engine's histogram
    (`mx_serve_ttft_seconds`, SERVING.md)."""
    return _DEFAULT.latency(name, "mx_serve_ttft_seconds", threshold_s,
                            target)


def serve_throughput(min_tokens_s, target=0.9, name="serve_tokens_s"):
    """Decode-throughput objective over `mx_serve_tokens_total`."""
    return _DEFAULT.throughput(name, "mx_serve_tokens_total", min_tokens_s,
                               target)


def step_time(threshold_s, target=0.99, name="step_time"):
    """Train-step latency objective over `mx_step_time_seconds`."""
    return _DEFAULT.latency(name, "mx_step_time_seconds", threshold_s,
                            target)


def gateway_ttft(tier, threshold_s=0.5, target=0.99, name=None):
    """Per-tier TTFT objective over the gateway's tier-labeled TTFT view
    (``mx_serve_ttft_seconds{priority=<tier>}`` — gateway submit to
    first token, queue wait and preemptions included). The trace-replay
    acceptance gate (`tools/loadgen.py` + tests/test_gateway.py) holds
    the high tier to this one."""
    if name is None:
        name = f"gateway_ttft_{tier}"
    return _DEFAULT.latency(name, "mx_serve_ttft_seconds", threshold_s,
                            target, labels={"priority": str(tier)})
