"""XPlane roofline analyzer: bytes-moved vs device-time vs peak HBM
bandwidth, per step phase.

VERDICT r5 Weak #1: the claim that BERT seq-512 MFU ~0.49 is the XLA
memory-bound floor "lives in docstrings, not in any committed
measurement". This module turns the device trace `profiler.py` already
captures into the auditable per-phase table that claim needs
(`benchmark/seq512_roofline.md`; regenerate on-chip with
`python tools/funnel_profile.py --roofline`).

Inputs:

- ``trace_events``: chrome-trace events as `profiler.device_events()`
  returns them — complete (``ph=="X"``) events on ``/device:`` (TPU/GPU)
  or ``/host:`` (CPU XLA) lanes, plus the ``process_name`` metadata rows.
- ``mem_analysis``: optional `profiler.analyze_memory()` dict for the
  step program — its argument/output/temp bytes give the program-level
  traffic bound the per-event numbers are checked against.

Per-event bytes come from the XPlane stat args when present (XLA attaches
``bytes accessed`` / ``bytes_accessed`` to HLO events); events without a
bytes stat contribute device time only and the report states the coverage
fraction, so a thin trace reads as *unknown*, not as *fast*.
"""
from __future__ import annotations

import re

__all__ = ["analyze", "format_table", "write_report", "from_profiler",
           "PEAK_HBM_GBS", "DEFAULT_PHASES"]

# peak HBM bandwidth per chip generation, GB/s (vendor-published figures;
# pass peak_gbs= explicitly for other parts). CPU has no meaningful HBM
# roof — peak_fraction is omitted there.
PEAK_HBM_GBS = {"v3": 900.0, "v4": 1228.0, "v5e": 819.0, "v5p": 2765.0,
                "v6e": 1638.0}

# events that are tracing/runtime infrastructure, not HLO work: Python
# frame events ("$file:line fn" — the CPU host lane records the Python
# stack), thunk-executor/pjit wrappers, and the profiler's own frames.
# Excluded by default so the "other" phase means *unclassified ops*, not
# *the interpreter* (device lanes on TPU/GPU never carry these).
DEFAULT_EXCLUDE = (r"^\$|^thunkexecutor|^pjitfunction|^xlamodule|"
                   r"^tsl::|^proces|^program_interpreter")

# phase classification by HLO/op-name pattern, first match wins (order
# matters: fusions named after their root op land in the root's phase)
DEFAULT_PHASES = (
    ("matmul/conv", r"dot|conv|einsum|gemm|mxu"),
    ("attention", r"attention|softmax|flash"),
    ("norm/reduce", r"norm|reduce|variance"),
    ("rng/dropout", r"rng|dropout|random|threefry"),
    ("copy/layout", r"copy|transpose|bitcast|reshape|broadcast|concat|"
                    r"slice|pad|gather|scatter|tuple"),
    ("collectives", r"all-reduce|all-gather|reduce-scatter|collective|"
                    r"permute"),
    ("infeed/outfeed", r"infeed|outfeed|transfer"),
    ("fusion/elementwise", r"fusion|add|sub|mul|div|tanh|exp|log|gelu|"
                           r"relu|max|min|select|compare|convert"),
)


def _device_lane_pids(events):
    """pids of the device/runtime lanes (from process_name metadata rows).
    Empty when the trace carries no metadata (synthetic fixtures) — then
    every complete event is taken."""
    pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            lane = e.get("args", {}).get("name", "")
            if lane.startswith(("/device:", "/host:")):
                pids.add(e["pid"])
    return pids


def _event_bytes(e):
    """bytes accessed by one HLO event, or None when the trace has no
    byte accounting for it — delegates to `profiler.event_stat_bytes`,
    the single extraction path shared with `telemetry.kernels` (stat-name
    spellings across jax/XLA versions are fixed there, once)."""
    from .. import profiler

    return profiler.event_stat_bytes(e)


_WARNED_DEVICES: set = set()


def _warn_unknown_device(device):
    """One warning per unknown device name: peak_fraction silently
    missing from every row looks like a data bug, not a lookup miss."""
    key = device.lower()
    if key in _WARNED_DEVICES:
        return
    _WARNED_DEVICES.add(key)
    import logging

    logging.getLogger("incubator_mxnet_tpu.telemetry.roofline").warning(
        "roofline: no PEAK_HBM_GBS entry for device %r — peak_fraction "
        "will be omitted; known devices: %s (pass peak_gbs= explicitly "
        "to override)", device, ", ".join(sorted(PEAK_HBM_GBS)))


def _classify(name, compiled_phases):
    low = name.lower()
    for phase, rx in compiled_phases:
        if rx.search(low):
            return phase
    return "other"


def analyze(trace_events, mem_analysis=None, phases=None, peak_gbs=None,
            device=None, exclude=DEFAULT_EXCLUDE):
    """Per-phase roofline table.

    Returns ``{"rows": [...], "total": {...}, "meta": {...}}`` where each
    row is ``{phase, events, time_us, bytes, bytes_known_events,
    achieved_gbs, peak_fraction}``. ``achieved_gbs`` divides known bytes
    by that phase's FULL device time, so missing byte stats bias the
    number LOW (conservative for a "we are at the bandwidth floor"
    claim). ``peak_fraction`` needs ``peak_gbs`` (or a ``device`` key of
    `PEAK_HBM_GBS`, e.g. "v5e"). ``exclude`` drops non-HLO
    runtime/interpreter events (`DEFAULT_EXCLUDE`; pass None to keep
    everything)."""
    if peak_gbs is None and device is not None:
        peak_gbs = PEAK_HBM_GBS.get(str(device).lower())
        if peak_gbs is None:
            _warn_unknown_device(str(device))
    compiled = [(p, re.compile(rx)) for p, rx in (phases or DEFAULT_PHASES)]
    rx_excl = re.compile(exclude) if exclude else None
    lane_pids = _device_lane_pids(trace_events)
    agg = {}                     # phase -> [events, time_us, bytes, known]
    for e in trace_events:
        if e.get("ph") != "X":
            continue
        if lane_pids and e.get("pid") not in lane_pids:
            continue
        name = str(e.get("name", "?"))
        if rx_excl is not None and rx_excl.search(name.lower()):
            continue
        phase = _classify(name, compiled)
        row = agg.setdefault(phase, [0, 0.0, 0, 0])
        row[0] += 1
        row[1] += float(e.get("dur", 0.0))
        b = _event_bytes(e)
        if b is not None:
            row[2] += b
            row[3] += 1
    rows = []
    for phase, (n, us, nbytes, known) in agg.items():
        gbs = (nbytes / (us * 1e-6) / 1e9) if us > 0 and nbytes else 0.0
        rows.append({
            "phase": phase, "events": n, "time_us": us, "bytes": nbytes,
            "bytes_known_events": known, "achieved_gbs": gbs,
            "peak_fraction": (gbs / peak_gbs) if peak_gbs else None,
        })
    rows.sort(key=lambda r: -r["time_us"])
    tot_us = sum(r["time_us"] for r in rows)
    tot_b = sum(r["bytes"] for r in rows)
    tot_ev = sum(r["events"] for r in rows)
    tot_known = sum(r["bytes_known_events"] for r in rows)
    tot_gbs = (tot_b / (tot_us * 1e-6) / 1e9) if tot_us > 0 and tot_b else 0.0
    total = {"phase": "total", "events": tot_ev, "time_us": tot_us,
             "bytes": tot_b, "bytes_known_events": tot_known,
             "achieved_gbs": tot_gbs,
             "peak_fraction": (tot_gbs / peak_gbs) if peak_gbs else None}
    meta = {"peak_gbs": peak_gbs, "device": device,
            "bytes_coverage": (tot_known / tot_ev) if tot_ev else 0.0}
    if mem_analysis:
        meta["program_bytes"] = (
            mem_analysis.get("argument_size_in_bytes", 0)
            + mem_analysis.get("output_size_in_bytes", 0)
            + mem_analysis.get("temp_size_in_bytes", 0))
    return {"rows": rows, "total": total, "meta": meta}


def from_profiler(mem_analysis=None, **kwargs):
    """Analyze the device trace captured by the last `profiler.stop()`."""
    from .. import profiler

    return analyze(profiler.device_events(), mem_analysis=mem_analysis,
                   **kwargs)


def _fmt_bytes(n):
    if n >= 2**30:
        return f"{n / 2**30:.2f} GiB"
    if n >= 2**20:
        return f"{n / 2**20:.2f} MiB"
    if n >= 2**10:
        return f"{n / 2**10:.1f} KiB"
    return f"{n} B"


def format_table(analysis):
    """Markdown per-phase table of an `analyze()` result."""
    meta = analysis["meta"]
    has_peak = meta.get("peak_gbs") is not None
    hdr = "| phase | events | time µs | bytes | GB/s"
    sep = "|---|---:|---:|---:|---:"
    if has_peak:
        hdr += " | % of peak"
        sep += "|---:"
    lines = [hdr + " |", sep + "|"]
    for r in list(analysis["rows"]) + [analysis["total"]]:
        bold = "**" if r["phase"] == "total" else ""
        line = (f"| {bold}{r['phase']}{bold} | {r['events']} | "
                f"{r['time_us']:.1f} | {_fmt_bytes(r['bytes'])} | "
                f"{r['achieved_gbs']:.1f}")
        if has_peak:
            pf = r["peak_fraction"]
            line += f" | {pf * 100:.1f}%" if pf is not None else " | -"
        lines.append(line + " |")
    cov = meta.get("bytes_coverage", 0.0)
    lines.append("")
    lines.append(f"byte-stat coverage: {cov * 100:.0f}% of device events "
                 "(events without an XPlane bytes stat contribute time "
                 "only, biasing GB/s low)")
    if has_peak:
        lines.append(f"peak HBM bandwidth assumed: {meta['peak_gbs']:.0f} "
                     f"GB/s ({meta.get('device') or 'explicit'})")
    if "program_bytes" in meta:
        lines.append("program-level traffic bound (XLA buffer plan, "
                     "arg+out+temp): " + _fmt_bytes(meta["program_bytes"]))
    return "\n".join(lines)


def write_report(path, analysis, title, notes=()):
    """Commit an `analyze()` result as a markdown artifact."""
    parts = [f"# {title}", "", format_table(analysis), ""]
    for n in notes:
        parts.append(f"- {n}")
    with open(path, "w") as f:
        f.write("\n".join(parts) + "\n")
    return path
