"""Per-tenant / per-model cost ledger for the serving plane.

A millions-of-users gateway needs to answer "who is spending the
hardware": capacity planning, chargeback, and the autoscale advisor all
start from per-tenant demand curves, not aggregate throughput. This
module attributes four costs at the serving seams (the scheduler's
prefill/decode timing, the gateway's dispatch path):

- **tokens**            — ``mx_capacity_tokens_total{tenant=,model=}``
- **device-seconds**    — ``mx_capacity_device_seconds_total{tenant=,
  model=,phase=}`` with ``phase="prefill"`` (per-chunk, exact per-slot
  attribution) vs ``phase="decode"`` (one batched program per step,
  split evenly across the slots decoding in it);
- **KV page-seconds**   — ``mx_capacity_kv_page_seconds_total{tenant=,
  model=}``: resident pool pages × seconds, the HBM-occupancy integral
  (also mirrored as the serving view
  ``mx_serve_kv_page_seconds_total{tenant=}``);
- **queue-wait**        — ``mx_capacity_queue_wait_seconds_total{
  tenant=,model=}``: gateway submit → first dispatch.

`measured_wall_s()` accumulates the total timed serve wall (every
prefill/decode duration once, BEFORE per-tenant splitting) so the
ledger is self-auditing: per-tenant device-seconds must sum back to it
(the committed acceptance gate holds the difference under 5%).

Off-path contract: every ``charge_*`` is a dead branch
(``if not _ENABLED: return``) and the scheduler/gateway seams check the
module flag once per step before doing any timing — disarmed, the hot
path pays one attribute load. Arms with the rest of the telemetry
plane (``MXNET_TELEMETRY=1`` at import) or via `enable()`.

`ledger_report()` rolls the series into {tenant: {model: costs}};
`fleet.fleet_report()` aggregates the same series across ranks under
its ``"capacity"`` key.
"""
from __future__ import annotations

import os
import re

from . import registry
from .locks import tracked_lock

__all__ = ["enable", "disable", "is_enabled", "reset",
           "charge_tokens", "charge_device_seconds",
           "split_device_seconds", "charge_kv_page_seconds",
           "charge_queue_wait", "measured_wall_s", "ledger_report",
           "capacity_view"]

_ENABLED = False
_WALL_LOCK = tracked_lock("telemetry.capacity", kind="lock")
_WALL = [0.0]                 # total timed serve wall (pre-split)

_SERIES_RE = re.compile(r'^(mx_capacity_\w+)\{(.*)\}$')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def enable():
    global _ENABLED
    _ENABLED = True


def disable():
    global _ENABLED
    _ENABLED = False


def is_enabled():
    return _ENABLED


def reset():
    """Zero the wall accumulator (the mx_capacity_* series live in the
    registry and reset with `registry.reset()`)."""
    with _WALL_LOCK:
        _WALL[0] = 0.0


def _t(tenant):
    return str(tenant) if tenant else "anon"


def charge_tokens(tenant, model, n=1):
    """Attribute `n` generated tokens (gateway emit path)."""
    if not _ENABLED:
        return
    registry.counter(
        "mx_capacity_tokens_total",
        "generated tokens attributed per tenant and model",
        labels={"tenant": _t(tenant), "model": str(model)}).inc(n)


def charge_device_seconds(tenant, model, phase, seconds):
    """Attribute `seconds` of device time in `phase` ("prefill" /
    "decode") to one tenant. Callers that timed a BATCHED program over
    several tenants should use `split_device_seconds` instead (it also
    feeds the wall accumulator exactly once)."""
    if not _ENABLED:
        return
    registry.counter(
        "mx_capacity_device_seconds_total",
        "serve device-seconds attributed per tenant/model, split "
        "prefill vs decode",
        labels={"tenant": _t(tenant), "model": str(model),
                "phase": str(phase)}).inc(float(seconds))


def split_device_seconds(tenants, model, phase, seconds):
    """Split one timed program invocation of `seconds` evenly across
    `tenants` (one entry per participating slot — multiplicity is the
    weight) and add `seconds` ONCE to the measured-wall accumulator.
    An empty tenant list still counts toward the wall (the time was
    spent) under the "anon" tenant."""
    if not _ENABLED:
        return
    seconds = float(seconds)
    with _WALL_LOCK:
        _WALL[0] += seconds
    tenants = list(tenants) or [None]
    share = seconds / len(tenants)
    for tenant in tenants:
        charge_device_seconds(tenant, model, phase, share)


def charge_kv_page_seconds(tenant, model, page_seconds):
    """Attribute resident-KV-page × seconds (HBM occupancy integral).
    Also feeds the per-tenant serving view
    ``mx_serve_kv_page_seconds_total{tenant=}``."""
    if not _ENABLED:
        return
    page_seconds = float(page_seconds)
    tenant = _t(tenant)
    registry.counter(
        "mx_capacity_kv_page_seconds_total",
        "resident KV pool pages x seconds per tenant/model",
        labels={"tenant": tenant, "model": str(model)}).inc(page_seconds)
    registry.counter(
        "mx_serve_kv_page_seconds_total",
        "resident KV pool pages x seconds per tenant (serving view of "
        "the capacity ledger)",
        labels={"tenant": tenant}).inc(page_seconds)


def charge_queue_wait(tenant, model, seconds):
    """Attribute gateway queue wait (submit → first dispatch)."""
    if not _ENABLED:
        return
    registry.counter(
        "mx_capacity_queue_wait_seconds_total",
        "gateway queue wait (submit to first dispatch) per tenant/model",
        labels={"tenant": _t(tenant), "model": str(model)}).inc(
            float(seconds))


def measured_wall_s():
    """Total timed serve wall accumulated by `split_device_seconds`
    (the per-tenant device-seconds must sum back to this)."""
    with _WALL_LOCK:
        return _WALL[0]


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------

def _parse(series_key):
    m = _SERIES_RE.match(series_key)
    if m is None:
        return None, {}
    return m.group(1), dict(_LABEL_RE.findall(m.group(2)))


def capacity_view(snapshot):
    """Roll one registry snapshot (``registry.report()``-shaped dict)
    into {tenant: {model: {tokens, device_s: {phase: s}, kv_page_s,
    queue_wait_s}}} — shared by `ledger_report` and the fleet rollup."""
    out = {}
    for key, info in snapshot.items():
        base, labels = _parse(key)
        if base is None:
            continue
        v = info.get("value") if isinstance(info, dict) else info
        if v is None:
            continue
        tenant = labels.get("tenant", "anon")
        model = labels.get("model", "?")
        row = out.setdefault(tenant, {}).setdefault(
            model, {"tokens": 0, "device_s": {}, "kv_page_s": 0.0,
                    "queue_wait_s": 0.0})
        if base == "mx_capacity_tokens_total":
            row["tokens"] += int(v)
        elif base == "mx_capacity_device_seconds_total":
            phase = labels.get("phase", "?")
            row["device_s"][phase] = row["device_s"].get(phase, 0.0) \
                + float(v)
        elif base == "mx_capacity_kv_page_seconds_total":
            row["kv_page_s"] += float(v)
        elif base == "mx_capacity_queue_wait_seconds_total":
            row["queue_wait_s"] += float(v)
    return out


def ledger_report():
    """The cost ledger as a dict: per-tenant/per-model rows plus the
    wall audit (device-second sum vs `measured_wall_s`)."""
    view = capacity_view(registry.report())
    device_sum = sum(s for t in view.values() for m in t.values()
                     for s in m["device_s"].values())
    return {"tenants": view, "device_seconds_sum": device_sum,
            "measured_wall_s": measured_wall_s()}


# arm with the rest of the telemetry plane (cheap counter incs at the
# serving seams — the <3% disarmed gate measures the flag checks)
if os.environ.get("MXNET_TELEMETRY", "0") not in ("0", ""):
    _ENABLED = True
