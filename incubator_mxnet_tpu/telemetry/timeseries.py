"""Time-series history layer over the metrics registry.

Every observatory below this one reports *point-in-time* snapshots —
`registry.report()` says what the queue depth and error-budget burn are
**now**, but a controller (the burn-rate alerter, the autoscale
advisor, a future actuating autoscaler) needs *windowed* signals:
rates, deltas, percentiles and threshold-fractions **over time**. This
module retains that history: armed, a fixed-interval sampler walks the
registry and appends every series' current value to a bounded ring —
one ring per series, including labeled views, pull gauges, and the
``:count`` / ``:sum`` sub-series it derives from each histogram — and
windowed queries read the rings:

- ``history(series, window_s)``      — raw ``[(t, v), ...]`` samples;
- ``rate(series, window_s)``         — per-second counter increase,
  counter-reset aware (a restarted process's counter drop is treated
  as a reset, not a negative rate — the Prometheus convention);
- ``delta(series, window_s)``        — last minus first value;
- ``avg_over_time`` / ``percentile_over_time`` — gauge aggregation
  (nearest-rank percentile, same convention as `tools/loadgen.py`);
- ``window_frac(series, window_s, pred)`` — fraction of samples in the
  window satisfying a predicate ("how long was occupancy above 0.85?").

Off-path contract (the `telemetry/locks.py` dead-branch discipline):
disarmed there is **no state, no thread, and no hot-path hook** — the
layer is pull-based, so the serving/training hot paths never see it at
all; off-path cost is zero by construction (the committed <3% gate in
tests measures the armed-module-imported case anyway). Arm with
``MXNET_TS_INTERVAL=<seconds>`` at import (spawns the daemon sampler
thread) or call `enable()`; tests and the dryrun drive deterministic
virtual-time histories via ``enable(thread=False)`` +
``sample_now(now=...)``.

Knobs: ``MXNET_TS_INTERVAL`` (sample period seconds, default 1.0),
``MXNET_TS_SAMPLES`` (ring capacity per series, default 512 — bounded
memory: capacity × series count floats, oldest overwritten).

All timestamps are ``time.monotonic()`` (or the caller's virtual
``now``) — wall-clock ``time.time()`` in a duration is lint FL019.
"""
from __future__ import annotations

import os
import threading
import time

from . import registry
from .locks import tracked_lock

__all__ = ["enable", "disable", "is_enabled", "reset", "sample_now",
           "history", "rate", "delta", "avg_over_time",
           "percentile_over_time", "window_frac", "series_names",
           "last", "sample_count", "DEFAULT_INTERVAL_S",
           "DEFAULT_SAMPLES"]

DEFAULT_INTERVAL_S = 1.0
DEFAULT_SAMPLES = 512

_ENABLED = False
_STATE = None                 # _Store while armed (survives disable()
                              # for post-run queries; reset() clears it)


class _Ring:
    """Bounded (t, value) ring: preallocated arrays, oldest overwritten."""

    __slots__ = ("cap", "ts", "vals", "n", "i", "kind")

    def __init__(self, cap, kind):
        self.cap = cap
        self.ts = [0.0] * cap
        self.vals = [0.0] * cap
        self.n = 0                # valid samples (≤ cap)
        self.i = 0                # next write index
        self.kind = kind          # "counter" | "gauge"

    def push(self, t, v):
        self.ts[self.i] = t
        self.vals[self.i] = v
        self.i = (self.i + 1) % self.cap
        if self.n < self.cap:
            self.n += 1

    def items(self):
        """Oldest→newest [(t, v), ...]."""
        if self.n < self.cap:
            return list(zip(self.ts[:self.n], self.vals[:self.n]))
        i = self.i
        return list(zip(self.ts[i:] + self.ts[:i],
                        self.vals[i:] + self.vals[:i]))


class _Store:
    __slots__ = ("interval", "samples", "rings", "lock", "thread",
                 "stop", "ticks")

    def __init__(self, interval, samples):
        self.interval = interval
        self.samples = samples
        self.rings = {}           # series key -> _Ring
        self.lock = tracked_lock("telemetry.timeseries", kind="lock")
        self.thread = None
        self.stop = None
        self.ticks = 0


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_samples():
    try:
        n = int(os.environ.get("MXNET_TS_SAMPLES", "") or DEFAULT_SAMPLES)
    except ValueError:
        n = DEFAULT_SAMPLES
    return max(2, n)


def enable(interval_s=None, samples=None, thread=True):
    """Arm the history layer. ``interval_s``/``samples`` default to the
    ``MXNET_TS_INTERVAL`` / ``MXNET_TS_SAMPLES`` knobs; ``thread=False``
    skips the daemon sampler (tests/dryrun drive `sample_now` with
    virtual timestamps instead). Idempotent; re-arming with a live
    sampler keeps the existing rings."""
    global _ENABLED, _STATE
    if interval_s is None:
        interval_s = _env_float("MXNET_TS_INTERVAL", DEFAULT_INTERVAL_S)
    interval_s = max(float(interval_s), 1e-3)
    if samples is None:
        samples = _env_samples()
    if _STATE is None:
        _STATE = _Store(interval_s, int(samples))
    _ENABLED = True
    st = _STATE
    if thread and st.thread is None:
        stop = threading.Event()

        def _loop():
            while not stop.wait(st.interval):
                try:
                    sample_now()
                except Exception:   # noqa: FL006 - sampler must survive
                    # a mid-teardown registry race; the next tick retries
                    pass
        t = threading.Thread(target=_loop, name="mx-timeseries-sampler",
                             daemon=True)
        st.stop = stop
        st.thread = t
        t.start()
    return st.interval, st.samples


def disable():
    """Stop sampling (the rings stay queryable until `reset()`)."""
    global _ENABLED
    _ENABLED = False
    st = _STATE
    if st is not None and st.stop is not None:
        st.stop.set()
        if st.thread is not None:
            st.thread.join(timeout=2.0)
        st.thread = None
        st.stop = None


def is_enabled():
    return _ENABLED


def reset():
    """Drop every ring and the sampler (tests)."""
    global _STATE
    disable()
    _STATE = None


def sample_now(now=None):
    """Take one sample of every registry series (the sampler thread's
    tick, also the deterministic manual tick — pass a virtual ``now``
    to build wall-clock-free histories). Histograms contribute
    ``<series>:count`` and ``<series>:sum`` counter-kind sub-series
    (windowed latency math wants both). Returns the number of series
    sampled, 0 while disarmed."""
    st = _STATE
    if st is None or not _ENABLED:
        return 0
    if now is None:
        now = time.monotonic()
    else:
        now = float(now)
    rep = registry.report()
    pushed = 0
    with st.lock:
        for key, info in rep.items():
            kind = info.get("type")
            if kind == "histogram":
                for suffix, v in ((":count", info.get("count", 0)),
                                  (":sum", info.get("sum", 0.0))):
                    ring = st.rings.get(key + suffix)
                    if ring is None:
                        ring = _Ring(st.samples, "counter")
                        st.rings[key + suffix] = ring
                    ring.push(now, float(v))
                    pushed += 1
                continue
            v = info.get("value")
            if v is None:
                continue
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            ring = st.rings.get(key)
            if ring is None:
                ring = _Ring(st.samples,
                             "counter" if kind == "counter" else "gauge")
                st.rings[key] = ring
            ring.push(now, v)
            pushed += 1
        st.ticks += 1
    return pushed


# ---------------------------------------------------------------------------
# windowed queries (every one returns None on no data / unknown series)
# ---------------------------------------------------------------------------

def _window(series, window_s, now):
    """Samples of `series` in the trailing window, oldest→newest, or
    None when the layer is cold or the series unknown."""
    st = _STATE
    if st is None:
        return None
    with st.lock:
        ring = st.rings.get(series)
        if ring is None:
            return None
        items = ring.items()
    if not items:
        return None
    if window_s is None:
        return items
    if now is None:
        now = items[-1][0]
    lo = now - float(window_s)
    return [(t, v) for t, v in items if t >= lo]


def history(series, window_s=None, now=None):
    """Raw [(t, value), ...] samples (trailing ``window_s``, or the
    whole ring). None for an unknown series."""
    return _window(series, window_s, now)


def last(series):
    """Most recent (t, value) sample, or None."""
    items = _window(series, None, None)
    return items[-1] if items else None


def delta(series, window_s, now=None):
    """Last minus first sampled value over the window (gauge-style;
    for counters across a reset prefer `rate`). None under 2 samples."""
    items = _window(series, window_s, now)
    if not items or len(items) < 2:
        return None
    return items[-1][1] - items[0][1]


def rate(series, window_s, now=None):
    """Per-second increase of a counter-kind series over the window.
    Counter-reset aware: a sample LOWER than its predecessor means the
    counter restarted from zero, so the new value is the increase since
    the reset (the Prometheus ``rate()`` convention). None under 2
    samples or a zero-length span."""
    items = _window(series, window_s, now)
    if not items or len(items) < 2:
        return None
    span = items[-1][0] - items[0][0]
    if span <= 0:
        return None
    inc = 0.0
    prev = items[0][1]
    for _, v in items[1:]:
        inc += v - prev if v >= prev else v
        prev = v
    return inc / span


def avg_over_time(series, window_s, now=None):
    """Mean of the sampled values in the window. None on no samples."""
    items = _window(series, window_s, now)
    if not items:
        return None
    return sum(v for _, v in items) / len(items)


def percentile_over_time(series, q, window_s, now=None):
    """Nearest-rank percentile (q in [0, 100]) of the sampled values in
    the window — same convention as `tools/loadgen.percentile`."""
    items = _window(series, window_s, now)
    if not items:
        return None
    xs = sorted(v for _, v in items)
    i = min(len(xs) - 1, max(0, int(round(q / 100.0 * (len(xs) - 1)))))
    return xs[i]


def window_frac(series, window_s, pred, now=None):
    """Fraction of samples in the window for which ``pred(value)`` is
    true — "how long was occupancy above 0.85?". None on no samples."""
    items = _window(series, window_s, now)
    if not items:
        return None
    return sum(1 for _, v in items if pred(v)) / len(items)


def series_names(prefix=None):
    """Sampled series keys (optionally filtered by prefix), sorted."""
    st = _STATE
    if st is None:
        return []
    with st.lock:
        names = list(st.rings)
    if prefix is not None:
        names = [n for n in names if n.startswith(prefix)]
    return sorted(names)


def sample_count():
    """Sampler ticks taken since arming (0 while disarmed)."""
    st = _STATE
    return 0 if st is None else st.ticks


# self-arm: MXNET_TS_INTERVAL opts into history retention at import
# (the background sampler is a standing thread, so plain
# MXNET_TELEMETRY=1 does NOT arm this layer — it is its own knob)
if os.environ.get("MXNET_TS_INTERVAL", "") not in ("", "0"):
    enable()
