"""Process-wide structured-metrics registry (reference capability:
`src/profiler/profiler.h` aggregate stats + the vital counters the C++
engine keeps; here a Prometheus-shaped registry the whole framework and
user code share).

Design constraints (VERDICT r5 Weak #3/#4 — metrics nobody owns drift):

- **lock-free fast path**: every metric keeps one mutable cell per thread
  (`threading.local`), appended to the metric's cell list under the
  registry lock exactly once per (metric, thread). `inc()`/`observe()`
  touch only the calling thread's cell — no lock, no allocation after the
  first call from a thread. Readers merge the shards on demand, so reads
  are O(threads) and writes are O(1).
- **pull-based built-ins**: series whose source of truth lives elsewhere
  (jit-cache hit/miss counts owned by `ndarray.jit_cache_info()`) are
  registered as *collect callbacks* so the hot path pays nothing here.

Built-in series (all `mx_`-prefixed):

==============================  ===========  ==============================
``mx_step_time_seconds``        histogram    train-step latency (fed by the
                                             estimator ``TelemetryHandler``
                                             and any caller of ``step()``)
``mx_examples_total``           counter      examples processed
``mx_jit_compile_seconds``      histogram    first-call (trace+compile)
                                             latency per program, labeled
                                             ``program=<name>`` — fed from
                                             `ndarray._cached_jit` and
                                             `gluon.block._CachedGraph`
``mx_jit_cache_hits_total``     gauge(pull)  eager op-call jit cache hits
``mx_jit_cache_misses_total``   gauge(pull)  eager op-call jit cache misses
``mx_h2d_bytes_total``          counter      host->device transfer bytes
                                             observed at the NDArray inlet
==============================  ===========  ==============================

Subsystem-owned series registered elsewhere but part of the same
contract: the serving engine (`serve/scheduler.py`, SERVING.md) owns
``mx_serve_ttft_seconds`` / ``mx_serve_tokens_total`` /
``mx_serve_queue_depth`` / ``mx_serve_slot_occupancy`` /
``mx_serve_evictions_total``, and the decode path owns
``mx_decode_bucket_pad_tokens_total`` (pad-to-bucket waste,
`models/decoding.py`).

`report()` -> plain dict; `dump(path)` -> JSON file; `exposition()` ->
Prometheus text format for scraping.
"""
from __future__ import annotations

import json
import re
import threading

__all__ = ["counter", "gauge", "histogram", "report", "dump", "exposition",
           "reset", "step", "Counter", "Gauge", "Histogram",
           "arm_textfile_dump", "stop_textfile_dump",
           "STEP_TIME", "EXAMPLES", "JIT_COMPILE", "H2D_BYTES"]

_LOCK = threading.Lock()  # noqa: FL018 - the metric cells back the tracked-lock telemetry itself
_METRICS: dict = {}          # (name, labels frozenset) -> metric
_COLLECTORS: list = []       # callables returning {series name: value}
_PULL_HELP: dict = {}        # pull-gauge base name -> HELP text

# step-time buckets: 100µs .. ~2min in roughly-log steps (seconds)
_DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0, 120.0)


def _series_key(name, labels):
    return (name, tuple(sorted(labels.items())) if labels else ())


def _label_str(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


# parses the label suffix a collector bakes into its series keys
# (built by _label_str, so values never contain an unescaped quote)
_PULL_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


class _Metric:
    """Shared thread-local-shard machinery. Subclasses define the cell
    layout (`_new_cell`) and the merge (`_merge`)."""

    kind = "untyped"

    def __init__(self, name, help="", labels=()):
        self.name = name
        self.help = help
        self.labels = labels
        self._cells: list = []            # one cell per writer thread
        self._local = threading.local()

    def _cell(self):
        # fast path: one attribute lookup; miss only on a thread's first
        # write to this metric
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = self._new_cell()
            self._local.cell = cell
            with _LOCK:
                self._cells.append(cell)
        return cell

    def snapshot(self):
        with _LOCK:
            cells = list(self._cells)
        return self._merge(cells)


class Counter(_Metric):
    kind = "counter"

    def _new_cell(self):
        return [0]

    def inc(self, n=1):
        self._cell()[0] += n

    def _merge(self, cells):
        return sum(c[0] for c in cells)

    @property
    def value(self):
        return self.snapshot()


class Gauge(_Metric):
    """Last-write-wins gauge. Writes stamp a process-wide sequence number
    so the merged value is the most recent write across threads."""

    kind = "gauge"
    _seq = [0]

    def _new_cell(self):
        return [None, -1]                 # value, seq

    def set(self, v):
        cell = self._cell()
        with _LOCK:
            Gauge._seq[0] += 1
            seq = Gauge._seq[0]
        cell[0] = v
        cell[1] = seq

    def _merge(self, cells):
        best, best_seq = None, -1
        for v, seq in cells:
            if seq > best_seq:
                best, best_seq = v, seq
        return best

    @property
    def value(self):
        return self.snapshot()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=None):
        self.buckets = tuple(buckets) if buckets else _DEFAULT_BUCKETS
        super().__init__(name, help, labels)

    def _new_cell(self):
        # bucket counts (+inf last), sum, count, min, max
        return [[0] * (len(self.buckets) + 1), 0.0, 0,
                float("inf"), float("-inf")]

    def observe(self, v):
        cell = self._cell()
        counts = cell[0]
        for i, b in enumerate(self.buckets):
            if v <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        cell[1] += v
        cell[2] += 1
        if v < cell[3]:
            cell[3] = v
        if v > cell[4]:
            cell[4] = v

    def _merge(self, cells):
        counts = [0] * (len(self.buckets) + 1)
        total, n = 0.0, 0
        mn, mx = float("inf"), float("-inf")
        for c_counts, c_sum, c_n, c_mn, c_mx in cells:
            for i, c in enumerate(c_counts):
                counts[i] += c
            total += c_sum
            n += c_n
            mn = min(mn, c_mn)
            mx = max(mx, c_mx)
        return {"buckets": dict(zip(self.buckets, counts[:-1])),
                "inf": counts[-1], "sum": total, "count": n,
                "min": (None if n == 0 else mn),
                "max": (None if n == 0 else mx)}


def _get_or_make(cls, name, help, labels, **kwargs):
    labels = labels or {}
    key = _series_key(name, labels)
    with _LOCK:
        m = _METRICS.get(key)
        if m is None:
            m = cls(name, help=help,
                    labels=tuple(sorted(labels.items())), **kwargs)
            _METRICS[key] = m
        elif not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}")
        elif kwargs.get("buckets") is not None \
                and tuple(kwargs["buckets"]) != m.buckets:
            # an explicit spec that silently loses to an earlier
            # registration corrupts every downstream bucket read
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}, conflicting with {tuple(kwargs['buckets'])}")
    return m


def counter(name, help="", labels=None):
    return _get_or_make(Counter, name, help, labels)


def gauge(name, help="", labels=None):
    return _get_or_make(Gauge, name, help, labels)


def histogram(name, help="", labels=None, buckets=None):
    return _get_or_make(Histogram, name, help, labels, buckets=buckets)


def register_collector(fn):
    """Register a pull-mode callback returning {series name: number} —
    for series whose counters live in another module's hot path."""
    with _LOCK:
        _COLLECTORS.append(fn)
    return fn


def register_pull_gauge(name, probe, help="", labels=None):
    """A gauge-typed series whose value is pulled from ``probe()`` at
    every `report()` / `exposition()` — for occupancy-style series whose
    source of truth is live host state in another subsystem (e.g.
    ``mx_serve_page_occupancy`` over the serving KV page allocator), so
    readers always see the current value instead of the last pushed one.

    ``labels`` attaches a fixed label set to the series (one collector
    per label combination — e.g. ``mx_gateway_queue_depth{priority=}``
    registers once per tier). ``probe`` returns a number, or None to
    omit the series this round (the idiom for weakly-bound sources that
    may be gone). Collector-only on purpose: registering a push `Gauge`
    under the same name would emit the series twice per exposition.
    ``help`` becomes the family's ``# HELP`` line in `exposition()`."""
    series = name + _label_str(tuple(sorted(labels.items()))
                               if labels else ())
    if help:
        with _LOCK:
            _PULL_HELP.setdefault(name, str(help))

    def _pull():
        v = probe()
        if v is None:
            return {}
        return {series: float(v)}

    _pull.__name__ = f"pull_gauge[{series}]"
    register_collector(_pull)
    return _pull


# ---------------------------------------------------------------------------
# built-in series
# ---------------------------------------------------------------------------

STEP_TIME = histogram("mx_step_time_seconds", "train-step wall time")
EXAMPLES = counter("mx_examples_total", "examples processed")
H2D_BYTES = counter("mx_h2d_bytes_total",
                    "host->device transfer bytes at the NDArray inlet")
# JIT_COMPILE is the unlabeled aggregate; per-program series are created
# on demand by observe_compile()
JIT_COMPILE = histogram("mx_jit_compile_seconds",
                        "trace+compile wall time per program")


def observe_compile(program, seconds):
    """Feed the jit-compile series (called from the jax.jit call sites in
    `ndarray/ndarray.py` and `gluon/block.py` on a program's first run)."""
    JIT_COMPILE.observe(seconds)
    histogram("mx_jit_compile_seconds", "trace+compile wall time",
              labels={"program": str(program)[:80]}).observe(seconds)


def add_h2d_bytes(n):
    H2D_BYTES.inc(n)


def step(seconds, examples=0):
    """Record one train step: latency + examples (examples/s is derivable
    as rate(mx_examples_total) or sum/count of the step histogram)."""
    STEP_TIME.observe(seconds)
    if examples:
        EXAMPLES.inc(examples)


@register_collector
def _jit_cache_collector():
    import sys

    nd = sys.modules.get("incubator_mxnet_tpu.ndarray.ndarray")
    if nd is None:
        return {}
    info = nd.jit_cache_info()
    return {"mx_jit_cache_hits_total": info.get("hits", 0),
            "mx_jit_cache_misses_total": info.get("misses", 0)}


# ---------------------------------------------------------------------------
# reading
# ---------------------------------------------------------------------------

def _log_collector_failure(fn, exc):
    """A broken pull-collector drops its series from reports — that must
    be visible (classified logging; FL006 discipline), not a blind skip."""
    import logging

    logging.getLogger("incubator_mxnet_tpu.telemetry").warning(
        "registry collector %r failed: %s: %s",
        getattr(fn, "__name__", fn), type(exc).__name__, exc)


def report():
    """Merged view of every series: {series name: {type, value, ...}}."""
    with _LOCK:
        metrics = list(_METRICS.values())
        collectors = list(_COLLECTORS)
    out = {}
    for m in metrics:
        key = m.name + _label_str(m.labels)
        snap = m.snapshot()
        if m.kind == "histogram":
            mean = snap["sum"] / snap["count"] if snap["count"] else None
            out[key] = {"type": "histogram", "count": snap["count"],
                        "sum": snap["sum"], "mean": mean,
                        "min": snap["min"], "max": snap["max"]}
        else:
            out[key] = {"type": m.kind, "value": snap}
    for fn in collectors:
        try:
            for name, v in (fn() or {}).items():
                out[name] = {"type": "gauge", "value": v}
        except Exception as e:
            _log_collector_failure(fn, e)
            continue
    return out


def dump(path):
    """Write `report()` as JSON; returns the path."""
    with open(path, "w") as f:
        json.dump(report(), f, indent=2, sort_keys=True)
    return path


def _escape_help(text):
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _escaped_label_str(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


def exposition():
    """Prometheus text exposition (format v0.0.4) of every series.

    Grammar-compliant for a stock Prometheus scraper (the
    ``MXNET_TELEMETRY_DUMP`` textfile rides this): one contiguous
    family per base name — ``# HELP`` (escaped: ``\\`` and newline),
    ``# TYPE``, then every sample of that family, label values escaped
    (``\\``, ``"``, newline) and histograms expanded to cumulative
    ``_bucket{le=}`` rows (closing ``le="+Inf"``) plus ``_sum`` /
    ``_count``. Pull gauges registered with a ``help`` get a family
    HELP like push metrics."""
    with _LOCK:
        metrics = list(_METRICS.values())
        collectors = list(_COLLECTORS)
        pull_help = dict(_PULL_HELP)
    # families keyed by base name, insertion-ordered: every sample of a
    # family is emitted under ONE # TYPE header (the text-format
    # grammar requires families to be contiguous)
    families = {}                  # base -> {"kind", "help", "rows": []}

    def family(base, kind, help=""):
        fam = families.get(base)
        if fam is None:
            fam = {"kind": kind, "help": help, "rows": []}
            families[base] = fam
        elif help and not fam["help"]:
            fam["help"] = help
        return fam

    for m in metrics:
        fam = family(m.name, m.kind, m.help)
        ls = _escaped_label_str(m.labels)
        snap = m.snapshot()
        if m.kind == "histogram":
            cum = 0
            base_labels = list(m.labels)
            for b, c in snap["buckets"].items():
                cum += c
                bl = _escaped_label_str(tuple(sorted(
                    base_labels + [("le", repr(float(b)))])))
                fam["rows"].append((f"{m.name}_bucket", bl, cum))
            bl = _escaped_label_str(tuple(sorted(
                base_labels + [("le", "+Inf")])))
            fam["rows"].append((f"{m.name}_bucket", bl,
                                cum + snap["inf"]))
            fam["rows"].append((f"{m.name}_sum", ls, snap["sum"]))
            fam["rows"].append((f"{m.name}_count", ls, snap["count"]))
        else:
            v = snap
            fam["rows"].append((m.name, ls, 0 if v is None else v))
    for fn in collectors:
        try:
            out = fn() or {}
        except Exception as e:
            _log_collector_failure(fn, e)
            continue
        for name, v in out.items():
            # collector keys may carry a baked-in label suffix; the
            # family is the base name (labels re-escaped for the text
            # format — report() keys keep the raw form)
            base, sep, label_part = name.partition("{")
            fam = family(base, "gauge", pull_help.get(base, ""))
            if sep:
                pairs = tuple(
                    (k, val) for k, val in _PULL_LABEL_RE.findall(
                        label_part[:-1] if label_part.endswith("}")
                        else label_part))
                ls = _escaped_label_str(pairs)
            else:
                ls = ""
            fam["rows"].append((base, ls, v))
    lines = []
    for base, fam in families.items():
        if fam["help"]:
            lines.append(f"# HELP {base} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {base} {fam['kind']}")
        for name, ls, v in fam["rows"]:
            lines.append(f"{name}{ls} {v}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# MXNET_TELEMETRY_DUMP — periodic Prometheus-textfile snapshots
# ---------------------------------------------------------------------------

_TEXTFILE = {"path": None, "interval": None, "thread": None,
             "stop": None}


def _write_textfile(path):
    """One atomic exposition() snapshot (tmp + os.replace, so a scraper
    never reads a half-written file)."""
    import os

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(exposition())
    os.replace(tmp, path)
    return path


def arm_textfile_dump(spec):
    """Arm the ``MXNET_TELEMETRY_DUMP=<path>[:interval_s]`` knob: write
    `exposition()` to `path` now and, when an interval is given, keep
    refreshing it from a daemon thread — the Prometheus node-exporter
    *textfile collector* pattern (the scraper reads the file; no HTTP
    endpoint needed inside training jobs). Returns (path, interval).
    Re-arming replaces the previous schedule."""
    import logging
    import threading as _threading

    spec = str(spec)
    path, interval = spec, None
    if ":" in spec:
        head, _, tail = spec.rpartition(":")
        try:
            interval = float(tail)
            path = head
        except ValueError:
            path, interval = spec, None   # a colon inside the path itself
    if interval is not None and interval <= 0:
        interval = None
    stop_textfile_dump()
    _write_textfile(path)
    log = logging.getLogger("incubator_mxnet_tpu.telemetry")
    if interval is None:
        log.info("telemetry dump: one-shot exposition snapshot at %s", path)
        _TEXTFILE.update(path=path, interval=None)
        return path, None
    stop = _threading.Event()

    def _loop():
        while not stop.wait(interval):
            try:
                _write_textfile(path)
            except OSError as e:
                log.warning("telemetry dump to %s failed: %s", path, e)

    t = _threading.Thread(target=_loop, name="mx-telemetry-dump",
                          daemon=True)
    t.start()
    _TEXTFILE.update(path=path, interval=interval, thread=t, stop=stop)
    log.info("telemetry dump: exposition snapshots at %s every %.3gs",
             path, interval)
    return path, interval


def stop_textfile_dump():
    """Stop the periodic dump thread (tests / re-arming)."""
    stop = _TEXTFILE.get("stop")
    if stop is not None:
        stop.set()
        t = _TEXTFILE.get("thread")
        if t is not None:
            t.join(timeout=2.0)
    _TEXTFILE.update(path=None, interval=None, thread=None, stop=None)


def reset():
    """Zero every registered series (tests). Built-ins stay registered;
    pull-mode collectors are NOT reset (their counters live elsewhere)."""
    with _LOCK:
        metrics = list(_METRICS.values())
    for m in metrics:
        with _LOCK:
            cells = list(m._cells)
        for c in cells:
            fresh = m._new_cell()
            for i in range(len(c)):
                c[i] = fresh[i]
