"""HBM observatory: subsystem-attributed live-buffer census, growth
watchdog, and OOM post-mortem.

Reference role: shardcheck's SC006 gives a *static* per-device byte
estimate; this module is its runtime counterpart — who actually owns
device memory right now. Subsystems register **owners** (serve KV pool,
prefix cache, params, optimizer state, ...) as weakly-bound probes; a
:func:`census` sweeps ``jax.live_arrays()`` and attributes every buffer to
the first owner claiming it, leaving the rest as ``unattributed``. The
census is exposed three ways:

- pull gauges ``mx_hbm_live_bytes_total`` / ``mx_hbm_live_bytes{owner=}``
  / ``mx_hbm_unattributed_bytes`` (collector — swept at report time only);
- the :func:`census` report dict (also `tools/memwatch.py`);
- a flight-recorder context probe, so EVERY crash dump carries the
  memory map at crash time.

**Growth watchdog**: :func:`watchdog_observe` tracks unattributed bytes
across steps and warns (log + ``mx_hbm_watchdog_warnings_total`` +
trace event) on sustained growth over the window — the leak signature a
page-budgeted serving host cares about. ``MXNET_MEMWATCH_INTERVAL=<sec>``
arms a daemon thread that observes on a timer.

**OOM post-mortem**: :func:`maybe_oom_postmortem` is threaded through the
dispatch/serve/estimator failure seams; on a RESOURCE_EXHAUSTED it dumps
census + top-K buffers + the compile ledger through the flight recorder
(the census/ledger context probes registered here and in `compiles.py`).
Armed by ``MXNET_TELEMETRY`` or standalone via ``MXNET_OOM_POSTMORTEM=1``.

Off-path contract: owner registration is a dict write; nothing sweeps
``jax.live_arrays()`` unless a census is actually requested (report pull,
watchdog tick, crash dump, or explicit call).
"""
from __future__ import annotations

import logging
import os
import threading
import time

from .locks import tracked_lock

from . import registry, tracing

__all__ = ["enable", "disable", "is_enabled", "reset", "register_owner",
           "unregister_owner", "census", "watchdog_observe",
           "arm_memwatch", "disarm_memwatch", "is_resource_exhausted",
           "maybe_oom_postmortem"]

logger = logging.getLogger("incubator_mxnet_tpu.telemetry.hbm")

_ENABLED = False
_LOCK = tracked_lock("telemetry.hbm", kind="lock")
_OWNERS: dict = {}            # name -> probe() (registration order wins ties)

# growth watchdog state
_WD_WINDOW = 5                # default N sustained-growth steps
_WD_MIN_GROWTH = 1 << 20      # ignore jitter below 1 MiB over the window
_WD_SAMPLES: list = []        # (unattributed bytes) ring, newest last
_WD_WARNED_STREAK = False
_MEMWATCH_THREAD = None
_MEMWATCH_STOP = None


def _arm_dispatch_hook(on):
    """The one per-op-adjacent seam (ndarray's eager-fallback except
    path) uses the module-global-None dead-branch discipline."""
    import sys

    nd = sys.modules.get("incubator_mxnet_tpu.ndarray.ndarray")
    if nd is not None:
        nd._OOM_HOOK = maybe_oom_postmortem if on else None


def enable():
    global _ENABLED
    _ENABLED = True
    _arm_dispatch_hook(True)


def disable():
    global _ENABLED
    _ENABLED = False
    _arm_dispatch_hook(False)


def is_enabled():
    return _ENABLED


def reset():
    """Drop owners and watchdog state (tests). Leaves arming alone."""
    global _WD_WARNED_STREAK
    with _LOCK:
        _OWNERS.clear()
        del _WD_SAMPLES[:]
        _WD_WARNED_STREAK = False


# --------------------------------------------------------------------------
# owners + census
# --------------------------------------------------------------------------

def register_owner(name, probe):
    """Register a subsystem as a buffer owner. ``probe()`` returns the
    jax arrays it currently owns — either an iterable, or a dict
    ``{"arrays": [...], "detail": {...}, "derived": {sub: bytes}}`` where
    `detail` is free-form context for the census report and `derived`
    attributes byte counts WITHIN the owner's arrays (e.g. the prefix
    cache's share of the KV pool pages) without double-counting them
    against the live sweep. Probes follow the weakly-bound-source idiom:
    return None once the subsystem is gone (the owner is then skipped).
    Re-registering a name replaces the probe."""
    with _LOCK:
        _OWNERS[str(name)] = probe


def unregister_owner(name):
    with _LOCK:
        _OWNERS.pop(str(name), None)


def _nbytes(a):
    n = getattr(a, "nbytes", None)
    if n is None:
        return 0
    return int(n)


def census(top_k=8):
    """Sweep ``jax.live_arrays()`` and attribute every buffer to a
    registered owner (first claim wins). Returns::

        {"total": bytes, "n_arrays": int,
         "owners": {name: bytes}, "derived": {name.sub: bytes},
         "detail": {name: {...}},  # owner-provided context
         "unattributed": bytes,
         "top": [{"bytes", "shape", "dtype", "owner"}, ...],  # largest K
         "ts": unix time}

    This is the runtime counterpart of shardcheck's SC006 static
    estimate; `SlotDecoder.hbm_crosscheck()` compares the two."""
    import jax

    with _LOCK:
        owners = list(_OWNERS.items())
    claim: dict = {}              # id(array) -> owner name
    owner_bytes: dict = {}
    derived: dict = {}
    detail: dict = {}
    for name, probe in owners:
        try:
            got = probe()
        except Exception:
            got = None
        if got is None:
            continue
        if isinstance(got, dict):
            arrays = got.get("arrays") or ()
            if got.get("detail"):
                detail[name] = got["detail"]
            for sub, b in (got.get("derived") or {}).items():
                derived[f"{name}.{sub}"] = int(b)
        else:
            arrays = got
        owner_bytes.setdefault(name, 0)
        for a in arrays:
            if a is not None and id(a) not in claim:
                claim[id(a)] = name
    total = 0
    n = 0
    tops = []
    try:
        live = jax.live_arrays()
    except Exception:
        live = []
    for a in live:
        b = _nbytes(a)
        total += b
        n += 1
        who = claim.get(id(a))
        if who is not None:
            owner_bytes[who] = owner_bytes.get(who, 0) + b
        if top_k:
            tops.append((b, a, who))
    attributed = sum(owner_bytes.values())
    report = {
        "total": total,
        "n_arrays": n,
        "owners": owner_bytes,
        "derived": derived,
        "detail": detail,
        "unattributed": max(0, total - attributed),
        "ts": time.time(),
    }
    if top_k:
        tops.sort(key=lambda t: -t[0])
        report["top"] = [
            {"bytes": b, "shape": tuple(getattr(a, "shape", ())),
             "dtype": str(getattr(a, "dtype", "?")),
             "owner": who or "unattributed"}
            for b, a, who in tops[:int(top_k)]]
    return report


def _collector():
    """Registry pull collector: the census as gauges, swept only at
    report()/exposition() time and only while armed."""
    if not _ENABLED:
        return {}
    try:
        c = census(top_k=0)
    except Exception:
        return {}
    out = {
        "mx_hbm_live_bytes_total": c["total"],
        "mx_hbm_live_arrays": c["n_arrays"],
        "mx_hbm_unattributed_bytes": c["unattributed"],
    }
    for name, b in c["owners"].items():
        out[f'mx_hbm_live_bytes{{owner="{name}"}}'] = b
    return out


def _flight_probe():
    """Flight-recorder context: census + top buffers in every crash dump
    (the OOM post-mortem payload). Swept at dump time regardless of
    arming — a crash dump should always carry the memory map."""
    try:
        return census(top_k=8)
    except Exception:
        return None


# --------------------------------------------------------------------------
# growth watchdog
# --------------------------------------------------------------------------

def watchdog_observe(window=None, min_growth=None):
    """Record one unattributed-bytes sample; warn when every step across
    the window grew and the total growth clears `min_growth` (default
    1 MiB over 5 samples). One warning per streak — the streak re-arms
    when growth pauses. Returns True when this observation warned."""
    global _WD_WARNED_STREAK
    window = int(window or _WD_WINDOW)
    floor = int(_WD_MIN_GROWTH if min_growth is None else min_growth)
    try:
        c = census(top_k=0)
    except Exception:
        return False
    with _LOCK:
        _WD_SAMPLES.append(c["unattributed"])
        del _WD_SAMPLES[:max(0, len(_WD_SAMPLES) - window)]
        samples = list(_WD_SAMPLES)
    if len(samples) < window:
        return False
    growing = all(b > a for a, b in zip(samples, samples[1:]))
    if not growing:
        _WD_WARNED_STREAK = False
        return False
    if samples[-1] - samples[0] < floor or _WD_WARNED_STREAK:
        return False
    _WD_WARNED_STREAK = True
    mb = (samples[-1] - samples[0]) / 2**20
    logger.warning(
        "HBM watchdog: unattributed live bytes grew %d steps in a row "
        "(+%.1f MiB, now %.1f MiB) — possible leak outside registered "
        "owners; run mx.telemetry.hbm.census() or tools/memwatch.py",
        window, mb, samples[-1] / 2**20)
    registry.counter("mx_hbm_watchdog_warnings_total",
                     "sustained unattributed HBM growth warnings").inc()
    tracing.event("hbm.growth", steps=window, grew_bytes=int(mb * 2**20),
                  unattributed=samples[-1])
    return True


def arm_memwatch(interval_s):
    """Start (or replace) the daemon sampling thread behind
    ``MXNET_MEMWATCH_INTERVAL`` — one watchdog observation every
    `interval_s` seconds. Returns the thread."""
    global _MEMWATCH_THREAD, _MEMWATCH_STOP
    disarm_memwatch()
    stop = threading.Event()

    def _loop():
        while not stop.wait(float(interval_s)):
            try:
                watchdog_observe()
            except Exception as e:  # noqa: FL006 — a broken owner probe
                # must not kill the watchdog timer thread; surface once
                # per tick at debug so a bad probe is still discoverable
                logger.debug("memwatch tick failed: %s", e)

    t = threading.Thread(target=_loop, name="mx-memwatch", daemon=True)
    _MEMWATCH_STOP = stop
    _MEMWATCH_THREAD = t
    t.start()
    return t


def disarm_memwatch():
    global _MEMWATCH_THREAD, _MEMWATCH_STOP
    if _MEMWATCH_STOP is not None:
        _MEMWATCH_STOP.set()
    _MEMWATCH_THREAD = None
    _MEMWATCH_STOP = None


# --------------------------------------------------------------------------
# OOM post-mortem
# --------------------------------------------------------------------------

def is_resource_exhausted(exc):
    """True for XLA RESOURCE_EXHAUSTED / out-of-memory shaped failures
    (matched on type name + message — the runtime's error classes aren't
    importable on every backend)."""
    if exc is None:
        return False
    try:
        s = f"{type(exc).__name__}: {exc}"
    except Exception:
        return False
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s)


def _postmortem_armed():
    v = os.environ.get("MXNET_OOM_POSTMORTEM")
    if v is not None:
        return v.strip().lower() not in ("", "0", "false", "off", "no")
    return _ENABLED


def maybe_oom_postmortem(where, exc):
    """Failure-seam hook (dispatch / serve / estimator): when `exc` is
    RESOURCE_EXHAUSTED-shaped and the post-mortem is armed, dump the
    flight recorder — the census and compile-ledger context probes put
    the memory map and program history in the payload. Returns the dump
    path (None when not an OOM, disarmed, or the dump itself failed —
    a broken post-mortem must never mask the OOM)."""
    if not is_resource_exhausted(exc) or not _postmortem_armed():
        return None
    try:
        registry.counter("mx_oom_postmortems_total",
                         "RESOURCE_EXHAUSTED post-mortem flight dumps").inc()
        registry.counter("mx_oom_postmortems_total",
                         "RESOURCE_EXHAUSTED post-mortem flight dumps",
                         labels={"where": str(where)}).inc()
        return tracing.flight_dump(f"oom_{where}", exc=exc)
    except Exception:
        return None


# census gauges + crash-dump context ride along from import: collectors
# are pull-only (dead until a report is actually read) and the flight
# probe only runs at dump time
registry.register_collector(_collector)
tracing.register_flight_context("hbm_census", _flight_probe)
