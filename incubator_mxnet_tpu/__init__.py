"""incubator_mxnet_tpu: a TPU-native deep learning framework with Apache
MXNet 2.0 capability parity, built on jax/XLA/pallas/pjit.

Typical use mirrors the reference:

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import np, npx, autograd, gluon

    net = gluon.nn.Dense(10)
    net.initialize()
    with autograd.record():
        loss = net(np.ones((2, 4))).sum()
    loss.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

# Multi-process rendezvous must happen before anything touches the XLA
# backend; join from env at import time when a coordinator is configured
# (the reference's analogue: ps-lite rendezvous from DMLC_* env on
# `mx.kv.create('dist_*')`, SURVEY.md §3.5).
import os as _os

# Memory-reserve knob must be forwarded BEFORE anything can initialize the
# XLA backend (profiler autostart, dist rendezvous below) — once a client
# exists, XLA_PYTHON_CLIENT_MEM_FRACTION is read-only (SURVEY §5.6).
if _os.environ.get("MXNET_GPU_MEM_POOL_RESERVE") and \
        "XLA_PYTHON_CLIENT_MEM_FRACTION" not in _os.environ:
    try:
        _frac = max(0.0, min(
            1.0, 1.0 - float(_os.environ["MXNET_GPU_MEM_POOL_RESERVE"]) / 100.0))
        _os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = f"{_frac:.2f}"
    except ValueError:
        pass

if _os.environ.get("COORDINATOR_ADDRESS") or _os.environ.get("DMLC_PS_ROOT_URI"):
    from .parallel import dist as _dist

    _dist.initialize()

from . import base  # noqa: F401
from .base import MXNetError  # noqa: F401
from .device import (  # noqa: F401
    Context,
    Device,
    cpu,
    current_device,
    gpu,
    gpu_memory_info,
    memory_stats,
    num_gpus,
    num_tpus,
    tpu,
)
from . import ndarray  # noqa: F401
from . import ndarray as nd  # noqa: F401
from .ndarray.ndarray import NDArray, waitall  # noqa: F401
from . import numpy  # noqa: F401
from . import numpy as np  # noqa: F401
from . import numpy_extension  # noqa: F401
from . import numpy_extension as npx  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401
from . import initializer  # noqa: F401
from . import initializer as init  # noqa: F401
from . import optimizer  # noqa: F401
from . import gluon  # noqa: F401
from . import kvstore  # noqa: F401
from . import kvstore as kv  # noqa: F401
from . import library  # noqa: F401
from . import operator  # noqa: F401
from . import image  # noqa: F401
from . import recordio  # noqa: F401
from . import lr_scheduler  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import parallel  # noqa: F401
from . import profiler  # noqa: F401
from . import symbol  # noqa: F401
from . import symbol as sym  # noqa: F401
from . import name  # noqa: F401
from . import attribute  # noqa: F401
from .attribute import AttrScope  # noqa: F401
from . import runtime  # noqa: F401
from . import rtc  # noqa: F401
from . import partition  # noqa: F401
from . import remat  # noqa: F401
from . import preemption  # noqa: F401
from . import callback  # noqa: F401
from . import engine  # noqa: F401
from . import context  # noqa: F401
from . import executor  # noqa: F401
from . import dlpack  # noqa: F401
from . import libinfo  # noqa: F401
from . import registry  # noqa: F401
from . import model  # noqa: F401
from . import visualization  # noqa: F401
from . import visualization as viz  # noqa: F401
from . import error  # noqa: F401
from . import log  # noqa: F401
from . import util  # noqa: F401
from . import analysis  # noqa: F401
from . import telemetry  # noqa: F401
from . import fault  # noqa: F401
from . import serve  # noqa: F401

util._apply_env_config()  # honor MXNET_* knobs (SURVEY §5.6)
from . import test_utils  # noqa: F401
from . import contrib  # noqa: F401
