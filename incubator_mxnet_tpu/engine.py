"""Engine control surface (reference: `python/mxnet/engine.py` —
`bulk`/`set_bulk_size` batch many small ops into one engine op to cut
dispatch overhead).

TPU-native: XLA fuses whole jit regions, and the eager path batches through
the op-call jit cache, so bulking is implicit. The knobs keep API parity:
`bulk` is a no-op scope whose *intent* (fewer, larger device programs) is
realized by `hybridize()`/jit, and `set_bulk_size` records the value for
introspection only.
"""
from __future__ import annotations

import contextlib

__all__ = ["bulk", "set_bulk_size"]

_BULK_SIZE = 15  # reference default MXNET_ENGINE_BULK_SIZE


def set_bulk_size(size: int) -> int:
    """Set the bulk window; returns the previous value (`engine.py:58`)."""
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


@contextlib.contextmanager
def bulk(size: int):
    """Scope batching ops into one engine op (`engine.py:77`). Under XLA
    the compiler owns op grouping — the scope is behavioral parity only."""
    prev = set_bulk_size(size)
    try:
        yield
    finally:
        set_bulk_size(prev)
