"""Name manager for symbol construction (reference: `python/mxnet/name.py` —
`NameManager` assigns unique names to unnamed symbols, `Prefix` prepends a
scope prefix).

TPU-native role: symbol nodes are pure-Python graph metadata (no C handles),
so the manager is just a thread-local counter stack.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_TLS = threading.local()


def _stack():
    if not hasattr(_TLS, "stack"):
        _TLS.stack = [NameManager()]
    return _TLS.stack


class NameManager:
    """Scope manager assigning unique names per hint (`name.py:29`)."""

    def __init__(self):
        self._counter: dict[str, int] = {}

    def get(self, name: str | None, hint: str) -> str:
        if name is not None:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *exc):
        _stack().pop()
        return False


class Prefix(NameManager):
    """Prepend a prefix to every auto-generated name (`name.py:74`)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def get(self, name: str | None, hint: str) -> str:
        if name is not None:
            return name
        return self._prefix + super().get(None, hint)


def current() -> NameManager:
    return _stack()[-1]
