"""Sharded BERT training step: the multi-chip flagship path.

Replaces the reference's distributed training stack (ps-lite workers+servers,
`src/kvstore/kvstore_dist.h`; NCCL allreduce, `kvstore_nccl.h`) with one jit
program over a `jax.sharding.Mesh` with axes:

- **dp**  — batch sharded (data parallel); XLA inserts gradient psum on ICI.
- **tp**  — attention heads and FFN hidden dim sharded (Megatron tensor
  parallel): qkv/ffn1 weights column-sharded, proj/ffn2 row-sharded, the
  pairwise all-reduces placed by XLA from the shardings.
- **sp**  — sequence parallelism in the LayerNorm/dropout regions
  (activations sharded over the tp axis along the sequence dim between
  blocks — Megatron-SP style), expressed with with_sharding_constraint.

The whole fwd+bwd+adam step is one compiled program; collectives overlap
with compute via XLA's latency-hiding scheduler (subsumes the reference's
P3 priority push, `src/kvstore/p3store_dist.h`).
"""
from __future__ import annotations

import math
from functools import partial

__all__ = ["BertConfig", "init_params", "forward", "loss_fn", "make_train_step",
           "param_specs"]


class BertConfig:
    def __init__(self, vocab_size=1000, units=64, hidden_size=128,
                 num_layers=2, num_heads=4, max_length=128, dtype="bfloat16"):
        self.vocab_size = vocab_size
        self.units = units
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_length = max_length
        self.dtype = dtype


def init_params(cfg: BertConfig, seed: int = 0):
    import jax
    import jax.numpy as jnp

    k = jax.random.PRNGKey(seed)
    dt = jnp.float32  # master params in fp32; compute casts to bf16
    U, H = cfg.units, cfg.hidden_size

    def dense(key, i, o):
        return {"w": jax.random.normal(key, (i, o), dt) / math.sqrt(i),
                "b": jnp.zeros((o,), dt)}

    keys = jax.random.split(k, 4 + 4 * cfg.num_layers)
    params = {
        "word_embed": jax.random.normal(keys[0], (cfg.vocab_size, U), dt) * 0.02,
        "pos_embed": jax.random.normal(keys[1], (cfg.max_length, U), dt) * 0.02,
        "ln_g": jnp.ones((U,), dt), "ln_b": jnp.zeros((U,), dt),
        "layers": [],
        "mlm": dense(keys[2], U, cfg.vocab_size),
    }
    for i in range(cfg.num_layers):
        kq, kp, k1, k2 = keys[4 + 4 * i:8 + 4 * i]
        params["layers"].append({
            "qkv": dense(kq, U, 3 * U),
            "proj": dense(kp, U, U),
            "ffn1": dense(k1, U, H),
            "ffn2": dense(k2, H, U),
            "ln1_g": jnp.ones((U,), dt), "ln1_b": jnp.zeros((U,), dt),
            "ln2_g": jnp.ones((U,), dt), "ln2_b": jnp.zeros((U,), dt),
        })
    return params


def param_specs(cfg: BertConfig):
    """PartitionSpec tree: Megatron TP sharding over the 'tp' axis."""
    import jax

    P = jax.sharding.PartitionSpec
    col = P(None, "tp")   # column parallel: out-dim sharded
    row = P("tp", None)   # row parallel: in-dim sharded
    repl = P()
    specs = {
        "word_embed": P("tp", None),  # vocab-sharded embedding
        "pos_embed": repl,
        "ln_g": repl, "ln_b": repl,
        "layers": [],
        "mlm": {"w": P(None, "tp"), "b": P("tp")},
    }
    for _ in range(cfg.num_layers):
        specs["layers"].append({
            "qkv": {"w": col, "b": P("tp")},
            "proj": {"w": row, "b": repl},
            "ffn1": {"w": col, "b": P("tp")},
            "ffn2": {"w": row, "b": repl},
            "ln1_g": repl, "ln1_b": repl,
            "ln2_g": repl, "ln2_b": repl,
        })
    return specs


def _ln(x, g, b, eps=1e-5):
    import jax.numpy as jnp

    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * g + b


def forward(params, tokens, cfg: BertConfig, sp_constraint=None):
    """tokens (N, T) int32 → mlm logits (N, T, vocab).

    `sp_constraint(x, kind)` applies sharding constraints; kind is 'seq'
    (LayerNorm/residual regions — sequence-sharded, SP) or 'full'
    (attention/FFN interior — heads/hidden sharded, TP)."""
    import jax
    import jax.numpy as jnp

    dt = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    cst = sp_constraint or (lambda x, kind: x)
    N, T = tokens.shape
    U, H = cfg.units, cfg.num_heads
    d = U // H

    x = params["word_embed"][tokens] + params["pos_embed"][:T]
    x = _ln(x, params["ln_g"], params["ln_b"]).astype(dt)
    x = cst(x, "seq")
    for lp in params["layers"]:
        # attention (TP region)
        h = cst(x, "full")
        qkv = h @ lp["qkv"]["w"].astype(dt) + lp["qkv"]["b"].astype(dt)
        qkv = qkv.reshape(N, T, 3, H, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # (N,T,H,d)
        scores = jnp.einsum("nthd,nshd->nhts", q, k) / math.sqrt(d)
        att = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        ctx = jnp.einsum("nhts,nshd->nthd", att, v).reshape(N, T, U)
        ctx = ctx @ lp["proj"]["w"].astype(dt) + lp["proj"]["b"].astype(dt)
        x = cst(x + ctx, "seq")
        x = _ln(x, lp["ln1_g"].astype(dt), lp["ln1_b"].astype(dt))
        # FFN (TP region)
        h = cst(x, "full")
        h = h @ lp["ffn1"]["w"].astype(dt) + lp["ffn1"]["b"].astype(dt)
        h = jax.nn.gelu(h)
        h = h @ lp["ffn2"]["w"].astype(dt) + lp["ffn2"]["b"].astype(dt)
        x = cst(x + h, "seq")
        x = _ln(x, lp["ln2_g"].astype(dt), lp["ln2_b"].astype(dt))
    logits = x @ params["mlm"]["w"].astype(dt) + params["mlm"]["b"].astype(dt)
    return logits.astype(jnp.float32)


def loss_fn(params, tokens, labels, cfg, sp_constraint=None):
    import jax
    import jax.numpy as jnp

    logits = forward(params, tokens, cfg, sp_constraint)
    lp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(lp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_train_step(cfg: BertConfig, mesh, lr=1e-3, use_sp=True):
    """Build the compiled sharded train step (adam) over `mesh`.

    Mesh must have axes ('dp', 'tp'). Returns (step, params, opt_state) with
    all states placed according to the TP specs."""
    import jax
    import jax.numpy as jnp

    P = jax.sharding.PartitionSpec
    NS = partial(jax.sharding.NamedSharding, mesh)

    specs = param_specs(cfg)
    params = init_params(cfg)
    leaves, treedef = jax.tree.flatten(params)
    spec_leaves = jax.tree.flatten(specs,
                                   is_leaf=lambda v: isinstance(v, P))[0]
    params = jax.tree.unflatten(
        treedef, [jax.device_put(v, NS(s))
                  for v, s in zip(leaves, spec_leaves)])
    zeros = jax.tree.map(jnp.zeros_like, params)
    opt_state = {"m": zeros, "v": jax.tree.map(jnp.zeros_like, params),
                 "t": jnp.zeros((), jnp.int32)}

    def cst(x, kind):
        if x.ndim != 3:
            return x
        if kind == "seq" and use_sp:
            return jax.lax.with_sharding_constraint(x, NS(P("dp", "tp", None)))
        return jax.lax.with_sharding_constraint(x, NS(P("dp", None, None)))

    def step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, labels, cfg,
                                                  cst)
        t = opt_state["t"] + 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        tf = t.astype(jnp.float32)

        def upd(p, g, m, v):
            m2 = b1 * m + (1 - b1) * g
            v2 = b2 * v + (1 - b2) * g * g
            mhat = m2 / (1 - jnp.power(b1, tf))
            vhat = v2 / (1 - jnp.power(b2, tf))
            return p - lr * mhat / (jnp.sqrt(vhat) + eps), m2, v2

        flat = jax.tree.map(upd, params, grads, opt_state["m"], opt_state["v"],
                            is_leaf=lambda v: hasattr(v, "shape"))
        new_params = jax.tree.map(lambda t3: t3[0], flat,
                                  is_leaf=lambda v: isinstance(v, tuple))
        new_m = jax.tree.map(lambda t3: t3[1], flat,
                             is_leaf=lambda v: isinstance(v, tuple))
        new_v = jax.tree.map(lambda t3: t3[2], flat,
                             is_leaf=lambda v: isinstance(v, tuple))
        return loss, new_params, {"m": new_m, "v": new_v, "t": t}

    param_sh = jax.tree.unflatten(treedef, [NS(s) for s in spec_leaves])
    opt_sh = {"m": param_sh, "v": param_sh, "t": NS(P())}
    batch_sh = NS(P("dp", None))
    from ..telemetry.compiles import ledgered_jit

    jitted = ledgered_jit(step, family="train.sharded_bert.step",
                          in_shardings=(param_sh, opt_sh, batch_sh,
                                        batch_sh),
                          donate_argnums=(0, 1))
    return jitted, params, opt_state
