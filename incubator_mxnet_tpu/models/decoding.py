"""KV-cache incremental decoding for causal LMs (the serving path).

Reference role: the reference deploys frozen graphs through the
cpp-package `Predictor` (`cpp-package/include/mxnet-cpp/`), and the
GPT-2 generation of its era (GluonNLP) re-ran the full forward per
token. TPU-native design instead compiles the WHOLE decode as one XLA
program:

- a static-shape KV cache `(L, N, H, max_length, d)` — no growing
  shapes, so there is exactly ONE compile per (batch, prompt-bucket,
  max_new_tokens) signature, not one per decoded length;
- prefill = one causal flash-attention pass over the prompt that also
  writes the prompt's K/V into the cache;
- decode = `lax.scan` over steps; each step runs a scan-over-layers
  single-token forward against the cache (O(T) work per token instead
  of the O(T²) full re-forward) and samples the next token in-graph;
- sampling (temperature / top-k) uses the framework RNG key so
  `mx.random.seed` reproduces generations.

The layer math mirrors `GPTModel.forward` exactly (pre-norm blocks,
gelu FFN, tied LM head) — greedy decode emits the same tokens as the
eager full-forward loop, asserted by `tests/test_gpt.py`.
"""
from __future__ import annotations

import functools
import logging
import math

import numpy as onp

__all__ = ["GPTDecoder", "NgramProposer", "bucket_prompt",
           "PROMPT_BUCKETS", "chunk_buckets", "bucket_chunk"]

_LOG = logging.getLogger("incubator_mxnet_tpu.models")

#: Default pad-to-bucket prompt lengths. Ad-hoc prompt lengths each
#: compile their own XLA program (the signature includes the prompt
#: width); snapping to power-of-two buckets bounds the program count at
#: len(PROMPT_BUCKETS) per (batch, max_new) — the waste is padding
#: tokens, which `mx_decode_bucket_pad_tokens_total` makes visible.
PROMPT_BUCKETS = (32, 64, 128, 256, 512)


def bucket_prompt(ids, buckets=PROMPT_BUCKETS, max_len=None, pad_id=0):
    """Pad token ids (N, T) to the smallest bucket >= T.

    Returns ``(padded_ids, t0)`` where ``t0`` is the true prompt length.
    Padding goes on the RIGHT with `pad_id`; the padded positions' K/V
    are causally invisible to the last real token and are overwritten by
    decode before the attention mask ever reaches them, so any valid
    token id works as filler. Prompts longer than every bucket are
    returned unpadded (exact-length compile, the pre-bucketing
    behavior); `max_len` (when given) caps the chosen bucket.

    Pads with host/device-agnostic `jnp.pad`; the padding waste is
    accounted in the ``mx_decode_bucket_pad_tokens_total`` counter.
    """
    jnp = _j().numpy
    ids = jnp.asarray(ids)
    if ids.ndim != 2:
        raise ValueError(f"bucket_prompt expects (N, T) ids, got "
                         f"shape {ids.shape}")
    n, t0 = ids.shape
    fits = sorted(b for b in buckets
                  if b >= t0 and (max_len is None or b <= max_len))
    if not fits:
        return ids, t0
    bucket = fits[0]
    if bucket == t0:
        return ids, t0
    padded = jnp.pad(ids, ((0, 0), (0, bucket - t0)),
                     constant_values=pad_id)
    from ..telemetry import registry

    registry.counter(
        "mx_decode_bucket_pad_tokens_total",
        "prompt tokens added by pad-to-bucket in the decode/serving "
        "path (padding waste)").inc(int(n * (bucket - t0)))
    return padded, t0


def chunk_buckets(page_tokens, prefill_chunk):
    """Static chunk-length buckets for the paged serving prefill
    (`serve.SlotDecoder`): power-of-two multiples of `page_tokens` up to
    `prefill_chunk`, plus `prefill_chunk` itself. Every chunk is a whole
    number of pages, so chunk writes land on page boundaries and the
    compiled chunk-prefill family stays bounded at len(buckets) programs.
    """
    pt = int(page_tokens)
    chunk = int(prefill_chunk)
    if pt < 1:
        raise ValueError(f"page_tokens must be >= 1, got {pt}")
    if chunk % pt:
        raise ValueError(
            f"prefill_chunk ({chunk}) must be a multiple of page_tokens "
            f"({pt}) so chunks stay page-aligned")
    out = set()
    b = pt
    while b < chunk:
        out.add(b)
        b *= 2
    out.add(chunk)
    return tuple(sorted(out))


def bucket_chunk(n, buckets):
    """Smallest chunk bucket >= n (the last prefill chunk of a prompt is
    padded up to it; the waste rides the same
    ``mx_decode_bucket_pad_tokens_total`` counter as prompt bucketing)."""
    fits = [b for b in buckets if b >= n]
    if not fits:
        raise ValueError(f"chunk of {n} tokens exceeds every bucket "
                         f"{tuple(buckets)}")
    return min(fits)


def _j():
    import jax

    return jax


def _ln(x, g, b, eps=1e-5):
    """float32-internal layer norm matching `npx.layer_norm`."""
    jnp = _j().numpy
    xd = x.dtype
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (x32 - mu) / jnp.sqrt(var + eps)
    return (y * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(xd)


def _dense(x, w, b=None):
    """`npx.fully_connected(flatten=False)`: y = x @ W^T (+ b)."""
    jnp = _j().numpy
    y = x @ w.T
    return y if b is None else y + b


def _split_qkv(h, n_heads):
    """(N, T, 3C) -> three (N, H, T, d), matching the gluon reshape."""
    jnp = _j().numpy
    N, T, C3 = h.shape
    C = C3 // 3
    d = C // n_heads
    qkv = h.reshape(N, T, 3, n_heads, d)
    q = jnp.transpose(qkv[:, :, 0], (0, 2, 1, 3))
    k = jnp.transpose(qkv[:, :, 1], (0, 2, 1, 3))
    v = jnp.transpose(qkv[:, :, 2], (0, 2, 1, 3))
    return q, k, v


class GPTDecoder:
    """Compiled KV-cache text generation over a (trained) `GPTModel`.

    Parameters are read from the model at construction (zero-copy jax
    references); the jit cache persists across calls, so repeated
    generation with the same shapes never recompiles.
    """

    def __init__(self, model):
        self._model = model
        self._n_heads = model.blocks[0].attn._num_heads
        self._units = model.blocks[0].attn._units
        self._tie = model._tie
        self._max_length = int(model.position_embed.shape[0])
        self._param_ids = None
        self._warned_stale = False
        self.refresh()

    # -- parameters ---------------------------------------------------------

    @staticmethod
    def _leaf(p):
        return p.data()._data  # noqa: SLF001 — jax value, zero-copy

    def _extract_params(self, model):
        jnp = _j().numpy
        per_layer = []
        for blk in model.blocks:
            per_layer.append({
                "ln1_g": self._leaf(blk.ln1.gamma),
                "ln1_b": self._leaf(blk.ln1.beta),
                "qkv_w": self._leaf(blk.attn.qkv.weight),
                "qkv_b": self._leaf(blk.attn.qkv.bias),
                "proj_w": self._leaf(blk.attn.proj.weight),
                "proj_b": self._leaf(blk.attn.proj.bias),
                "ln2_g": self._leaf(blk.ln2.gamma),
                "ln2_b": self._leaf(blk.ln2.beta),
                "ffn1_w": self._leaf(blk.ffn.ffn1.weight),
                "ffn1_b": self._leaf(blk.ffn.ffn1.bias),
                "ffn2_w": self._leaf(blk.ffn.ffn2.weight),
                "ffn2_b": self._leaf(blk.ffn.ffn2.bias),
            })
        # stack per-layer leaves on a leading L axis: scan-over-layers
        # keeps compile time flat in depth (one traced layer body)
        stacked = {k: jnp.stack([lp[k] for lp in per_layer])
                   for k in per_layer[0]}
        params = {
            "layers": stacked,
            "embed": self._leaf(model.word_embed.weight),
            "pos": self._leaf(model.position_embed),
            "lnf_g": self._leaf(model.ln_f.gamma),
            "lnf_b": self._leaf(model.ln_f.beta),
        }
        if not self._tie:
            params["head_w"] = self._leaf(model.lm_head.weight)
        return params

    def _current_ids(self):
        """Identity fingerprint of every live parameter buffer — jax
        arrays are immutable, so any set_data / optimizer step rebinds the
        buffer and changes its id."""
        return tuple(id(self._leaf(p)) for p in
                     self._model.collect_params().values())

    def refresh(self):
        """Re-read parameters from the model if any changed since the
        last stack (cheap identity walk; the O(model) re-stack only runs
        after an actual update — serving calls stay zero-copy)."""
        ids = self._current_ids()
        if ids != self._param_ids:
            self._params = self._extract_params(self._model)
            self._param_ids = ids

    # -- math ---------------------------------------------------------------

    def _logits(self, params, x):
        x = _ln(x, params["lnf_g"], params["lnf_b"])
        if self._tie:
            return x @ params["embed"].T
        return x @ params["head_w"].T

    def _prefill_layer(self, x, lp, cache_len):
        """Full-prompt causal attention; returns (x', k, v) padded to S."""
        jax = _j()
        jnp = jax.numpy
        from ..ops.flash_attention import flash_attention

        H = self._n_heads
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q, k, v = _split_qkv(_dense(h, lp["qkv_w"], lp["qkv_b"]), H)
        d = q.shape[-1]
        o = flash_attention(q, k, v, causal=True,
                            sm_scale=1.0 / math.sqrt(d))
        N, _, T, _ = o.shape
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(N, T, H * d)
        x = x + _dense(o, lp["proj_w"], lp["proj_b"])
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        ffn = _dense(jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
                     lp["ffn2_w"], lp["ffn2_b"])
        pad = [(0, 0), (0, 0), (0, cache_len - T), (0, 0)]
        return x + ffn, jnp.pad(k, pad), jnp.pad(v, pad)

    def _decode_layer(self, x, lp, ck, cv, pos):
        """One-token forward against the cache; writes k/v at `pos`."""
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax

        H = self._n_heads
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q, k, v = _split_qkv(_dense(h, lp["qkv_w"], lp["qkv_b"]), H)
        d = q.shape[-1]
        # write this token's k/v at position pos (static-shape update)
        ck = lax.dynamic_update_slice(ck, k, (0, 0, pos, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, 0, pos, 0))
        # attend to positions 0..pos; later slots hold zeros/garbage that
        # the mask excludes (f32 scores for a stable softmax)
        s = jnp.einsum("nhqd,nhkd->nhqk", q, ck,
                       preferred_element_type=jnp.float32)
        s = s / math.sqrt(d)
        mask = jnp.arange(ck.shape[2]) <= pos
        s = jnp.where(mask[None, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(cv.dtype)
        o = jnp.einsum("nhqk,nhkd->nhqd", p, cv)
        N = x.shape[0]
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(N, 1, H * d)
        x = x + _dense(o, lp["proj_w"], lp["proj_b"])
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        ffn = _dense(jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
                     lp["ffn2_w"], lp["ffn2_b"])
        return x + ffn, ck, cv

    def _sample(self, logits, key, temperature, top_k, do_sample):
        jax = _j()
        jnp = jax.numpy
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits.astype(jnp.float32) / temperature
        if top_k is not None:
            vals, idx = jax.lax.top_k(logits, top_k)
            choice = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(
                idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    # -- the compiled program ----------------------------------------------

    @functools.cached_property
    def _generate_fn(self):
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax

        def generate(params, tokens, t0, key, temperature, *, max_new,
                     top_k, do_sample, cache_len):
            # `tokens` is the BUCKET-padded prompt (N, B); `t0` is the
            # true prompt length, a traced scalar so every length in the
            # bucket shares one program. Padded positions write junk K/V
            # beyond t0, but decode overwrites position p before the
            # `arange <= pos` mask ever admits it, so the junk is never
            # attended.
            N, B = tokens.shape
            L = params["layers"]["ln1_g"].shape[0]

            # ---- prefill: full causal pass over the padded prompt ----
            x = params["embed"][tokens] + params["pos"][:B]

            def pre_layer(x, lp):
                x, k, v = self._prefill_layer(x, lp, cache_len)
                return x, (k, v)

            x, (ck, cv) = lax.scan(pre_layer, x, params["layers"])
            # last REAL token (causal: its row never saw the padding)
            logits0 = self._logits(
                params, lax.dynamic_slice_in_dim(x, t0 - 1, 1,
                                                 axis=1)[:, 0])  # (N, V)

            # ---- decode: one scan step per new token ----
            def step(carry, step_key):
                ck, cv, pos, tok = carry

                x = (params["embed"][tok][:, None]
                     + lax.dynamic_slice_in_dim(params["pos"], pos, 1))

                def dec_layer(x, layer):
                    lp, ck_l, cv_l = layer
                    x, ck_l, cv_l = self._decode_layer(x, lp, ck_l, cv_l,
                                                       pos)
                    return x, (ck_l, cv_l)

                x, (ck, cv) = lax.scan(dec_layer, x,
                                       (params["layers"], ck, cv))
                logits = self._logits(params, x[:, 0])
                nxt = self._sample(logits, step_key, temperature, top_k,
                                   do_sample)
                return (ck, cv, pos + 1, nxt), tok

            first = self._sample(logits0, key, temperature, top_k,
                                 do_sample)
            # each step consumes the carried token and samples the next:
            # `first` + (max_new - 1) steps = max_new generated tokens
            keys = jax.random.split(jax.random.fold_in(key, 1),
                                    max_new)[1:]
            (_, _, _, last), toks = lax.scan(
                step, (ck, cv, t0.astype(jnp.int32), first), keys)
            # toks holds the CARRIED token per step; append the final
            # sample to complete max_new outputs
            out = jnp.concatenate(
                [jnp.transpose(toks, (1, 0)), last[:, None]], axis=1)
            return out

        from ..telemetry.compiles import ledgered_jit

        return ledgered_jit(generate,
                            family="gpt.generate",
                            static_argnames=("max_new", "top_k",
                                             "do_sample", "cache_len"))

    def _auto_refresh(self):
        """Re-stack parameters if the model was updated since the last
        read. `refresh()` after a parameter update is easy to forget, so
        `generate` calls this on every entry (cheap identity walk): stale
        params are re-read automatically, with a one-time warning so the
        missing `refresh()` call gets fixed at the source."""
        ids = self._current_ids()
        if ids != self._param_ids:
            if self._param_ids is not None and not self._warned_stale:
                self._warned_stale = True
                _LOG.warning(
                    "GPTDecoder: model parameters changed since the last "
                    "refresh(); auto-refreshing. Call refresh() after "
                    "parameter updates to make the re-stack explicit.")
            self._params = self._extract_params(self._model)
            self._param_ids = ids

    def generate(self, tokens, max_new_tokens, temperature=1.0, top_k=None,
                 do_sample=False, seed=None):
        """Generate `max_new_tokens` continuations of `tokens` (N, T0).

        Greedy by default; `do_sample=True` draws from the
        temperature-scaled (optionally top-k-truncated) distribution
        using the framework RNG (`mx.random.seed` reproduces runs).

        The prompt is padded to a :data:`PROMPT_BUCKETS` length bucket
        before compile, so ad-hoc prompt lengths share one XLA program
        per (batch, bucket, max_new) signature instead of one per exact
        length. Parameters are auto-refreshed if the model changed since
        the last read (see :meth:`_auto_refresh`).
        """
        jax = _j()
        jnp = jax.numpy
        from .. import random as mxrandom
        from ..ndarray.ndarray import NDArray

        self._auto_refresh()
        toks = tokens._data if isinstance(tokens, NDArray) else \
            jnp.asarray(tokens)
        toks = toks.astype(jnp.int32)
        if max_new_tokens <= 0:
            return NDArray(toks)          # no-op budget: prompt unchanged
        T0 = toks.shape[1]
        total = T0 + max_new_tokens
        if total > self._max_length:
            raise ValueError(
                f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_length ({self._max_length})")
        padded, t0 = bucket_prompt(toks, max_len=self._max_length)
        if seed is not None:
            key = jax.random.PRNGKey(seed)
        else:
            key = mxrandom.next_key()
        new = self._generate_fn(
            self._params, padded, jnp.int32(t0), key,
            jnp.float32(max(temperature, 1e-6)),
            max_new=max_new_tokens,
            top_k=None if top_k is None else int(top_k),
            do_sample=bool(do_sample),
            cache_len=padded.shape[1] + max_new_tokens)
        return NDArray(jnp.concatenate([toks, new], axis=1))


class NgramProposer:
    """Model-free draft source for speculative decoding.

    Proposes the ``k`` tokens that followed the most recent earlier
    occurrence of the sequence's longest matching suffix n-gram —
    greedy decode of small models (and structured output in general)
    is highly repetitive, so a pure host-numpy suffix match drafts
    useful continuations with ZERO extra device programs. When nothing
    matches, it proposes a repeat of the last token (the cheapest
    guess that is still sometimes right for degenerate loops).

    The proposal is only ever a *hint*: the target model verifies every
    drafted token, so a bad draft costs acceptance rate, never
    correctness (see `serve.SlotDecoder` spec decode).
    """

    def __init__(self, k, max_ngram=3):
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.max_ngram = int(max_ngram)
        if self.max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")

    def propose(self, seq):
        """Draft ``k`` tokens continuing 1-D token id array ``seq``
        (prompt + everything generated so far). Returns ``(k,)`` int32
        host numpy."""
        seq = onp.asarray(seq, onp.int32).reshape(-1)
        if seq.size == 0:
            return onp.zeros(self.k, onp.int32)
        out = onp.full(self.k, seq[-1], onp.int32)     # fallback: repeat
        for n in range(min(self.max_ngram, seq.size - 1), 0, -1):
            pat = seq[-n:]
            # candidate windows strictly BEFORE the suffix itself
            wins = onp.lib.stride_tricks.sliding_window_view(seq, n)[:-1]
            hits = onp.flatnonzero((wins == pat).all(axis=1))
            if hits.size == 0:
                continue
            i = int(hits[-1])                          # most recent match
            cont = seq[i + n:i + n + self.k]
            if cont.size == 0:
                continue
            out[:cont.size] = cont
            out[cont.size:] = cont[-1]
            return out
        return out
