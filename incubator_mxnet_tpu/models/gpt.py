"""Decoder-only causal language model (GPT-style), gluon API.

Reference role: the reference era's GluonNLP ships GPT-2 for text
generation (`gluonnlp.model.train.GPT2Model` built on MXNet base ops —
no fused attention, dense (T,T) masks). Here the causal path is
first-class: `npx.flash_attention(causal=True)` routes the triangular
mask INTO the kernel (pallas streaming beyond the memory cliff, fused XLA
below it), so long-context decoding never materializes T² masks.

Shares the transformer building blocks with `models/bert.py` where the
math is identical (PositionwiseFFN); attention differs (causal,
pre-norm residuals — the GPT-2 layout).
"""
from __future__ import annotations

import math

from .. import numpy as np
from .. import numpy_extension as npx
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from .bert import PositionwiseFFN

__all__ = ["CausalSelfAttention", "GPTBlock", "GPTModel", "gpt2_small",
           "gpt_tiny"]


class CausalSelfAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0):
        super().__init__()
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                            in_units=units)
        self.proj = nn.Dense(units, flatten=False, use_bias=True,
                             in_units=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        N, T, C = x.shape
        H = self._num_heads
        d = C // H
        qkv = self.qkv(x).reshape(N, T, 3, H, d)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        out = npx.flash_attention(q, k, v, causal=True,
                                  sm_scale=1.0 / math.sqrt(d))
        out = out.transpose(0, 2, 1, 3).reshape(N, T, C)
        if self.dropout is not None:
            out = self.dropout(out)
        return self.proj(out)


class GPTBlock(HybridBlock):
    """Pre-norm residual block (the GPT-2 layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0):
        super().__init__()
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.attn = CausalSelfAttention(units, num_heads, dropout)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                   activation="gelu")

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self.ffn(self.ln2(x))


class GPTModel(HybridBlock):
    """Token+position embed → N pre-norm blocks → final LN → tied LM head."""

    def __init__(self, vocab_size, units, hidden_size, num_layers,
                 num_heads, max_length, dropout=0.1, tie_weights=True):
        super().__init__()
        self._tie = tie_weights
        self.word_embed = nn.Embedding(vocab_size, units)
        self.position_embed = Parameter(shape=(max_length, units),
                                        init="normal")
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.blocks = nn.HybridSequential()
        for _ in range(num_layers):
            self.blocks.add(GPTBlock(units, hidden_size, num_heads, dropout))
        self.ln_f = nn.LayerNorm(in_channels=units)
        if not tie_weights:
            self.lm_head = nn.Dense(vocab_size, flatten=False,
                                    use_bias=False, in_units=units)

    def forward(self, tokens):
        N, T = tokens.shape
        x = self.word_embed(tokens) + self.position_embed.data()[:T]
        if self.dropout is not None:
            x = self.dropout(x)
        x = self.ln_f(self.blocks(x))
        if self._tie:
            # weight tying (GPT-2): logits = h @ E^T
            return np.dot(x, self.word_embed.weight.data().T)
        return self.lm_head(x)

    def generate(self, tokens, max_new_tokens, temperature=1.0, top_k=None):
        """Greedy / top-k sampling loop (eager — each step re-runs the
        compiled forward on the grown prefix; a KV-cache decode loop is
        the serving-path optimization, out of scope for parity)."""
        from .. import random as mxrandom

        del mxrandom  # sampling uses np.random via npx.topk below
        out = tokens
        for _ in range(max_new_tokens):
            logits = self(out)[:, -1]                       # (N, V)
            if temperature != 1.0:
                logits = logits / temperature
            if top_k is not None:
                kth = npx.topk(logits, k=top_k, ret_typ="value",
                               axis=-1)[:, -1:]
                logits = np.where(logits < kth,
                                  np.full_like(logits, -1e30), logits)
            nxt = np.argmax(logits, axis=-1).reshape(-1, 1).astype("int32")
            out = np.concatenate([out, nxt], axis=1)
        return out


def gpt2_small(vocab_size=50257, max_length=1024, dropout=0.1):
    """GPT-2 124M configuration."""
    return GPTModel(vocab_size, 768, 3072, 12, 12, max_length, dropout)


def gpt_tiny(vocab_size=1000, max_length=128, dropout=0.1):
    """Tiny config for tests and compile checks."""
    return GPTModel(vocab_size, 64, 128, 2, 4, max_length, dropout)
