"""Decoder-only causal language model (GPT-style), gluon API.

Reference role: the reference era's GluonNLP ships GPT-2 for text
generation (`gluonnlp.model.train.GPT2Model` built on MXNet base ops —
no fused attention, dense (T,T) masks). Here the causal path is
first-class: `npx.flash_attention(causal=True)` routes the triangular
mask INTO the kernel (pallas streaming beyond the memory cliff, fused XLA
below it), so long-context decoding never materializes T² masks.

Shares the transformer building blocks with `models/bert.py` where the
math is identical (PositionwiseFFN); attention differs (causal,
pre-norm residuals — the GPT-2 layout).
"""
from __future__ import annotations

import math

from .. import numpy as np
from .. import numpy_extension as npx
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from .bert import PositionwiseFFN

__all__ = ["CausalSelfAttention", "GPTBlock", "GPTModel", "gpt2_small",
           "gpt_tiny"]


class CausalSelfAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0):
        super().__init__()
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                            in_units=units)
        self.proj = nn.Dense(units, flatten=False, use_bias=True,
                             in_units=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        N, T, C = x.shape
        H = self._num_heads
        d = C // H
        qkv = self.qkv(x).reshape(N, T, 3, H, d)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        out = npx.flash_attention(q, k, v, causal=True,
                                  sm_scale=1.0 / math.sqrt(d))
        out = out.transpose(0, 2, 1, 3).reshape(N, T, C)
        if self.dropout is not None:
            out = self.dropout(out)
        return self.proj(out)


class GPTBlock(HybridBlock):
    """Pre-norm residual block (the GPT-2 layout)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0):
        super().__init__()
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.attn = CausalSelfAttention(units, num_heads, dropout)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout,
                                   activation="gelu")

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self.ffn(self.ln2(x))


class GPTModel(HybridBlock):
    """Token+position embed → N pre-norm blocks → final LN → tied LM head."""

    def __init__(self, vocab_size, units, hidden_size, num_layers,
                 num_heads, max_length, dropout=0.1, tie_weights=True):
        super().__init__()
        self._tie = tie_weights
        self.word_embed = nn.Embedding(vocab_size, units)
        self.position_embed = Parameter(shape=(max_length, units),
                                        init="normal")
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.blocks = nn.HybridSequential()
        for _ in range(num_layers):
            self.blocks.add(GPTBlock(units, hidden_size, num_heads, dropout))
        self.ln_f = nn.LayerNorm(in_channels=units)
        if not tie_weights:
            self.lm_head = nn.Dense(vocab_size, flatten=False,
                                    use_bias=False, in_units=units)

    def forward(self, tokens):
        N, T = tokens.shape
        x = self.word_embed(tokens) + self.position_embed.data()[:T]
        if self.dropout is not None:
            x = self.dropout(x)
        x = self.ln_f(self.blocks(x))
        if self._tie:
            # weight tying (GPT-2): logits = h @ E^T
            return np.dot(x, self.word_embed.weight.data().T)
        return self.lm_head(x)

    def generate(self, tokens, max_new_tokens, temperature=1.0, top_k=None,
                 do_sample=False, seed=None, use_cache=True):
        """Generate continuations of `tokens` (N, T0).

        `use_cache=True` (default) compiles the whole decode as ONE XLA
        program over a static-shape KV cache (`models/decoding.py`) —
        O(T) work per token, no per-length recompiles. `use_cache=False`
        keeps the eager full-forward loop (O(T²); the parity reference
        for tests).

        Greedy unless `do_sample=True`, which draws from the
        temperature-scaled, optionally top-k-truncated distribution
        using the framework RNG (`mx.random.seed` / `seed=` reproduce).
        """
        if use_cache:
            from .decoding import GPTDecoder

            if getattr(self, "_decoder", None) is None:
                self._decoder = GPTDecoder(self)
            else:
                self._decoder.refresh()
            return self._decoder.generate(
                tokens, max_new_tokens, temperature=temperature,
                top_k=top_k, do_sample=do_sample, seed=seed)

        from .. import random as mxrandom

        out = tokens
        for i in range(max_new_tokens):
            logits = self(out)[:, -1]                       # (N, V)
            if do_sample:
                import jax

                logits = logits / max(temperature, 1e-6)
                lo = logits._data.astype("float32")  # noqa: SLF001
                key = (jax.random.PRNGKey(seed) if seed is not None
                       else mxrandom.next_key())
                key = jax.random.fold_in(key, i)
                if top_k is not None:
                    vals, idx = jax.lax.top_k(lo, int(top_k))
                    choice = jax.random.categorical(key, vals, axis=-1)
                    import jax.numpy as jnp

                    nxt_j = jnp.take_along_axis(
                        idx, choice[:, None], axis=-1)[:, 0]
                else:
                    nxt_j = jax.random.categorical(key, lo, axis=-1)
                nxt = np.array(nxt_j).reshape(-1, 1).astype("int32")
            else:
                nxt = np.argmax(logits, axis=-1).reshape(-1, 1) \
                        .astype("int32")
            out = np.concatenate([out, nxt], axis=1)
        return out


def gpt2_small(vocab_size=50257, max_length=1024, dropout=0.1):
    """GPT-2 124M configuration."""
    return GPTModel(vocab_size, 768, 3072, 12, 12, max_length, dropout)


def gpt_tiny(vocab_size=1000, max_length=128, dropout=0.1):
    """Tiny config for tests and compile checks."""
    return GPTModel(vocab_size, 64, 128, 2, 4, max_length, dropout)
