"""Model families beyond the vision zoo.

- `bert`: Gluon-API BERT encoder (the reference ecosystem's GluonNLP
  BERT-base, BASELINE.json config 3) built on npx attention ops.
- `sharded_bert`: the same architecture as pure-jax functions with explicit
  dp/tp/sp shardings over a Mesh — the multi-chip flagship path.
"""
from .bert import BERTClassifier, BERTEncoder, BERTModel, TransformerEncoderCell  # noqa: F401
from . import sharded_bert  # noqa: F401
