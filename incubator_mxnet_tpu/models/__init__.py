"""Model families beyond the vision zoo.

- `bert`: Gluon-API BERT encoder (the reference ecosystem's GluonNLP
  BERT-base, BASELINE.json config 3) built on npx attention ops.
- `sharded_bert`: the same architecture as pure-jax functions with explicit
  dp/tp/sp shardings over a Mesh — the multi-chip flagship path.
- `gpt`: decoder-only causal LM (GluonNLP GPT-2 role) over the causal
  flash-attention path, with a sampling `generate` loop.
"""
from .bert import BERTClassifier, BERTEncoder, BERTModel, TransformerEncoderCell  # noqa: F401
from . import gpt  # noqa: F401
from . import sharded_bert  # noqa: F401
