"""BERT (gluon API) — the reference's flagship NLP model family comes from
GluonNLP built on MXNet base ops (SURVEY.md §2.4 notes the reference itself
has no attention kernel; its CPU path fuses self-attention via oneDNN
subgraphs, `src/operator/subgraph/dnnl/dnnl_transformer_qk_property.h`).

Here attention is a first-class op: `use_flash=True` (default) routes
through the pallas flash-attention kernel (`npx.flash_attention` →
`ops/flash_attention.py`), taking `valid_length` directly instead of a
dense (T, T) mask; `use_flash=False` keeps the XLA softmax path with
`npx.masked_softmax`. Note: the flash path applies dropout to the
attention *output* rather than the probability matrix (documented
divergence — prob-dropout would break the online softmax recurrence)."""
from __future__ import annotations

import math

from .. import numpy as np
from .. import numpy_extension as npx
from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter

__all__ = ["MultiHeadAttention", "TransformerEncoderCell", "BERTEncoder",
           "BERTModel", "BERTClassifier", "bert_base", "bert_small",
           "tp_param_shardings"]


def tp_param_shardings(net, tp_axis="tp"):
    """Megatron-style tensor-parallel PartitionSpecs for a gluon BERT.

    Returns a list aligned with `DataParallel`'s trainable-parameter order
    (collect_params values with grad_req != 'null'). Column-parallel layers
    (qkv, ffn1) shard their output dim; row-parallel layers (proj, ffn2)
    shard their input dim; embeddings and the MLM decoder shard the vocab
    dim; norms/bias-only params replicate. XLA's GSPMD inserts the
    all-reduces the reference would route through NCCL."""
    import jax

    P = jax.sharding.PartitionSpec
    specs = []
    for name, p in net.collect_params().items():
        if p.grad_req == "null":
            continue
        if name.endswith(("qkv.weight", "ffn1.weight")):
            specs.append(P(tp_axis, None))
        elif name.endswith(("qkv.bias", "ffn1.bias")):
            specs.append(P(tp_axis))
        elif name.endswith(("proj.weight", "ffn2.weight")):
            specs.append(P(None, tp_axis))
        elif name.endswith(("word_embed.weight", "mlm_decoder.weight")):
            specs.append(P(tp_axis, None))
        elif name.endswith("mlm_decoder.bias"):
            specs.append(P(tp_axis))
        else:
            specs.append(P())
    return specs


class MultiHeadAttention(HybridBlock):
    def __init__(self, units, num_heads, dropout=0.0, use_flash=True):
        super().__init__()
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        self._use_flash = use_flash
        self.qkv = nn.Dense(3 * units, flatten=False, use_bias=True,
                            in_units=units)
        self.proj = nn.Dense(units, flatten=False, use_bias=True,
                             in_units=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None, valid_length=None):
        # x: (N, T, C)
        N, T, C = x.shape
        H = self._num_heads
        d = C // H
        qkv = self.qkv(x)  # (N, T, 3C)
        if self._use_flash and mask is None:
            # stay in the projection layout (N, T, H, d): the attention op
            # contracts it directly ("bthd"), so no head transpose is ever
            # materialized — the relayout copies were ~8% of the seq-512
            # train step
            q = qkv[..., :C].reshape(N, T, H, d)
            k = qkv[..., C:2 * C].reshape(N, T, H, d)
            v = qkv[..., 2 * C:].reshape(N, T, H, d)
            out = npx.flash_attention(q, k, v, valid_length=valid_length,
                                      layout="bthd")
            out = out.reshape(N, T, C)
            if self.dropout is not None:
                out = self.dropout(out)
            return self.proj(out)
        qkv = qkv.reshape(N, T, 3, H, d)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)         # (N, H, T, d)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        q = q.reshape(N * H, T, d)
        k = k.reshape(N * H, T, d)
        v = v.reshape(N * H, T, d)
        if mask is None and valid_length is not None:
            mask = _dense_mask_from_valid_length(x, valid_length, H)
        scores = npx.batch_dot(q, k, transpose_b=True) / math.sqrt(d)
        if mask is not None:
            att = npx.masked_softmax(scores, mask)
        else:
            att = npx.softmax(scores, axis=-1)
        if self.dropout is not None:
            att = self.dropout(att)
        out = npx.batch_dot(att, v)  # (N*H, T, d)
        out = out.reshape(N, H, T, d).transpose(0, 2, 1, 3).reshape(N, T, C)
        return self.proj(out)


def _dense_mask_from_valid_length(x, valid_length, num_heads):
    """(N*H, T, T) pairwise validity mask from (N,) lengths — the
    masked_softmax fallback when flash is disabled."""
    steps = npx.arange_like(x, axis=1)
    m = (steps.reshape(1, -1, 1)
         < valid_length.reshape(-1, 1, 1).astype("float32"))
    m2 = (steps.reshape(1, 1, -1)
          < valid_length.reshape(-1, 1, 1).astype("float32"))
    return np.repeat((m * m2).astype("float32"), num_heads, axis=0)


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu"):
        super().__init__()
        self.ffn1 = nn.Dense(hidden_size, flatten=False, in_units=units)
        self.ffn2 = nn.Dense(units, flatten=False, in_units=hidden_size)
        self._activation = activation
        self._drop_rate = dropout
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        h = npx.activation(self.ffn1(x), act_type=self._activation)
        if self.dropout is not None:
            h = self.dropout(h)
        return self.ffn2(h)


class TransformerEncoderCell(HybridBlock):
    """Pre-LN transformer block (BERT uses post-LN; configurable)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, use_flash=True):
        super().__init__()
        self._pre_norm = pre_norm
        self._drop_rate = dropout
        self.attention = MultiHeadAttention(units, num_heads, dropout,
                                            use_flash=use_flash)
        self.ffn = PositionwiseFFN(units, hidden_size, dropout)
        self.ln1 = nn.LayerNorm(in_channels=units)
        self.ln2 = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x, mask=None, valid_length=None):
        if self._pre_norm:
            h = self.attention(self.ln1(x), mask, valid_length)
            x = x + (self.dropout(h) if self.dropout else h)
            h = self.ffn(self.ln2(x))
            return x + (self.dropout(h) if self.dropout else h)
        h = self.attention(x, mask, valid_length)
        # post-LN residual sites go through the fused
        # residual+dropout+LN op (one pallas pass on TPU)
        x = npx.residual_dropout_ln(x, h, self.ln1.gamma.data(),
                                    self.ln1.beta.data(),
                                    p=self._drop_rate,
                                    eps=self.ln1._epsilon)
        h = self.ffn(x)
        return npx.residual_dropout_ln(x, h, self.ln2.gamma.data(),
                                       self.ln2.beta.data(),
                                       p=self._drop_rate,
                                       eps=self.ln2._epsilon)


class BERTEncoder(HybridBlock):
    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 dropout=0.1, type_vocab_size=2, use_flash=True,
                 seq_shard_axis=None, batch_shard_axis="dp"):
        super().__init__()
        self._units = units
        self._use_flash = use_flash
        # sequence parallelism: shard the T axis of activations between
        # blocks (Megatron-SP layout); axis names resolved against the
        # active mesh, dropped when absent
        self._seq_shard_axis = seq_shard_axis
        self._batch_shard_axis = batch_shard_axis
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(type_vocab_size, units)
        self.position_embed = Parameter(shape=(max_length, units),
                                        init="normal")
        self.ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.layers.add(TransformerEncoderCell(units, hidden_size,
                                                   num_heads, dropout,
                                                   use_flash=use_flash))

    def forward(self, tokens, token_types=None, valid_length=None):
        N, T = tokens.shape
        x = self.word_embed(tokens)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = x + self.position_embed.data()[:T]
        x = self.ln(x)
        if self.dropout is not None:
            x = self.dropout(x)
        sp, ba = self._seq_shard_axis, self._batch_shard_axis
        if sp is not None:
            x = npx.sharding_constraint(x, (ba, sp, None))
        if self._use_flash:
            # flash path: (B,) lengths straight into the kernel, no dense mask
            for cell in self.layers:
                x = cell(x, None, valid_length)
                if sp is not None:
                    x = npx.sharding_constraint(x, (ba, sp, None))
            return x
        mask = None
        if valid_length is not None:
            H = self.layers[0].attention._num_heads
            mask = _dense_mask_from_valid_length(x, valid_length, H)
        for cell in self.layers:
            x = cell(x, mask)
        return x


class BERTModel(HybridBlock):
    """Encoder + MLM and NSP heads (pretraining objective, config 3)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, dropout=0.1,
                 use_flash=True, seq_shard_axis=None, batch_shard_axis="dp"):
        super().__init__()
        self.encoder = BERTEncoder(vocab_size, units, hidden_size, num_layers,
                                   num_heads, max_length, dropout,
                                   use_flash=use_flash,
                                   seq_shard_axis=seq_shard_axis,
                                   batch_shard_axis=batch_shard_axis)
        self.mlm_dense = nn.Dense(units, flatten=False, activation="tanh",
                                  in_units=units)
        self.mlm_ln = nn.LayerNorm(in_channels=units)
        self.mlm_decoder = nn.Dense(vocab_size, flatten=False, in_units=units)
        self.nsp = nn.Dense(2, in_units=units)

    def forward(self, tokens, token_types=None, valid_length=None):
        seq = self.encoder(tokens, token_types, valid_length)
        mlm_scores = self.mlm_decoder(self.mlm_ln(self.mlm_dense(seq)))
        nsp_scores = self.nsp(seq[:, 0])
        return mlm_scores, nsp_scores


class BERTClassifier(HybridBlock):
    def __init__(self, encoder, num_classes=2, dropout=0.1):
        super().__init__()
        self.encoder = encoder
        self.dropout = nn.Dropout(dropout)
        self.classifier = nn.Dense(num_classes)

    def forward(self, tokens, token_types=None, valid_length=None):
        seq = self.encoder(tokens, token_types, valid_length)
        pooled = seq[:, 0]
        return self.classifier(self.dropout(pooled))


def bert_base(vocab_size=30522, max_length=512, dropout=0.1, use_flash=True,
              seq_shard_axis=None):
    return BERTModel(vocab_size, 768, 3072, 12, 12, max_length, dropout,
                     use_flash=use_flash, seq_shard_axis=seq_shard_axis)


def bert_small(vocab_size=1000, max_length=128, dropout=0.1, use_flash=True,
               seq_shard_axis=None):
    """Tiny config for tests and compile-checks."""
    return BERTModel(vocab_size, 64, 128, 2, 4, max_length, dropout,
                     use_flash=use_flash, seq_shard_axis=seq_shard_axis)
