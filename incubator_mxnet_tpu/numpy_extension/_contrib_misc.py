"""Contrib operator corpus: the reference's `src/operator/contrib/` long
tail re-implemented as jax lowerings.

Reference files cited per op. Backward passes the reference hand-writes
(`_backward_hawkesll`, `_backward_index_copy`, STE grads, …) come from
`jax.vjp` or `jax.custom_vjp` here.
"""
from __future__ import annotations

import math

from ..ndarray.ndarray import (
    NDArray, apply_op, apply_op_flat, unwrap_arrays,
)

__all__ = [
    "quadratic", "index_copy", "index_array", "gradientmultiplier",
    "dynamic_reshape", "count_sketch", "hawkesll", "round_ste", "sign_ste",
    "all_finite", "multi_all_finite", "ctc_loss", "adaptive_avg_pooling2d",
    "bilinear_resize2d", "batch_norm_with_relu", "sync_batch_norm",
    "softsign", "pad", "norm", "slice", "slice_channel", "add_n",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _jax():
    import jax

    return jax


def quadratic(data, a=0.0, b=0.0, c=0.0):
    """a·x² + b·x + c (reference `contrib/quadratic_op.cc` — the tutorial
    op; kept for script parity)."""
    return apply_op("quadratic",
                    lambda x: a * x * x + b * x + c, (data,),
                    static_info=("abc", float(a), float(b), float(c)))


def index_copy(old_tensor, index_vector, new_tensor):
    """Functional row copy: out = old with rows at `index_vector`
    replaced by `new_tensor` (reference `contrib/index_copy.cc`)."""
    def fn(old, idx, new):
        return old.at[idx.astype("int32")].set(new)

    return apply_op("index_copy", fn,
                    (old_tensor, index_vector, new_tensor))


def index_array(data, axes=None):
    """Index grid of `data`: output shape data.shape + (len(axes),)
    holding each position's coordinates (reference
    `contrib/index_array.cc`)."""
    axes_t = None if axes is None else tuple(int(a) for a in axes)

    def fn(x):
        jnp = _jnp()
        sel = axes_t if axes_t is not None else tuple(range(x.ndim))
        grids = jnp.meshgrid(*[jnp.arange(n) for n in x.shape],
                             indexing="ij")
        return jnp.stack([grids[a] for a in sel], axis=-1).astype("int64")

    return apply_op("index_array", fn, (data,),
                    static_info=("axes", axes_t))


def gradientmultiplier(data, scalar=1.0):
    """Identity forward, gradient scaled by `scalar` (reference
    `contrib/gradient_multiplier_op.cc` — GRL / DANN training)."""
    jax = _jax()
    s = float(scalar)

    @jax.custom_vjp
    def f(x):
        return x

    f.defvjp(lambda x: (x, None), lambda _, g: (g * s,))
    return apply_op("gradientmultiplier", f, (data,),
                    static_info=("scalar", s))


def dynamic_reshape(data, shape_like):
    """Reshape `data` to the VALUES held in `shape_like` (reference
    `contrib/dynamic_shape_ops.cc`). Eager-only by nature: the target
    shape is data-dependent, which XLA cannot trace — same reason the
    reference marks it FComputeEx-only."""
    target = tuple(int(v) for v in shape_like.asnumpy().astype("int64"))
    return apply_op("dynamic_reshape", lambda x: x.reshape(target),
                    (data,), static_info=("shape", target))


def count_sketch(data, h, s, out_dim, processing_batch_size=32):  # noqa: ARG001
    """Count sketch projection (reference `contrib/count_sketch.cc`):
    out[n, h[j]] += s[j] · data[n, j], h/s the hash index/sign vectors."""
    od = int(out_dim)

    def fn(x, hh, ss):
        jnp = _jnp()
        n = x.shape[0]
        out = jnp.zeros((n, od), x.dtype)
        idx = hh.astype("int32")
        return out.at[:, idx].add(x * ss[None, :].astype(x.dtype))

    return apply_op("count_sketch", fn, (data, h, s),
                    static_info=("out_dim", od))


def hawkesll(lda, alpha, beta, state, lags, marks, valid_length, max_time):
    """Hawkes process log-likelihood (reference `contrib/hawkes_ll.cc`,
    kernel at `hawkes_ll-inl.h:120`): returns (loglike (N,), out_state
    (N, K)). lax.scan replaces the per-sample sequential CPU kernel —
    the T-loop carries (state, last-event-time, t, ll) per sample, and
    jax.vjp provides the gradients the reference hand-derives."""
    def fn(mu, a, b, st0, lg, mk, vlen, mtime):
        jnp = _jnp()
        jax = _jax()
        n, t_len = lg.shape
        k = st0.shape[1]
        mk = mk.astype("int32")

        def step(carry, inp):
            state, last, t, ll, j = carry
            lag_j, mark_j = inp            # (N,), (N,) int
            t = t + lag_j
            onehot = jax.nn.one_hot(mark_j, k, dtype=st0.dtype)  # (N,K)
            d = t - jnp.sum(last * onehot, axis=1)               # (N,)
            bk = b[mark_j]
            ed = jnp.exp(-bk * d)
            mu_k = jnp.sum(mu * onehot, axis=1)
            st_k = jnp.sum(state * onehot, axis=1)
            lam = mu_k + a[mark_j] * bk * st_k * ed
            comp = mu_k * d + a[mark_j] * st_k * (1.0 - ed)
            valid = (j < vlen).astype(st0.dtype)                 # (N,)
            ll = ll + valid * (jnp.log(lam) - comp)
            new_state = state + onehot * ((1.0 + st_k * ed)[:, None]
                                          - state)
            new_last = last + onehot * (t[:, None] - last)
            state = jnp.where((valid > 0)[:, None], new_state, state)
            last = jnp.where((valid > 0)[:, None], new_last, last)
            return (state, last, t, ll, j + 1), None

        init = (st0, jnp.zeros((n, k), st0.dtype),
                jnp.zeros((n,), st0.dtype), jnp.zeros((n,), st0.dtype),
                jnp.zeros((n,), "int32"))
        (state, last, _t, ll, _j), _ = jax.lax.scan(
            step, init, (lg.T, mk.T))
        # remaining compensator to max_time + state decay
        # (hawkesll_forward_compensator, hawkes_ll-inl.h:169)
        d = mtime[:, None] - last                               # (N,K)
        ed = jnp.exp(-b[None, :] * d)
        rem = mu * d + a[None, :] * state * (1.0 - ed)
        return ll - jnp.sum(rem, axis=1), state * ed

    return apply_op("hawkesll", fn,
                    (lda, alpha, beta, state, lags, marks,
                     valid_length, max_time), n_outputs=2)


def _ste(name, fwd):
    jax = _jax()

    @jax.custom_vjp
    def f(x):
        return fwd(x)

    f.defvjp(lambda x: (fwd(x), None), lambda _, g: (g,))
    f.__name__ = name
    return f


def round_ste(data):
    """round() with straight-through gradient (reference
    `contrib/stes_op.cc` — quantization-aware training)."""
    return apply_op("round_ste", _ste("round_ste", lambda x: _jnp().round(x)),
                    (data,))


def sign_ste(data):
    """sign() with straight-through gradient (reference
    `contrib/stes_op.cc`)."""
    return apply_op("sign_ste", _ste("sign_ste", lambda x: _jnp().sign(x)),
                    (data,))


def all_finite(data, init_output=True):  # noqa: ARG001
    """1 iff every element is finite (reference
    `contrib/all_finite.cc` — AMP overflow check)."""
    return apply_op(
        "all_finite",
        lambda x: _jnp().isfinite(x).all().astype("float32").reshape(1),
        (data,))


def multi_all_finite(*arrays, num_arrays=None, init_output=True):  # noqa: ARG001
    """AND of all_finite over a list of arrays (reference
    `contrib/all_finite.cc`)."""
    arrs = unwrap_arrays(arrays)

    def fn(xs):
        jnp = _jnp()
        ok = jnp.array(True)
        for x in xs:
            ok = ok & jnp.isfinite(x).all()
        return ok.astype("float32").reshape(1)

    return apply_op_flat("multi_all_finite", fn, (arrs,))


def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist Temporal Classification loss (reference
    `src/operator/nn/ctc_loss.cc`; the reference wraps warp-ctc /
    cuDNN-CTC — here the standard log-domain alpha recursion runs as a
    `lax.scan` over time, so XLA vectorizes over batch and the gradient
    is `jax.vjp` of the recursion).

    data (T, B, C) unnormalized activations (softmax applied inside,
    like the reference), label (B, L). Returns (B,) negative
    log-likelihood. `blank_label` 'first' → blank=0 (labels 1-based) or
    'last' → blank=C-1."""
    if blank_label not in ("first", "last"):
        raise ValueError("blank_label must be 'first' or 'last'")

    def fn(x, lab, dlen, llen):
        jnp = _jnp()
        jax = _jax()
        t_max, b, c = x.shape
        l_max = lab.shape[1]
        blank = 0 if blank_label == "first" else c - 1
        logp = jax.nn.log_softmax(x.astype("float32"), axis=-1)
        lab = lab.astype("int32")
        s_len = 2 * l_max + 1
        neg_inf = jnp.float32(-1e30)

        # extended label: [blank, l1, blank, l2, ..., blank]
        ext = jnp.full((b, s_len), blank, dtype="int32")
        ext = ext.at[:, 1::2].set(lab)
        pos = jnp.arange(s_len)[None, :]
        valid_s = pos < (2 * llen[:, None] + 1)
        # skip transition allowed where ext[s] != blank and != ext[s-2]
        ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)),
                         constant_values=blank)[:, :s_len]
        can_skip = (ext != blank) & (ext != ext_m2) & (pos >= 2)

        alpha0 = jnp.full((b, s_len), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
        first_lab = ext[:, 1]
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[0], first_lab[:, None],
                                axis=1)[:, 0])
        alpha0 = jnp.where(valid_s & (pos <= 1), alpha0, neg_inf)

        def step(alpha, inp):
            logp_t, t = inp
            a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                           constant_values=-1e30)[:, :s_len]
            a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                           constant_values=-1e30)[:, :s_len]
            a_m2 = jnp.where(can_skip, a_m2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_m1), a_m2)
            emit = jnp.take_along_axis(logp_t, ext, axis=1)
            new = jnp.where(valid_s, merged + emit, neg_inf)
            # past this sample's input length the lattice is frozen
            new = jnp.where((t < dlen)[:, None], new, alpha)
            return new, None

        ts = jnp.arange(1, t_max)
        alpha, _ = jax.lax.scan(step, alpha0, (logp[1:], ts))
        end = 2 * llen[:, None]                 # final blank position
        a_end = jnp.take_along_axis(alpha, end, axis=1)[:, 0]
        a_last = jnp.take_along_axis(
            alpha, jnp.maximum(end - 1, 0), axis=1)[:, 0]
        # empty label: only the all-blank path exists
        a_last = jnp.where(llen > 0, a_last, neg_inf)
        return -jnp.logaddexp(a_end, a_last)

    import numpy as onp

    t_max, b, _c = data.shape
    l_max = label.shape[1]
    if data_lengths is None or not use_data_lengths:
        data_lengths = NDArray(_jnp().full((b,), t_max, dtype="int32"))
    if label_lengths is None or not use_label_lengths:
        # reference convention without explicit lengths: count labels
        # until the first padding value (-1 or 0 for blank='first')
        pad_v = 0 if blank_label == "first" else -1
        lab_np = label.asnumpy().astype("int64")
        lens = onp.full((b,), l_max, dtype="int32")
        for i in range(b):
            nz = onp.where(lab_np[i] == pad_v)[0]
            if nz.size:
                lens[i] = nz[0]
        label_lengths = NDArray(_jnp().asarray(lens))
    return apply_op("ctc_loss", fn,
                    (data, label, data_lengths, label_lengths),
                    static_info=("blank", blank_label))


def adaptive_avg_pooling2d(data, output_size=1):
    """NCHW adaptive average pooling (reference
    `contrib/adaptive_avg_pooling.cc`): bin i covers
    [floor(i·H/out), ceil((i+1)·H/out)) — exact reference binning."""
    if isinstance(output_size, int):
        oh = ow = int(output_size)
    else:
        oh, ow = (int(v) for v in output_size)

    def fn(x):
        jnp = _jnp()
        n, c, h, w = x.shape
        rows = []
        for i in range(oh):
            h0, h1 = (i * h) // oh, -((-(i + 1) * h) // oh)
            cols = []
            for j in range(ow):
                w0, w1 = (j * w) // ow, -((-(j + 1) * w) // ow)
                cols.append(x[:, :, h0:h1, w0:w1].mean(axis=(2, 3)))
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)

    return apply_op("adaptive_avg_pooling2d", fn, (data,),
                    static_info=("out", oh, ow))


def bilinear_resize2d(data, height=None, width=None, scale_height=None,
                      scale_width=None, mode="size"):  # noqa: ARG001
    """NCHW bilinear resize with align-corners sampling (reference
    `contrib/bilinear_resize.cc` uses the (in-1)/(out-1) grid)."""
    def fn(x):
        jnp = _jnp()
        n, c, h, w = x.shape
        oh = int(height) if height else int(round(h * scale_height))
        ow = int(width) if width else int(round(w * scale_width))
        ys = (jnp.arange(oh) * ((h - 1) / max(oh - 1, 1))
              if oh > 1 else jnp.zeros((1,)))
        xs = (jnp.arange(ow) * ((w - 1) / max(ow - 1, 1))
              if ow > 1 else jnp.zeros((1,)))
        y0 = jnp.floor(ys).astype("int32")
        x0 = jnp.floor(xs).astype("int32")
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0).astype(x.dtype)[None, None, :, None]
        wx = (xs - x0).astype(x.dtype)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]  # noqa: E731
        top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
        bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
        return top * (1 - wy) + bot * wy

    return apply_op("bilinear_resize2d", fn, (data,),
                    static_info=("hw", height, width,
                                 scale_height, scale_width))


def batch_norm_with_relu(x, gamma, beta, running_mean, running_var,
                         **kwargs):
    """BatchNorm fused with ReLU (reference `contrib/batch_norm_relu.cc`);
    XLA fuses the relu epilogue into the normalization kernel."""
    from . import batch_norm, relu

    return relu(batch_norm(x, gamma, beta, running_mean, running_var,
                           **kwargs))


def sync_batch_norm(x, gamma, beta, moving_mean, moving_var, key=None,
                    ndev=1, **kwargs):  # noqa: ARG001
    """Cross-device BatchNorm (reference `contrib/sync_batch_norm.cc`).
    Under pjit with a batch-sharded input, XLA computes the GLOBAL batch
    statistics automatically (reductions span the sharded axis), so this
    lowers to plain batch_norm — the `key`/`ndev` machinery the
    reference needs for explicit cross-GPU reduction has no analogue.
    For explicit shard_map code, `gluon.nn.SyncBatchNorm` inserts the
    psum."""
    from . import batch_norm

    return batch_norm(x, gamma, beta, moving_mean, moving_var, **kwargs)


def softsign(data):
    """x / (1 + |x|) (reference `mshadow_op.h` softsign)."""
    return apply_op("softsign", lambda x: x / (1 + _jnp().abs(x)), (data,))


def pad(data, mode="constant", pad_width=None, constant_value=0.0):
    """Reference `src/operator/pad.cc`: pad_width is a flat 2·ndim tuple
    (before, after per axis); modes constant/edge/reflect."""
    pw = tuple(int(v) for v in pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    jmode = {"constant": "constant", "edge": "edge",
             "reflect": "reflect"}[mode]

    def fn(x):
        jnp = _jnp()
        if jmode == "constant":
            return jnp.pad(x, pairs, mode="constant",
                           constant_values=constant_value)
        return jnp.pad(x, pairs, mode=jmode)

    return apply_op("pad", fn, (data,),
                    static_info=("pw", pairs, mode, float(constant_value)))


def norm(data, ord=2, axis=None, keepdims=False, out=None):  # noqa: A002
    """Matrix/vector norm op (reference `src/operator/tensor/broadcast_
    reduce_norm_value.cc`)."""
    if ord not in (1, 2):
        raise ValueError(f"npx.norm supports ord 1 or 2, got {ord!r}")
    ax = axis if axis is None or isinstance(axis, int) \
        else tuple(int(a) for a in axis)
    from . import _safe_accumulation

    safe = _safe_accumulation()

    def fn(x):
        jnp = _jnp()
        in_dt = x.dtype
        if safe and str(in_dt) in ("float16", "bfloat16"):
            x = x.astype("float32")
        if ord == 1:
            out = jnp.abs(x).sum(axis=ax, keepdims=keepdims)
        else:
            out = jnp.sqrt((x * x).sum(axis=ax, keepdims=keepdims))
        return out.astype(in_dt) if safe else out

    return apply_op("norm", fn, (data,),
                    static_info=("ord", ord, ax, keepdims), out=out)


def slice(data, begin, end, step=None):  # noqa: A001
    """Reference `slice` op (src/operator/tensor/matrix_op.cc): None
    entries in begin/end mean 'from the edge'."""
    import builtins

    begin = tuple(begin)
    end = tuple(end)
    step = tuple(step) if step is not None else (None,) * len(begin)
    keys = tuple(builtins.slice(b, e, s)
                 for b, e, s in zip(begin, end, step))
    return apply_op("slice", lambda x: x[keys], (data,),
                    static_info=("bes", begin, end, step))


def slice_channel(data, num_outputs, axis=1, squeeze_axis=False):
    """SliceChannel / split into num_outputs along axis (reference
    `src/operator/slice_channel.cc`). Returns a list."""
    n = int(num_outputs)

    def fn(x):
        jnp = _jnp()
        parts = jnp.split(x, n, axis=axis)
        if squeeze_axis:
            parts = [p.squeeze(axis) for p in parts]
        return tuple(parts)

    return list(apply_op("slice_channel", fn, (data,), n_outputs=n,
                         static_info=("n", n, axis, bool(squeeze_axis))))


def add_n(*args):
    """Sum of a list of arrays in one fused kernel (reference
    `src/operator/tensor/elemwise_sum.cc`)."""
    arrs = unwrap_arrays(args)

    def fn(xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out

    return apply_op_flat("add_n", fn, (arrs,))
