"""Graph-sampling contrib ops (reference `src/operator/contrib/
dgl_graph.cc` — the DGL integration surface) plus `edge_id` / `getnnz` /
`dgl_adjacency`.

Design note: the reference registers every one of these CPU-only
(`FComputeEx<cpu>`) — they are data-preparation ops that walk ragged CSR
structure, the part of a GNN pipeline that stays on host while the dense
message-passing math runs on the accelerator. The TPU-native translation
keeps that split: host numpy over the CSR fields, results wrapped back
into `CSRNDArray`/`NDArray` for the device compute that follows.
"""
from __future__ import annotations

import numpy as onp

from ..ndarray.ndarray import NDArray
from ..ndarray.sparse import CSRNDArray

__all__ = [
    "edge_id", "getnnz", "dgl_adjacency", "dgl_subgraph",
    "dgl_csr_neighbor_uniform_sample", "dgl_csr_neighbor_non_uniform_sample",
    "dgl_graph_compact",
]


def _csr_fields(g):
    if not isinstance(g, CSRNDArray):
        raise TypeError("graph must be a CSRNDArray")
    return (onp.asarray(g._sp_data), onp.asarray(g._sp_col_indices),
            onp.asarray(g._sp_indptr), g._sp_shape)


def _jnp():
    import jax.numpy as jnp

    return jnp


def edge_id(data, u, v):
    """edge_id(csr, u, v)[i] = csr[u[i], v[i]] if the edge exists else -1
    (reference dgl_graph.cc:1326)."""
    vals, cols, indptr, _shape = _csr_fields(data)
    un = onp.asarray(u.asnumpy(), onp.int64)
    vn = onp.asarray(v.asnumpy(), onp.int64)
    out = onp.full(un.shape, -1.0, onp.float32)
    for i, (r, c) in enumerate(zip(un, vn)):
        lo, hi = indptr[r], indptr[r + 1]
        hit = onp.where(cols[lo:hi] == c)[0]
        if hit.size:
            out[i] = vals[lo + hit[0]]
    return NDArray(_jnp().asarray(out))


def getnnz(data, axis=None):
    """Stored-value count of a CSR matrix, total or per row/column
    (reference `src/operator/contrib/nnz.cc`)."""
    _vals, cols, indptr, shape = _csr_fields(data)
    if axis is None:
        return NDArray(_jnp().asarray(
            onp.array([indptr[-1]], onp.int64)))
    if axis == 0:   # per column
        cnt = onp.zeros(shape[1], onp.int64)
        onp.add.at(cnt, cols, 1)
        return NDArray(_jnp().asarray(cnt))
    if axis == 1:   # per row
        return NDArray(_jnp().asarray(onp.diff(indptr).astype(onp.int64)))
    raise ValueError("axis must be None, 0 or 1")


def dgl_adjacency(data):
    """Adjacency matrix of a graph CSR: same structure, data all 1.0
    (reference dgl_graph.cc:1402)."""
    _vals, cols, indptr, shape = _csr_fields(data)
    return CSRNDArray(onp.ones(len(cols), onp.float32), cols, indptr,
                      shape)


def _induced_subgraph(vals, cols, indptr, vids):
    """Rows/cols restricted to `vids` (renumbered by position). Returns
    (new_data 1..n row-major, orig_data, new_cols, new_indptr)."""
    vset = {int(v): i for i, v in enumerate(vids)}
    new_data, orig_data, new_cols = [], [], []
    new_indptr = [0]
    eid = 1
    for v in vids:
        lo, hi = indptr[v], indptr[v + 1]
        for k in range(lo, hi):
            c = int(cols[k])
            if c in vset:
                new_data.append(eid)
                orig_data.append(vals[k])
                new_cols.append(vset[c])
                eid += 1
        new_indptr.append(len(new_cols))
    return (onp.asarray(new_data, onp.float32),
            onp.asarray(orig_data, onp.float32),
            onp.asarray(new_cols, onp.int32),
            onp.asarray(new_indptr, onp.int32))


def dgl_subgraph(graph, *varrays, return_mapping=False, num_args=None):  # noqa: ARG001
    """Induced subgraph per vertex set (reference dgl_graph.cc:1130):
    first output per set has renumbered edge ids 1..n, and with
    `return_mapping` a second CSR carries the original edge ids."""
    vals, cols, indptr, _shape = _csr_fields(graph)
    outs, mappings = [], []
    for va in varrays:
        vids = onp.asarray(va.asnumpy(), onp.int64).reshape(-1)
        nd, od, nc, ni = _induced_subgraph(vals, cols, indptr, vids)
        n = len(vids)
        outs.append(CSRNDArray(nd, nc, ni, (n, n)))
        if return_mapping:
            mappings.append(CSRNDArray(od, nc, ni, (n, n)))
    return outs + mappings if return_mapping else outs


def _neighbor_sample(vals, cols, indptr, seeds, num_hops, num_neighbor,
                     max_num_vertices, prob=None, rng=None):
    rng = rng or onp.random
    sampled = list(dict.fromkeys(int(s) for s in seeds))
    layer = {v: 0 for v in sampled}
    edges = {}                      # (src, dst) -> orig edge value
    frontier = list(sampled)
    for hop in range(1, num_hops + 1):
        nxt = []
        for v in frontier:
            lo, hi = int(indptr[v]), int(indptr[v + 1])
            nbrs = onp.arange(lo, hi)
            if len(nbrs) > num_neighbor:
                if prob is not None:
                    p = prob[cols[lo:hi]].astype(onp.float64)
                    p = p / p.sum()
                    nbrs = rng.choice(nbrs, num_neighbor, replace=False,
                                      p=p)
                else:
                    nbrs = rng.choice(nbrs, num_neighbor, replace=False)
                nbrs = onp.sort(nbrs)
            for k in nbrs:
                c = int(cols[k])
                if len(sampled) >= max_num_vertices and c not in layer:
                    continue
                if c not in layer:
                    layer[c] = hop
                    sampled.append(c)
                    nxt.append(c)
                edges[(v, c)] = vals[k]
        frontier = nxt
    sampled = sampled[:max_num_vertices]
    return sampled, layer, edges


def _sample_outputs(sampled, layer, edges, max_num_vertices, prob=None):
    jnp = _jnp()
    n = len(sampled)
    verts = onp.zeros(max_num_vertices + 1, onp.int64)
    verts[:n] = sampled
    verts[-1] = n
    ren = {v: i for i, v in enumerate(sampled)}
    rows = [[] for _ in range(max_num_vertices)]
    for (s, d), val in edges.items():
        if s in ren and d in ren:
            rows[ren[s]].append((ren[d], val))
    data, cidx = [], []
    indptr = [0]
    for r in rows:
        for c, val in sorted(r):
            cidx.append(c)
            data.append(val)
        indptr.append(len(cidx))
    sub = CSRNDArray(onp.asarray(data, onp.float32),
                     onp.asarray(cidx, onp.int32),
                     onp.asarray(indptr, onp.int32),
                     (max_num_vertices, max_num_vertices))
    layers = onp.full(max_num_vertices, -1, onp.int64)
    for i, v in enumerate(sampled):
        layers[i] = layer[v]
    out = [NDArray(jnp.asarray(verts)), sub]
    if prob is not None:
        pr = onp.zeros(max_num_vertices, onp.float32)
        for i, v in enumerate(sampled):
            pr[i] = prob[v]
        out.append(NDArray(jnp.asarray(pr)))
    out.append(NDArray(jnp.asarray(layers)))
    return out


def dgl_csr_neighbor_uniform_sample(csr, *seed_arrays, num_args=None,  # noqa: ARG001
                                    num_hops=1, num_neighbor=2,
                                    max_num_vertices=100):
    """Uniform neighborhood sampling for DGL (dgl_graph.cc:738). Per
    seed array returns [vertices (max+1, last = count), sampled-edge
    CSR, layer ids]."""
    vals, cols, indptr, _shape = _csr_fields(csr)
    outs = [[], [], []]
    for sa in seed_arrays:
        seeds = onp.asarray(sa.asnumpy(), onp.int64).reshape(-1)
        sampled, layer, edges = _neighbor_sample(
            vals, cols, indptr, seeds, int(num_hops), int(num_neighbor),
            int(max_num_vertices))
        o = _sample_outputs(sampled, layer, edges, int(max_num_vertices))
        for i in range(3):
            outs[i].append(o[i])
    flat = outs[0] + outs[1] + outs[2]
    return flat if len(seed_arrays) > 1 else \
        [outs[0][0], outs[1][0], outs[2][0]]


def dgl_csr_neighbor_non_uniform_sample(csr, prob, *seed_arrays,
                                        num_args=None, num_hops=1,  # noqa: ARG001
                                        num_neighbor=2,
                                        max_num_vertices=100):
    """Probability-weighted neighborhood sampling (dgl_graph.cc:842):
    adds a per-vertex probability output after the edge CSR."""
    vals, cols, indptr, _shape = _csr_fields(csr)
    pn = onp.asarray(prob.asnumpy(), onp.float32).reshape(-1)
    outs = [[], [], [], []]
    for sa in seed_arrays:
        seeds = onp.asarray(sa.asnumpy(), onp.int64).reshape(-1)
        sampled, layer, edges = _neighbor_sample(
            vals, cols, indptr, seeds, int(num_hops), int(num_neighbor),
            int(max_num_vertices), prob=pn)
        o = _sample_outputs(sampled, layer, edges, int(max_num_vertices),
                            prob=pn)
        for i in range(4):
            outs[i].append(o[i])
    flat = outs[0] + outs[1] + outs[2] + outs[3]
    return flat if len(seed_arrays) > 1 else \
        [outs[0][0], outs[1][0], outs[2][0], outs[3][0]]


def dgl_graph_compact(*args, graph_sizes=None, return_mapping=False,
                      num_args=None):  # noqa: ARG001
    """Strip the trailing empty rows/columns a neighbor-sample CSR
    carries (dgl_graph.cc compact op). Inputs: N sampled CSRs followed
    by their N vertex arrays; `graph_sizes` the true vertex counts."""
    n_graphs = len(args) // 2
    graphs = args[:n_graphs]
    vert_arrays = args[n_graphs:]
    sizes = graph_sizes if isinstance(graph_sizes, (list, tuple)) \
        else [graph_sizes] * n_graphs
    outs, mappings = [], []
    for g, _va, size in zip(graphs, vert_arrays, sizes):
        vals, cols, indptr, _shape = _csr_fields(g)
        size = int(size)
        keep = indptr[size]
        nd = onp.arange(1, keep + 1, dtype=onp.float32)
        outs.append(CSRNDArray(nd, cols[:keep], indptr[:size + 1],
                               (size, size)))
        mappings.append(CSRNDArray(vals[:keep], cols[:keep],
                                   indptr[:size + 1], (size, size)))
    return outs + mappings if return_mapping else \
        (outs if n_graphs > 1 else outs[0])
