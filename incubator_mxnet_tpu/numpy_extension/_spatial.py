"""Spatial-transform / optical-flow operator family (reference:
`src/operator/spatial_transformer.cc`, `grid_generator.cc`,
`bilinear_sampler.cc`, `roi_pooling.cc`, `correlation.cc`,
`src/operator/contrib/deformable_convolution.cc`, `src/operator/contrib/fft/`).

TPU-native: everything lowers to gathers + matmuls with static shapes —
bilinear sampling is a 4-corner gather, deformable conv is im2col-at-offsets
followed by one big MXU matmul, correlation is a displacement-stacked
windowed reduction. All ops jit/grad cleanly through the funnel.
"""
from __future__ import annotations

from ..ndarray.ndarray import apply_op_flat

__all__ = ["grid_generator", "bilinear_sampler", "spatial_transformer",
           "roi_pooling", "correlation", "deformable_convolution",
           "fft", "ifft"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _bilinear_nchw(img, gy, gx, padding="zero"):
    """Sample img (C, H, W) at float pixel coords gy/gx (...,) → (C, ...).

    padding="zero": out-of-range corners contribute 0, matching the
    reference sampler (`src/operator/bilinear_sampler-inl.h` accumulates
    only corners inside [0, W-1]×[0, H-1]). padding="border": clamp to the
    edge (the ROI-op convention)."""
    jnp = _jnp()
    c, h, w = img.shape
    y0 = jnp.floor(gy)
    x0 = jnp.floor(gx)
    wy = gy - y0
    wx = gx - x0

    def at(yi, xi):
        ci = jnp.clip(yi.astype("int32"), 0, h - 1)
        cj = jnp.clip(xi.astype("int32"), 0, w - 1)
        v = img[:, ci, cj]  # (C, ...)
        if padding == "zero":
            inside = ((yi >= 0) & (yi <= h - 1) & (xi >= 0)
                      & (xi <= w - 1)).astype(img.dtype)
            v = v * inside
        return v

    v00 = at(y0, x0)
    v01 = at(y0, x0 + 1)
    v10 = at(y0 + 1, x0)
    v11 = at(y0 + 1, x0 + 1)
    del c
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def grid_generator(data, transform_type="affine", target_shape=None):
    """Generate a sampling grid (reference: `src/operator/grid_generator.cc`).

    affine: data (N, 6) row-major 2×3 matrices → grid (N, 2, H, W) of
    normalized [-1,1] (x, y) coords. warp: data (N, 2, H, W) pixel flow
    added to the identity grid and normalized."""
    if transform_type == "affine":
        if target_shape is None:
            raise ValueError("grid_generator(affine): target_shape required")
        h, w = target_shape

        def fn(theta):
            jnp = _jnp()
            n = theta.shape[0]
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
            gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
            # elementwise affine (NOT a matmul): grid coordinates must stay
            # full f32 — the TPU MXU's bf16 default would quantize them
            t = theta.reshape(n, 6)[:, :, None, None]
            ox = t[:, 0] * gx + t[:, 1] * gy + t[:, 2]
            oy = t[:, 3] * gx + t[:, 4] * gy + t[:, 5]
            return jnp.stack([ox, oy], 1)

        return apply_op_flat("grid_generator", fn, (data,), {})

    if transform_type == "warp":
        def fn(flow):
            jnp = _jnp()
            n, _, h2, w2 = flow.shape
            gy, gx = jnp.meshgrid(jnp.arange(h2, dtype=flow.dtype),
                                  jnp.arange(w2, dtype=flow.dtype),
                                  indexing="ij")
            x = flow[:, 0] + gx
            y = flow[:, 1] + gy
            xn = x / max((w2 - 1) / 2.0, 1e-12) - 1.0
            yn = y / max((h2 - 1) / 2.0, 1e-12) - 1.0
            return jnp.stack([xn, yn], 1)

        return apply_op_flat("grid_generator", fn, (data,), {})
    raise ValueError(f"unknown transform_type {transform_type!r}")


def bilinear_sampler(data, grid, cudnn_off=None):  # noqa: ARG001
    """Sample data with a normalized grid (reference:
    `src/operator/bilinear_sampler.cc`). data (N, C, H, W); grid
    (N, 2, h, w) with channel 0 = x, 1 = y in [-1, 1]."""
    def fn(x, g):
        jnp = _jnp()
        import jax

        _, _, h, w = x.shape
        gx = (g[:, 0] + 1.0) * (w - 1) / 2.0
        gy = (g[:, 1] + 1.0) * (h - 1) / 2.0
        return jax.vmap(_bilinear_nchw)(x, gy, gx)

    return apply_op_flat("bilinear_sampler", fn, (data, grid), {})


def spatial_transformer(data, loc, target_shape=None,
                        transform_type="affine", sampler_type="bilinear",
                        cudnn_off=None):  # noqa: ARG001
    """Affine spatial transformer network head (reference:
    `src/operator/spatial_transformer.cc`): grid_generator + sampler."""
    if transform_type != "affine" or sampler_type != "bilinear":
        raise ValueError("spatial_transformer supports affine/bilinear only")
    if target_shape is None:
        target_shape = data.shape[2:]
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


def roi_pooling(data, rois, pooled_size, spatial_scale=1.0):
    """Max ROI pooling (reference: `src/operator/roi_pooling.cc`).
    data (N, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2].

    Divergence from the reference: bins max over a fixed 2×2 bilinear
    sample lattice per bin (static shapes for XLA) instead of the
    data-dependent integer pixel partition; values agree for axis-aligned
    integer ROIs and stay within one interpolation step otherwise."""
    def fn(x, r):
        jnp = _jnp()
        import jax

        ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
                  else (pooled_size, pooled_size))
        ns = 2

        def one_roi(roi):
            bidx = roi[0].astype("int32")
            x1, y1 = roi[1] * spatial_scale, roi[2] * spatial_scale
            x2, y2 = roi[3] * spatial_scale, roi[4] * spatial_scale
            rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
            rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
            gy = (y1 + (jnp.arange(ph)[:, None] + (jnp.arange(ns)[None, :]
                  + 0.5) / ns) * (rh / ph)).reshape(-1)
            gx = (x1 + (jnp.arange(pw)[:, None] + (jnp.arange(ns)[None, :]
                  + 0.5) / ns) * (rw / pw)).reshape(-1)
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            samples = _bilinear_nchw(x[bidx], yy, xx,
                                     padding="border")  # (C, ph*ns, pw*ns)
            c = samples.shape[0]
            samples = samples.reshape(c, ph, ns, pw, ns)
            return samples.max(axis=(2, 4))

        return jax.vmap(one_roi)(r)

    return apply_op_flat("roi_pooling", fn, (data, rois), {})


def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference: `src/operator/correlation.cc`).
    data1/data2 (N, C, H, W) → (N, D*D, H', W') where D = 2*(d//s2)+1.

    Each displacement channel is mean over channels (and the k×k patch
    window) of elementwise products (is_multiply) or |a−b| differences —
    expressed as a shift + windowed average so the whole op is one fused
    XLA program rather than a custom kernel."""
    def fn(a, b):
        jnp = _jnp()

        _, _, h, w = a.shape
        k = int(kernel_size)
        d = int(max_displacement)
        s1, s2, p = int(stride1), int(stride2), int(pad_size)
        br = d // s2
        disp = [(dy * s2, dx * s2) for dy in range(-br, br + 1)
                for dx in range(-br, br + 1)]
        ap = jnp.pad(a, ((0, 0), (0, 0), (p, p), (p, p)))
        bp = jnp.pad(b, ((0, 0), (0, 0), (p, p), (p, p)))

        def win_mean(x):
            # k×k window mean via reduce_window
            if k == 1:
                return x
            import jax.lax as lax

            s = lax.reduce_window(x, 0.0, lax.add, (1, 1, k, k),
                                  (1, 1, 1, 1), "SAME")
            return s / float(k * k)

        chans = []
        for dy, dx in disp:
            shifted = jnp.roll(bp, (-dy, -dx), axis=(2, 3))
            prod = ap * shifted if is_multiply else jnp.abs(ap - shifted)
            chans.append(win_mean(prod).mean(axis=1))  # (N, H+2p, W+2p)
        out = jnp.stack(chans, axis=1)
        # crop the displacement+kernel border (reference: border_size =
        # max_displacement + kernel_radius; output = ceil((padded-2*border)
        # / stride1)) — also guarantees the rolled reads never wrapped
        kr = (k - 1) // 2
        border = d + kr
        ph_, pw_ = h + 2 * p, w + 2 * p
        if ph_ - 2 * border < 1 or pw_ - 2 * border < 1:
            raise ValueError(
                f"correlation: input {h}x{w} with pad_size={p} is smaller "
                f"than 2*(max_displacement+kernel_radius)={2 * border}; "
                f"increase pad_size")
        oh = (ph_ - 2 * border + s1 - 1) // s1
        ow = (pw_ - 2 * border + s1 - 1) // s1
        return out[:, :, border:border + oh * s1:s1,
                   border:border + ow * s1:s1]

    return apply_op_flat("correlation", fn, (data1, data2), {})


def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_filter=None, num_deformable_group=1,
                           no_bias=False, mask=None):
    """Deformable convolution v1/v2 (reference:
    `src/operator/contrib/deformable_convolution.cc` and
    `modulated_deformable_convolution.cc`).

    data (N, C, H, W); offset (N, 2*G*kh*kw, OH, OW) with interleaved
    (dy, dx) per kernel tap per deformable group G; weight
    (F, C, kh, kw); `mask` (N, G*kh*kw, OH, OW), if given, modulates each
    sampled tap (v2). Implemented as bilinear im2col at offset positions
    followed by ONE (F, C*kh*kw) × (C*kh*kw, OH*OW) MXU matmul per image."""
    def fn(x, off, wgt, *maybe_bias):
        jnp = _jnp()
        import jax

        n, c, h, w = x.shape
        f = wgt.shape[0]
        # the weight tensor is authoritative for the tap geometry; `kernel`
        # (and num_filter) are validation-only, like the reference's param
        # struct cross-check
        kh, kw = wgt.shape[2], wgt.shape[3]
        if tuple(kernel) != (kh, kw):
            raise ValueError(
                f"deformable_convolution: kernel={tuple(kernel)} disagrees "
                f"with weight shape {wgt.shape}")
        if num_filter is not None and int(num_filter) != f:
            raise ValueError(
                f"deformable_convolution: num_filter={num_filter} disagrees "
                f"with weight shape {wgt.shape}")
        sh, sw = stride
        ph, pw = pad
        dh, dw = dilate
        g = int(num_deformable_group)
        oh = (h + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (w + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cg = c // g

        base_y = (jnp.arange(oh) * sh - ph)[:, None, None]  # (OH,1,1)
        base_x = (jnp.arange(ow) * sw - pw)[None, :, None]  # (1,OW,1)
        tap_y = (jnp.arange(kh) * dh)[None, None, :].repeat(kw, -1) \
            .reshape(1, 1, kh * kw)
        tap_x = jnp.tile(jnp.arange(kw) * dw, kh).reshape(1, 1, kh * kw)

        def one(img, offs, mk):
            # offs (2*G*kh*kw, OH, OW) → (G, kh*kw, OH, OW, 2)
            o = offs.reshape(g, kh * kw, 2, oh, ow)
            dy = o[:, :, 0].transpose(0, 2, 3, 1)  # (G, OH, OW, K)
            dx = o[:, :, 1].transpose(0, 2, 3, 1)
            sy = base_y + tap_y + dy          # (G, OH, OW, K)
            sx = base_x + tap_x + dx
            if mk is not None:                # (G*kh*kw, OH, OW)
                mods = mk.reshape(g, kh * kw, oh, ow) \
                    .transpose(0, 2, 3, 1)    # (G, OH, OW, K)
            cols = []
            for gi in range(g):
                grp = img[gi * cg:(gi + 1) * cg]  # (cg, H, W)
                sampled = _bilinear_nchw(grp, sy[gi], sx[gi],
                                         padding="zero")
                if mk is not None:
                    sampled = sampled * mods[gi][None]  # modulate taps (v2)
                cols.append(sampled)
            col = jnp.concatenate(cols, 0)        # (C, OH, OW, K)
            col = col.transpose(0, 3, 1, 2).reshape(c * kh * kw, oh * ow)
            out = wgt.reshape(f, c * kh * kw) @ col
            return out.reshape(f, oh, ow)

        if has_mask:
            mk_batch = maybe_bias[-1]
            bias_vals = maybe_bias[:-1]
            y = jax.vmap(one)(x, off, mk_batch)
        else:
            bias_vals = maybe_bias
            y = jax.vmap(lambda i, o: one(i, o, None))(x, off)
        if bias_vals and not no_bias:
            y = y + bias_vals[0].reshape(1, f, 1, 1)
        return y

    has_mask = mask is not None
    args = [data, offset, weight]
    if bias is not None and not no_bias:
        args.append(bias)
    if has_mask:
        args.append(mask)
    return apply_op_flat("deformable_convolution", fn, tuple(args), {})


def fft(data, compute_size=None):  # noqa: ARG001
    """FFT over the last axis, interleaved real/imag output (reference:
    `src/operator/contrib/fft/fft.cc` — output last dim is 2×input)."""
    def fn(x):
        jnp = _jnp()
        z = jnp.fft.fft(x.astype("float32"), axis=-1)
        return jnp.stack([z.real, z.imag], axis=-1) \
            .reshape(*x.shape[:-1], 2 * x.shape[-1]).astype(x.dtype)

    return apply_op_flat("fft", fn, (data,), {})


def ifft(data, compute_size=None):  # noqa: ARG001
    """Inverse of `fft`'s interleaved layout (reference:
    `src/operator/contrib/fft/ifft.cc` — returns the real part, scaled
    by n like the reference's cuFFT (unnormalized) inverse)."""
    def fn(x):
        jnp = _jnp()
        n = x.shape[-1] // 2
        z = x.reshape(*x.shape[:-1], n, 2)
        comp = z[..., 0] + 1j * z[..., 1]
        return (jnp.fft.ifft(comp, axis=-1).real * n).astype(x.dtype)

    return apply_op_flat("ifft", fn, (data,), {})


def modulated_deformable_convolution(data, offset, mask, weight,
                                     bias=None, kernel=(3, 3),
                                     stride=(1, 1), pad=(0, 0),
                                     dilate=(1, 1), num_filter=None,
                                     num_deformable_group=1,
                                     no_bias=False, **kwargs):  # noqa: ARG001
    """Deformable convolution v2 (reference
    `contrib/modulated_deformable_convolution.cc`): v1 plus a learned
    per-tap modulation mask — delegates to `deformable_convolution`,
    which already implements the modulated sampling path."""
    return deformable_convolution(
        data, offset, weight, bias=bias, kernel=kernel, stride=stride,
        pad=pad, dilate=dilate, num_filter=num_filter,
        num_deformable_group=num_deformable_group, no_bias=no_bias,
        mask=mask)


__all__.append("modulated_deformable_convolution")
