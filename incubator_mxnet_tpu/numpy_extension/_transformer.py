"""Transformer contrib ops: interleaved projection matmuls and Longformer
sliding-window attention.

Reference: `src/operator/contrib/transformer.cc` —
`_contrib_interleaved_matmul_selfatt_qk/valatt` (:200 CPU kernel,
strided batch gemm over the interleaved [q|k|v]-per-head layout),
`_contrib_interleaved_matmul_encdec_qk/valatt`, `_contrib_div_sqrt_dim`,
and `_contrib_sldwin_atten_{score,context,mask_like}` (:887-1100,
mask math at `transformer-inl.h:71`).

TPU-native design: the strided-gemm tricks exist to avoid CUDA transpose
kernels; here each op is a reshape + einsum that XLA lays out onto the
MXU directly, and jax.vjp provides the backward that the reference
hand-writes. The sliding-window ops gather the (2w+1)-wide band with
`take_along_axis` — O(T·w) memory like the reference, not the O(T²)
dense score matrix.
"""
from __future__ import annotations

import math

from ..ndarray.ndarray import apply_op

__all__ = [
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "div_sqrt_dim", "sldwin_atten_score", "sldwin_atten_context",
    "sldwin_atten_mask_like",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


def interleaved_matmul_selfatt_qk(queries_keys_values, heads):
    """scale·Q@Kᵀ over an interleaved QKV projection.

    Input (seq, batch, 3·embed) where the last dim is per-head blocks
    [q(hd) | k(hd) | v(hd)]; output (batch·heads, seq, seq), batch-major
    attention batches (b·heads + h), scale = 1/sqrt(head_dim).
    """
    def fn(qkv):
        jnp = _jnp()
        t, b, e3 = qkv.shape
        hd = e3 // 3 // heads
        x = qkv.reshape(t, b, heads, 3, hd)
        q, k = x[..., 0, :], x[..., 1, :]
        att = jnp.einsum("tbhd,sbhd->bhts", q, k) / math.sqrt(hd)
        return att.reshape(b * heads, t, t)

    return apply_op("interleaved_matmul_selfatt_qk", fn,
                    (queries_keys_values,), static_info=("heads", heads))


def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads):
    """attention @ V over the interleaved QKV projection.

    Inputs (seq, batch, 3·embed) and (batch·heads, seq, seq); output
    (seq, batch, embed)."""
    def fn(qkv, att):
        jnp = _jnp()
        t, b, e3 = qkv.shape
        hd = e3 // 3 // heads
        v = qkv.reshape(t, b, heads, 3, hd)[..., 2, :]
        a = att.reshape(b, heads, t, t)
        out = jnp.einsum("bhts,sbhd->tbhd", a, v)
        return out.reshape(t, b, heads * hd)

    return apply_op("interleaved_matmul_selfatt_valatt", fn,
                    (queries_keys_values, attention),
                    static_info=("heads", heads))


def interleaved_matmul_encdec_qk(queries, keys_values, heads):
    """Encoder-decoder attention scores over an interleaved KV projection.

    queries (seq_q, batch, embed), keys_values (seq_kv, batch, 2·embed)
    with per-head [k(hd) | v(hd)]; output (batch·heads, seq_q, seq_kv)."""
    def fn(q, kv):
        jnp = _jnp()
        tq, b, e = q.shape
        hd = e // heads
        qh = q.reshape(tq, b, heads, hd)
        k = kv.reshape(kv.shape[0], b, heads, 2, hd)[..., 0, :]
        att = jnp.einsum("tbhd,sbhd->bhts", qh, k) / math.sqrt(hd)
        return att.reshape(b * heads, tq, kv.shape[0])

    return apply_op("interleaved_matmul_encdec_qk", fn,
                    (queries, keys_values), static_info=("heads", heads))


def interleaved_matmul_encdec_valatt(keys_values, attention, heads):
    """attention @ V for encoder-decoder attention; output
    (seq_q, batch, embed)."""
    def fn(kv, att):
        jnp = _jnp()
        tk, b, e2 = kv.shape
        hd = e2 // 2 // heads
        v = kv.reshape(tk, b, heads, 2, hd)[..., 1, :]
        tq = att.shape[1]
        a = att.reshape(b, heads, tq, tk)
        out = jnp.einsum("bhts,sbhd->tbhd", a, v)
        return out.reshape(tq, b, heads * hd)

    return apply_op("interleaved_matmul_encdec_valatt", fn,
                    (keys_values, attention), static_info=("heads", heads))


def div_sqrt_dim(data):
    """data / sqrt(data.shape[-1]) (reference transformer.cc
    `_contrib_div_sqrt_dim`)."""
    return apply_op(
        "div_sqrt_dim",
        lambda x: x / math.sqrt(x.shape[-1]), (data,))


def _band_positions(jnp, t, w, w_len, dilation):
    """pos[i, h, j] = i + (j - w)·dilation[h] — the key position that
    window slot j of query i addresses (slot w is the diagonal; causal
    mode simply truncates to the left half [0..w])."""
    i = jnp.arange(t)[:, None, None]
    j = jnp.arange(w_len)[None, None, :]
    return i + (j - w) * dilation.astype("int32")[None, :, None]


def sldwin_atten_score(query, key, dilation, w=None, symmetric=True):
    """Longformer sliding-window attention scores.

    query/key (batch, seq, heads, hd), dilation (heads,); output
    (batch, seq, heads, 2w+1) (symmetric) or (batch, seq, heads, w+1)
    (causal). Out-of-range slots are 0 — `sldwin_atten_mask_like`
    produces the matching mask."""
    w = int(w)
    # causal w_len = w+1 truncates the band to slots [-w..0] — the same
    # j - w offset formula covers both modes
    w_len = 2 * w + 1 if symmetric else w + 1

    def fn(q, k, dil):
        jnp = _jnp()
        b, t, h, hd = q.shape
        pos = _band_positions(jnp, t, w, w_len, dil)
        valid = (pos >= 0) & (pos < t)
        posc = jnp.clip(pos, 0, t - 1)
        k5 = k[:, :, :, None, :]                     # (b,t,h,1,hd)
        ind = posc[None, :, :, :, None]              # (1,t,h,wl,1)
        kg = jnp.take_along_axis(k5, ind, axis=1)    # (b,t,h,wl,hd)
        score = jnp.einsum("bihd,bihjd->bihj", q, kg)
        return score * valid[None].astype(score.dtype)

    return apply_op("sldwin_atten_score", fn, (query, key, dilation),
                    static_info=("w", w, "sym", bool(symmetric)))


def sldwin_atten_context(score, value, dilation, w=None, symmetric=True):
    """Context vectors from sliding-window scores: output
    (batch, seq, heads, hd)."""
    w = int(w)
    w_len = 2 * w + 1 if symmetric else w + 1

    def fn(s, v, dil):
        jnp = _jnp()
        b, t, h, hd = v.shape
        pos = _band_positions(jnp, t, w, w_len, dil)
        valid = (pos >= 0) & (pos < t)
        posc = jnp.clip(pos, 0, t - 1)
        v5 = v[:, :, :, None, :]
        ind = posc[None, :, :, :, None]
        vg = jnp.take_along_axis(v5, ind, axis=1)    # (b,t,h,wl,hd)
        s = s * valid[None].astype(s.dtype)
        return jnp.einsum("bihj,bihjd->bihd", s, vg)

    return apply_op("sldwin_atten_context", fn, (score, value, dilation),
                    static_info=("w", w, "sym", bool(symmetric)))


def sldwin_atten_mask_like(score, dilation, valid_length, w=None,
                           symmetric=True):
    """0/1 mask matching `sldwin_atten_score`'s output — exact port of
    the reference mask math (`transformer-inl.h:71` SldWinAttenMaskLike,
    including the integer-division dilation boundaries)."""
    w = int(w)
    w_len = 2 * w + 1 if symmetric else w + 1

    def fn(s, dil, vlen):
        jnp = _jnp()
        b, t, h, _ = s.shape
        i = jnp.arange(t)[None, :, None, None]           # seq idx
        j = jnp.arange(w_len)[None, None, None, :]       # win idx
        d = dil.astype("int32")[None, None, :, None]
        vl = vlen.astype("int32")[:, None, None, None]
        is_zero = (j < (w - i // d)) | (i >= vl)
        if symmetric:
            is_zero = is_zero | ((w_len - j - 1) < (w - (vl - i - 1) // d))
        return jnp.where(is_zero, 0.0, 1.0).astype(s.dtype) \
            * jnp.ones_like(s)

    return apply_op("sldwin_atten_mask_like", fn,
                    (score, dilation, valid_length),
                    static_info=("w", w, "sym", bool(symmetric)))
