"""Region-proposal / RoI detection op family (reference
`src/operator/contrib/proposal.cc`, `multi_proposal.cc`,
`psroi_pooling.cc`, `deformable_psroi_pooling.cc`, `rroi_align.cc`,
`mrcnn_mask_target.cu`).

TPU-native shape discipline: every stage is fixed-size — proposals are
top-k'd and NMS'd at static counts (matching the reference's
rpn_pre/post_nms_top_n parameters, which already impose static sizes),
so the whole RPN head stays jit-compilable. Bilinear sampling reuses
the vectorized gather pattern from `_spatial.py`.
"""
from __future__ import annotations

import math

from ..ndarray.ndarray import apply_op

__all__ = [
    "proposal", "multi_proposal", "psroi_pooling",
    "deformable_psroi_pooling", "rroi_align", "mrcnn_mask_target",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _gen_anchors(jnp, base_size, scales, ratios):
    """Reference anchor enumeration (proposal-inl.h:200): ratios first,
    then scales, centered on the base box."""
    anchors = []
    cx = cy = (base_size - 1) / 2.0
    size = base_size * base_size
    for r in ratios:
        size_ratio = math.floor(size / r)
        w = round(math.sqrt(size_ratio))
        h = round(w * r)
        for s in scales:
            ws, hs = w * s, h * s
            anchors.append([cx - (ws - 1) / 2, cy - (hs - 1) / 2,
                            cx + (ws - 1) / 2, cy + (hs - 1) / 2])
    return jnp.asarray(anchors, "float32")


def _nms_keep(jnp, boxes, scores, thresh, max_out):
    """Static-shape greedy NMS: returns `max_out` indices (padded with
    -1). O(max_out · N) like the reference kernel."""
    import jax

    n = boxes.shape[0]
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1 + 1, 0) * jnp.maximum(y2 - y1 + 1, 0)

    def body(carry, _):
        alive, keep_i = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf
        bx1, by1, bx2, by2 = (boxes[best, i] for i in range(4))
        ix1 = jnp.maximum(x1, bx1)
        iy1 = jnp.maximum(y1, by1)
        ix2 = jnp.minimum(x2, bx2)
        iy2 = jnp.minimum(y2, by2)
        inter = jnp.maximum(ix2 - ix1 + 1, 0) * \
            jnp.maximum(iy2 - iy1 + 1, 0)
        iou = inter / (area + area[best] - inter + 1e-12)
        alive = alive & (iou <= thresh)
        alive = alive.at[best].set(False)
        return (alive, 0), jnp.where(valid, best, -1)

    (_, _), kept = jax.lax.scan(body, (jnp.ones((n,), bool), 0),
                                None, length=max_out)
    return kept


def _proposal_one(jnp, cls_prob, bbox_pred, im_info, anchors, stride,
                  pre_nms, post_nms, thresh, min_size):
    import jax

    a = anchors.shape[0]
    h, w = cls_prob.shape[-2:]
    # foreground scores are the second half of the 2A channel block
    scores = cls_prob[a:].reshape(a, h, w).transpose(1, 2, 0).reshape(-1)
    deltas = bbox_pred.reshape(a, 4, h, w).transpose(2, 3, 0, 1) \
        .reshape(-1, 4)
    shift_x = jnp.arange(w) * stride
    shift_y = jnp.arange(h) * stride
    grid = jnp.stack(jnp.meshgrid(shift_y, shift_x, indexing="ij"), -1)
    shifts = jnp.concatenate(
        [grid[..., 1:2], grid[..., 0:1]] * 2, axis=-1)   # (H,W,4) x1y1x2y2
    boxes = (anchors[None, None] + shifts[:, :, None]).reshape(-1, 4)
    # bbox transform (proposal-inl.h BBoxTransformInv)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    cx = boxes[:, 0] + ws * 0.5
    cy = boxes[:, 1] + hs * 0.5
    pcx = deltas[:, 0] * ws + cx
    pcy = deltas[:, 1] * hs + cy
    pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * ws
    ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * hs
    prop = jnp.stack([pcx - pw * 0.5, pcy - ph * 0.5,
                      pcx + pw * 0.5, pcy + ph * 0.5], axis=1)
    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    prop = jnp.stack([jnp.clip(prop[:, 0], 0, im_w - 1),
                      jnp.clip(prop[:, 1], 0, im_h - 1),
                      jnp.clip(prop[:, 2], 0, im_w - 1),
                      jnp.clip(prop[:, 3], 0, im_h - 1)], axis=1)
    msz = min_size * im_scale
    keep = ((prop[:, 2] - prop[:, 0] + 1) >= msz) & \
        ((prop[:, 3] - prop[:, 1] + 1) >= msz)
    scores = jnp.where(keep, scores, -jnp.inf)
    k = min(pre_nms, scores.shape[0])
    top_s, top_i = jax.lax.top_k(scores, k)
    top_boxes = prop[top_i]
    kept = _nms_keep(jnp, top_boxes, top_s, thresh, post_nms)
    safe = jnp.maximum(kept, 0)
    out_boxes = jnp.where((kept >= 0)[:, None], top_boxes[safe], 0.0)
    out_scores = jnp.where(kept >= 0, top_s[safe], 0.0)
    return out_boxes, out_scores


def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
             feature_stride=16, output_score=False, iou_loss=False):  # noqa: ARG001
    """RPN proposal generation (reference contrib/proposal.cc): anchors
    → bbox deltas → clip → min-size filter → top-k → NMS. Output
    (post_nms_top_n, 5) rois [batch_idx, x1, y1, x2, y2]."""
    sc = tuple(float(s) for s in scales)
    ra = tuple(float(r) for r in ratios)

    def fn(cp, bp, info):
        jnp = _jnp()
        anchors = _gen_anchors(jnp, feature_stride, sc, ra)
        boxes, scores = _proposal_one(
            jnp, cp[0], bp[0], info[0], anchors, feature_stride,
            int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
            float(threshold), float(rpn_min_size))
        rois = jnp.concatenate(
            [jnp.zeros((boxes.shape[0], 1), boxes.dtype), boxes], axis=1)
        if output_score:
            return rois, scores[:, None]
        return rois

    return apply_op("proposal", fn, (cls_prob, bbox_pred, im_info),
                    n_outputs=2 if output_score else 1,
                    static_info=("p", rpn_pre_nms_top_n,
                                 rpn_post_nms_top_n, threshold,
                                 rpn_min_size, sc, ra, feature_stride,
                                 bool(output_score)))


def multi_proposal(cls_prob, bbox_pred, im_info, **kwargs):
    """Batched RPN proposals (reference contrib/multi_proposal.cc):
    per-image proposal with the batch index in column 0."""
    output_score = kwargs.get("output_score", False)
    sc = tuple(float(s)
               for s in kwargs.get("scales", (4, 8, 16, 32)))
    ra = tuple(float(r) for r in kwargs.get("ratios", (0.5, 1, 2)))
    stride = kwargs.get("feature_stride", 16)
    pre = int(kwargs.get("rpn_pre_nms_top_n", 6000))
    post = int(kwargs.get("rpn_post_nms_top_n", 300))
    thr = float(kwargs.get("threshold", 0.7))
    msz = float(kwargs.get("rpn_min_size", 16))

    def fn(cp, bp, info):
        jnp = _jnp()
        anchors = _gen_anchors(jnp, stride, sc, ra)
        all_rois, all_scores = [], []
        for b in range(cp.shape[0]):
            boxes, scores = _proposal_one(jnp, cp[b], bp[b], info[b],
                                          anchors, stride, pre, post,
                                          thr, msz)
            idx = jnp.full((boxes.shape[0], 1), float(b), boxes.dtype)
            all_rois.append(jnp.concatenate([idx, boxes], axis=1))
            all_scores.append(scores[:, None])
        rois = jnp.concatenate(all_rois, axis=0)
        if output_score:
            return rois, jnp.concatenate(all_scores, axis=0)
        return rois

    return apply_op("multi_proposal", fn, (cls_prob, bbox_pred, im_info),
                    n_outputs=2 if output_score else 1,
                    static_info=("p", pre, post, thr, msz, sc, ra,
                                 stride, bool(output_score)))


def psroi_pooling(data, rois, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """Position-sensitive RoI pooling (reference contrib/
    psroi_pooling.cc): bin (i,j) of output channel c averages input
    channel (c·group² + i·group + j) over the bin's region."""
    od = int(output_dim)
    ps = int(pooled_size)
    gs = int(group_size) or ps

    def fn(x, r):
        jnp = _jnp()
        n_rois = r.shape[0]
        h, w = x.shape[-2:]
        batch = r[:, 0].astype("int32")
        x1 = jnp.round(r[:, 1]) * spatial_scale
        y1 = jnp.round(r[:, 2]) * spatial_scale
        x2 = (jnp.round(r[:, 3]) + 1) * spatial_scale
        y2 = (jnp.round(r[:, 4]) + 1) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / ps, rh / ps
        imgs = x[batch]                        # (R, C, H, W)
        ys = jnp.arange(h, dtype="float32")
        xs = jnp.arange(w, dtype="float32")
        outs = []
        for i in range(ps):
            for j in range(ps):
                hs = jnp.floor(y1 + i * bin_h)
                he = jnp.ceil(y1 + (i + 1) * bin_h)
                wss = jnp.floor(x1 + j * bin_w)
                wee = jnp.ceil(x1 + (j + 1) * bin_w)
                my = ((ys[None, :] >= hs[:, None])
                      & (ys[None, :] < he[:, None])).astype(x.dtype)
                mxx = ((xs[None, :] >= wss[:, None])
                       & (xs[None, :] < wee[:, None])).astype(x.dtype)
                mask = my[:, :, None] * mxx[:, None, :]     # (R,H,W)
                cnt = jnp.maximum(mask.sum(axis=(1, 2)), 1.0)
                gi = (i * gs) // ps
                gj = (j * gs) // ps
                chans = jnp.arange(od) * gs * gs + gi * gs + gj
                sel = imgs[:, chans]                        # (R,od,H,W)
                pooled = (sel * mask[:, None]).sum(axis=(2, 3)) \
                    / cnt[:, None]
                outs.append(pooled)
        out = jnp.stack(outs, axis=-1).reshape(n_rois, od, ps, ps)
        return out

    return apply_op("psroi_pooling", fn, (data, rois),
                    static_info=("p", float(spatial_scale), od, ps, gs))


def deformable_psroi_pooling(data, rois, trans, spatial_scale,
                             output_dim, group_size, pooled_size,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False):
    """Deformable PS-RoI pooling (reference contrib/
    deformable_psroi_pooling.cc): PSROI bins shifted by learned
    normalized offsets, values bilinearly sampled."""
    od = int(output_dim)
    ps = int(pooled_size)
    gs = int(group_size) or ps
    pt = int(part_size) or ps
    spp = max(int(sample_per_part), 1)

    def fn(x, r, tr):
        jnp = _jnp()
        n_rois = r.shape[0]
        h, w = x.shape[-2:]
        batch = r[:, 0].astype("int32")
        x1 = jnp.round(r[:, 1]) * spatial_scale - 0.5
        y1 = jnp.round(r[:, 2]) * spatial_scale - 0.5
        x2 = (jnp.round(r[:, 3]) + 1) * spatial_scale - 0.5
        y2 = (jnp.round(r[:, 4]) + 1) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / ps, rh / ps
        sub_w, sub_h = bin_w / spp, bin_h / spp
        imgs = x[batch]
        outs = []
        for i in range(ps):
            for j in range(ps):
                if no_trans:
                    dy = jnp.zeros((n_rois,))
                    dx = jnp.zeros((n_rois,))
                else:
                    pi = (i * pt) // ps
                    pj = (j * pt) // ps
                    cls = 0   # class-agnostic offsets (reference default)
                    dy = tr[:, cls * 2, pi, pj] * trans_std * rh
                    dx = tr[:, cls * 2 + 1, pi, pj] * trans_std * rw
                acc = 0.0
                for si in range(spp):
                    for sj in range(spp):
                        yy = y1 + i * bin_h + (si + 0.5) * sub_h + dy
                        xx = x1 + j * bin_w + (sj + 0.5) * sub_w + dx
                        y0 = jnp.floor(jnp.clip(yy, 0, h - 1))
                        x0 = jnp.floor(jnp.clip(xx, 0, w - 1))
                        y1i = jnp.clip(y0 + 1, 0, h - 1).astype("int32")
                        x1i = jnp.clip(x0 + 1, 0, w - 1).astype("int32")
                        y0i = y0.astype("int32")
                        x0i = x0.astype("int32")
                        wy = (jnp.clip(yy, 0, h - 1) - y0)[:, None]
                        wx = (jnp.clip(xx, 0, w - 1) - x0)[:, None]
                        gi = (i * gs) // ps
                        gj = (j * gs) // ps
                        chans = jnp.arange(od) * gs * gs + gi * gs + gj
                        sel = imgs[:, chans]                # (R,od,H,W)
                        ridx = jnp.arange(n_rois)
                        v00 = sel[ridx, :, y0i, x0i]
                        v01 = sel[ridx, :, y0i, x1i]
                        v10 = sel[ridx, :, y1i, x0i]
                        v11 = sel[ridx, :, y1i, x1i]
                        acc = acc + ((1 - wy) * (1 - wx) * v00
                                     + (1 - wy) * wx * v01
                                     + wy * (1 - wx) * v10
                                     + wy * wx * v11)
                outs.append(acc / (spp * spp))
        return jnp.stack(outs, axis=-1).reshape(n_rois, od, ps, ps)

    args = (data, rois, trans)
    return apply_op("deformable_psroi_pooling", fn, args,
                    static_info=("p", float(spatial_scale), od, gs, ps,
                                 pt, spp, float(trans_std),
                                 bool(no_trans)))


def rroi_align(data, rois, pooled_size, spatial_scale):
    """Rotated RoI align (reference contrib/rroi_align.cc): rois
    (R, 6) = [batch, cx, cy, w, h, angle°]; bilinear samples on the
    rotated grid."""
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)

    def fn(x, r):
        jnp = _jnp()
        n_rois = r.shape[0]
        h, w = x.shape[-2:]
        batch = r[:, 0].astype("int32")
        cx = r[:, 1] * spatial_scale
        cy = r[:, 2] * spatial_scale
        rw = jnp.maximum(r[:, 3] * spatial_scale, 1.0)
        rh = jnp.maximum(r[:, 4] * spatial_scale, 1.0)
        theta = r[:, 5] * jnp.pi / 180.0
        imgs = x[batch]
        # normalized bin centers in roi frame
        gy = (jnp.arange(ph) + 0.5) / ph - 0.5
        gx = (jnp.arange(pw) + 0.5) / pw - 0.5
        gyy, gxx = jnp.meshgrid(gy, gx, indexing="ij")   # (ph,pw)
        cosT = jnp.cos(theta)[:, None, None]
        sinT = jnp.sin(theta)[:, None, None]
        lx = gxx[None] * rw[:, None, None]
        ly = gyy[None] * rh[:, None, None]
        sx = cx[:, None, None] + lx * cosT - ly * sinT
        sy = cy[:, None, None] + lx * sinT + ly * cosT
        sx = jnp.clip(sx, 0, w - 1)
        sy = jnp.clip(sy, 0, h - 1)
        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        x1 = jnp.clip(x0 + 1, 0, w - 1).astype("int32")
        y1 = jnp.clip(y0 + 1, 0, h - 1).astype("int32")
        wx = (sx - x0)[..., None]            # (R,ph,pw,1)
        wy = (sy - y0)[..., None]
        x0 = x0.astype("int32")
        y0 = y0.astype("int32")
        ridx = jnp.arange(n_rois)[:, None, None]

        def g(yi, xi):
            # advanced indexing broadcast → (R, ph, pw, C)
            return imgs[ridx, :, yi, xi]

        v00 = g(y0, x0)
        v01 = g(y0, x1)
        v10 = g(y1, x0)
        v11 = g(y1, x1)
        out = ((1 - wy) * (1 - wx) * v00 + (1 - wy) * wx * v01
               + wy * (1 - wx) * v10 + wy * wx * v11)
        return out.transpose(0, 3, 1, 2)

    return apply_op("rroi_align", fn, (data, rois),
                    static_info=("p", ph, pw, float(spatial_scale)))


def mrcnn_mask_target(rois, gt_masks, matches, cls_targets,
                      num_rois=None, num_classes=None, mask_size=(14, 14),
                      sample_ratio=2, aligned=False):  # noqa: ARG001
    """Mask R-CNN training-target generator (reference contrib/
    mrcnn_mask_target.cu — GPU-only there; host-free jax here).

    rois (B, R, 4) corner format, gt_masks (B, M, H, W), matches (B, R)
    gt index per roi, cls_targets (B, R) class ids. Returns
    (mask_targets (B, R, C, ms, ms), mask_cls (B, R, C, ms, ms))."""
    ms = (mask_size, mask_size) if isinstance(mask_size, int) \
        else tuple(mask_size)
    mh, mw = int(ms[0]), int(ms[1])

    def fn(r, gm, mt, ct):
        import jax

        jnp = _jnp()
        b, n_r = r.shape[:2]
        hh, ww = gm.shape[-2:]
        # roi_align each matched gt mask down to (mh, mw)
        gy = (jnp.arange(mh) + 0.5) / mh
        gx = (jnp.arange(mw) + 0.5) / mw

        def one(roi, mask):
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            sy = y1 + gy * jnp.maximum(y2 - y1, 1.0)
            sx = x1 + gx * jnp.maximum(x2 - x1, 1.0)
            sy = jnp.clip(sy, 0, hh - 1)
            sx = jnp.clip(sx, 0, ww - 1)
            y0 = jnp.floor(sy)
            x0 = jnp.floor(sx)
            y1i = jnp.clip(y0 + 1, 0, hh - 1).astype("int32")
            x1i = jnp.clip(x0 + 1, 0, ww - 1).astype("int32")
            wy = (sy - y0)[:, None]
            wx = (sx - x0)[None, :]
            y0i, x0i = y0.astype("int32"), x0.astype("int32")
            v00 = mask[y0i][:, x0i]
            v01 = mask[y0i][:, x1i]
            v10 = mask[y1i][:, x0i]
            v11 = mask[y1i][:, x1i]
            return ((1 - wy) * (1 - wx) * v00 + (1 - wy) * wx * v01
                    + wy * (1 - wx) * v10 + wy * wx * v11)

        sampled = jax.vmap(jax.vmap(one))(
            r, gm[jnp.arange(b)[:, None], mt.astype("int32")])
        onehot = jax.nn.one_hot(ct.astype("int32"), num_classes,
                                dtype=r.dtype)       # (B,R,C)
        targets = sampled[:, :, None] * onehot[..., None, None]
        weights = jnp.broadcast_to(onehot[..., None, None],
                                   (b, n_r, num_classes, mh, mw))
        return targets, weights

    return apply_op("mrcnn_mask_target", fn,
                    (rois, gt_masks, matches, cls_targets), n_outputs=2,
                    static_info=("p", mh, mw, int(num_classes or 0)))
