"""Bounding-box / detection operator family (reference:
`src/operator/contrib/bounding_box.cc` — box_iou, box_nms, box_encode,
box_decode, bipartite_matching — and `src/operator/contrib/roi_align.cc`).

TPU-native: everything is expressed as fixed-shape tensor math (sort +
masked suppression scans instead of data-dependent loops), so the whole
family jit-compiles and batches on the MXU. Suppressed/invalid results use
the reference's -1 sentinel convention.
"""
from __future__ import annotations

from ..ndarray.ndarray import apply_op_flat

__all__ = ["box_iou", "box_nms", "box_encode", "box_decode",
           "bipartite_matching", "roi_align", "slice_like",
           "broadcast_like", "batch_take", "multibox_prior",
           "multibox_target", "multibox_detection"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _to_corner(b, fmt):
    jnp = _jnp()
    if fmt == "corner":
        return b
    # center: (x, y, w, h) → (xmin, ymin, xmax, ymax)
    xy = b[..., :2]
    wh = b[..., 2:4] / 2.0
    return jnp.concatenate([xy - wh, xy + wh], axis=-1)


def _corner_to_center(b):
    jnp = _jnp()
    wh = b[..., 2:4] - b[..., :2]
    xy = (b[..., :2] + b[..., 2:4]) / 2.0
    return jnp.concatenate([xy, wh], axis=-1)


def _iou_corner(lhs, rhs):
    """lhs (..., N, 4), rhs (..., M, 4) corners → (..., N, M) IoU."""
    jnp = _jnp()
    lt = jnp.maximum(lhs[..., :, None, :2], rhs[..., None, :, :2])
    rb = jnp.minimum(lhs[..., :, None, 2:4], rhs[..., None, :, 2:4])
    wh = jnp.clip(rb - lt, 0.0, None)
    inter = wh[..., 0] * wh[..., 1]
    area_l = ((lhs[..., 2] - lhs[..., 0])
              * (lhs[..., 3] - lhs[..., 1]))[..., :, None]
    area_r = ((rhs[..., 2] - rhs[..., 0])
              * (rhs[..., 3] - rhs[..., 1]))[..., None, :]
    return inter / jnp.maximum(area_l + area_r - inter, 1e-12)


def box_iou(lhs, rhs, format="corner"):  # noqa: A002
    """Pairwise IoU (reference: bounding_box.cc:118 _contrib_box_iou)."""
    def fn(a, b):
        return _iou_corner(_to_corner(a, format), _to_corner(b, format))

    return apply_op_flat("box_iou", fn, (lhs, rhs), {})


def _nms_core(d, overlap_thresh, valid_thresh, topk, coord_start,
              score_index, id_index, background_id, force_suppress,
              in_format, out_format):
    """jax-level NMS body shared by `box_nms` and `multibox_detection`
    (no funnel/NDArray layering — safe to call inside another op's fn)."""
    jnp = _jnp()
    batch_shape = d.shape[:-2]
    n, k = d.shape[-2], d.shape[-1]
    flat = d.reshape((-1, n, k))

    def one(batch):
        scores = batch[:, score_index]
        order = jnp.argsort(-scores)  # descending
        sorted_rows = batch[order]
        s_scores = sorted_rows[:, score_index]
        boxes = _to_corner(
            sorted_rows[:, coord_start:coord_start + 4], in_format)
        iou = _iou_corner(boxes, boxes)
        valid = s_scores > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)
        if id_index >= 0 and not force_suppress:
            ids = sorted_rows[:, id_index]
            same_class = ids[:, None] == ids[None, :]
        else:
            same_class = jnp.ones((n, n), bool)
        if id_index >= 0 and background_id >= 0:
            valid = valid & (sorted_rows[:, id_index] != background_id)
        suppress_pair = (iou > overlap_thresh) & same_class

        # greedy scan in score order: row i survives unless suppressed
        # by an earlier surviving row
        def body(i, keep):
            sup = (suppress_pair[:, i] & keep
                   & (jnp.arange(n) < i)).any()
            return keep.at[i].set(keep[i] & ~sup)

        import jax

        keep = jax.lax.fori_loop(0, n, body, valid)
        if out_format != in_format:
            conv = (boxes if out_format == "corner"
                    else _corner_to_center(boxes))
            sorted_rows = sorted_rows.at[
                :, coord_start:coord_start + 4].set(conv)
        # compact survivors to the top (stable: argsort of ~keep keeps
        # score order within each group), fill the tail with -1
        perm = jnp.argsort(~keep, stable=True)
        compacted = sorted_rows[perm]
        row_valid = keep[perm]
        return jnp.where(row_valid[:, None], compacted, -1.0)

    import jax

    out = jax.vmap(one)(flat)
    return out.reshape(batch_shape + (n, k))


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Non-maximum suppression (reference: bounding_box.cc _contrib_box_nms).

    data: (..., N, K) rows [id?, score, x1, y1, x2, y2, ...]. Reference
    output semantics (bounding_box-inl.h:326): surviving rows compacted to
    the top in score order, all remaining rows filled with -1."""
    def fn(d):
        return _nms_core(d, overlap_thresh, valid_thresh, topk, coord_start,
                         score_index, id_index, background_id,
                         force_suppress, in_format, out_format)

    return apply_op_flat("box_nms", fn, (data,), {})


def box_encode(samples, matches, anchors, refs, means=(0., 0., 0., 0.),
               stds=(0.1, 0.1, 0.2, 0.2)):
    """SSD-style box target encoding (reference: bounding_box.cc
    _contrib_box_encode). anchors/refs are corner boxes; outputs
    (targets, masks) with mask = sample>0.5."""
    def fn(sm, mt, an, rf):
        jnp = _jnp()
        # gather the matched reference box per anchor
        ref = jnp.take_along_axis(rf, mt[..., None].astype("int32"), axis=1)
        a_w = an[..., 2] - an[..., 0]
        a_h = an[..., 3] - an[..., 1]
        a_x = (an[..., 0] + an[..., 2]) / 2.0
        a_y = (an[..., 1] + an[..., 3]) / 2.0
        r_w = ref[..., 2] - ref[..., 0]
        r_h = ref[..., 3] - ref[..., 1]
        r_x = (ref[..., 0] + ref[..., 2]) / 2.0
        r_y = (ref[..., 1] + ref[..., 3]) / 2.0
        t = jnp.stack([
            ((r_x - a_x) / jnp.maximum(a_w, 1e-12) - means[0]) / stds[0],
            ((r_y - a_y) / jnp.maximum(a_h, 1e-12) - means[1]) / stds[1],
            (jnp.log(jnp.maximum(r_w, 1e-12)
                     / jnp.maximum(a_w, 1e-12)) - means[2]) / stds[2],
            (jnp.log(jnp.maximum(r_h, 1e-12)
                     / jnp.maximum(a_h, 1e-12)) - means[3]) / stds[3],
        ], axis=-1)
        mask = (sm > 0.5).astype(t.dtype)[..., None]
        return t * mask, jnp.broadcast_to(mask, t.shape)

    return apply_op_flat("box_encode", fn, (samples, matches, anchors, refs),
                         {}, n_outputs=2)


def box_decode(data, anchors, std0=0.1, std1=0.1, std2=0.2, std3=0.2,
               clip=-1.0, format="center"):  # noqa: A002
    """Decode SSD regression deltas back to boxes (reference:
    bounding_box.cc _contrib_box_decode). anchors in `format`; returns
    corner boxes."""
    def fn(d, an):
        jnp = _jnp()
        anc = _to_corner(an, format)
        a_w = anc[..., 2] - anc[..., 0]
        a_h = anc[..., 3] - anc[..., 1]
        a_x = (anc[..., 0] + anc[..., 2]) / 2.0
        a_y = (anc[..., 1] + anc[..., 3]) / 2.0
        dx = d[..., 0] * std0 * a_w + a_x
        dy = d[..., 1] * std1 * a_h + a_y
        dw = d[..., 2] * std2
        dh = d[..., 3] * std3
        if clip > 0:
            dw = jnp.minimum(dw, clip)
            dh = jnp.minimum(dh, clip)
        w = jnp.exp(dw) * a_w / 2.0
        h = jnp.exp(dh) * a_h / 2.0
        return jnp.stack([dx - w, dy - h, dx + w, dy + h], axis=-1)

    return apply_op_flat("box_decode", fn, (data, anchors), {})


def bipartite_matching(data, threshold, is_ascend=False, topk=-1):  # noqa: ARG001
    """Greedy bipartite matching over a (..., N, M) affinity matrix
    (reference: bounding_box.cc _contrib_bipartite_matching). Returns
    (row_match, col_match): for each row, the matched column (or -1), and
    for each column, the matched row (or -1)."""
    def fn(d):
        import jax

        jnp = _jnp()
        batch_shape = d.shape[:-2]
        n, m = d.shape[-2], d.shape[-1]
        flat = d.reshape((-1, n, m))
        sign = 1.0 if is_ascend else -1.0
        big = jnp.asarray(jnp.inf, d.dtype)

        def one(mat):
            work = sign * mat  # minimize

            def body(_, carry):
                work, row_m, col_m = carry
                idx = jnp.argmin(work)
                i, j = idx // m, idx % m
                ok = work[i, j] < big
                row_m = jnp.where(ok, row_m.at[i].set(j), row_m)
                col_m = jnp.where(ok, col_m.at[j].set(i), col_m)
                work = jnp.where(ok, work.at[i, :].set(big), work)
                work = jnp.where(ok, work.at[:, j].set(big), work)
                return work, row_m, col_m

            row_m = jnp.full((n,), -1, jnp.int32)
            col_m = jnp.full((m,), -1, jnp.int32)
            steps = min(n, m) if topk <= 0 else min(topk, n, m)
            _, row_m, col_m = jax.lax.fori_loop(0, steps, body,
                                                (work, row_m, col_m))
            if threshold is not None:
                vals = jnp.take_along_axis(
                    mat, jnp.clip(row_m, 0)[:, None].astype("int32"),
                    axis=1)[:, 0]
                bad = (row_m >= 0) & ((vals < threshold) if not is_ascend
                                      else (vals > threshold))

                def clear_col(k, cm):
                    j = jnp.clip(row_m[k], 0)
                    return jnp.where(bad[k], cm.at[j].set(-1), cm)

                col_m = jax.lax.fori_loop(0, n, clear_col, col_m)
                row_m = jnp.where(bad, -1, row_m)
            return row_m, col_m

        rows, cols = jax.vmap(one)(flat)
        return (rows.reshape(batch_shape + (n,)),
                cols.reshape(batch_shape + (m,)))

    return apply_op_flat("bipartite_matching", fn, (data,), {}, n_outputs=2)


def roi_align(data, rois, pooled_size, spatial_scale=1.0, sample_ratio=2,
              position_sensitive=False):
    """ROI Align with bilinear sampling (reference:
    `src/operator/contrib/roi_align.cc`). data (N, C, H, W); rois (R, 5)
    rows [batch_idx, x1, y1, x2, y2] in image coords; returns
    (R, C, ph, pw).

    Divergence from the reference: `sample_ratio <= 0` (the reference's
    per-ROI adaptive ceil(roi_size/pooled_size) sampling) is data-dependent
    and cannot compile to static shapes; it maps to a fixed 2×2 sample
    grid per bin here. Pass an explicit positive sample_ratio for exact
    reference parity."""
    if position_sensitive:
        raise NotImplementedError(
            "roi_align: position_sensitive (PSRoIAlign) is not implemented")
    def fn(x, r):
        import jax

        jnp = _jnp()
        ph, pw = (pooled_size if isinstance(pooled_size, (tuple, list))
                  else (pooled_size, pooled_size))
        n, c, h, w = x.shape
        ns = int(sample_ratio) if sample_ratio > 0 else 2

        def one_roi(roi):
            bidx = roi[0].astype("int32")
            x1, y1, x2, y2 = (roi[1] * spatial_scale, roi[2] * spatial_scale,
                              roi[3] * spatial_scale, roi[4] * spatial_scale)
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            bin_w = rw / pw
            bin_h = rh / ph
            # ns×ns bilinear samples per bin, averaged
            gy = (y1 + (jnp.arange(ph)[:, None] + (jnp.arange(ns)[None, :]
                  + 0.5) / ns) * bin_h).reshape(-1)  # (ph*ns,)
            gx = (x1 + (jnp.arange(pw)[:, None] + (jnp.arange(ns)[None, :]
                  + 0.5) / ns) * bin_w).reshape(-1)  # (pw*ns,)
            img = x[bidx]  # (C, H, W)
            # shared bilinear gather (one sampler implementation for the
            # whole roi/spatial family; border mode = ROI-op convention)
            from ._spatial import _bilinear_nchw

            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            v = _bilinear_nchw(img, yy, xx, padding="border")
            v = v.reshape(c, ph, ns, pw, ns).mean(axis=(2, 4))
            return v

        return jax.vmap(one_roi)(r)

    return apply_op_flat("roi_align", fn, (data, rois), {})


def slice_like(data, shape_like, axes=None):
    """Slice `data` to match `shape_like`'s shape on `axes` (reference:
    `src/operator/tensor/matrix_op.cc` slice_like)."""
    target = tuple(shape_like.shape)

    def fn(d, s):  # noqa: ARG001
        sl = [slice(None)] * d.ndim
        ax = range(d.ndim) if axes is None else axes
        for a in ax:
            sl[a] = slice(0, target[a])
        return d[tuple(sl)]

    return apply_op_flat("slice_like", fn, (data, shape_like), {})


def broadcast_like(data, other, lhs_axes=None, rhs_axes=None):
    """Broadcast `data` to `other`'s shape (reference: matrix_op.cc
    broadcast_like)."""
    target = tuple(other.shape)

    def fn(d, o):  # noqa: ARG001
        jnp = _jnp()
        if lhs_axes is None:
            return jnp.broadcast_to(d, target)
        shape = list(d.shape)
        for la, ra in zip(lhs_axes, rhs_axes):
            shape[la] = target[ra]
        return jnp.broadcast_to(d, tuple(shape))

    return apply_op_flat("broadcast_like", fn, (data, other), {})


def batch_take(a, indices):
    """Per-row gather: out[i] = a[i, indices[i]] (reference:
    `src/operator/tensor/indexing_op.cc` batch_take)."""
    def fn(x, idx):
        jnp = _jnp()
        return jnp.take_along_axis(
            x, idx[..., None].astype("int32"), axis=-1)[..., 0]

    return apply_op_flat("batch_take", fn, (a, indices), {})


def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """SSD anchor generation (reference:
    `src/operator/contrib/multibox_prior.cc:30` MultiBoxPriorForward).

    data: (N, C, H, W) feature map (only H/W used). Output (1, H*W*A, 4)
    corner boxes in [0,1] coords, A = len(sizes) + len(ratios) - 1, laid
    out exactly like the reference: per cell, all sizes at ratios[0],
    then ratios[1:] at sizes[0]."""
    sizes = [float(s) for s in (sizes if isinstance(sizes, (list, tuple))
                                else [sizes])]
    ratios = [float(r) for r in (ratios if isinstance(ratios, (list, tuple))
                                 else [ratios])]

    def fn(x):
        jnp = _jnp()
        h, w = x.shape[2], x.shape[3]
        step_y = steps[0] if steps[0] > 0 else 1.0 / h
        step_x = steps[1] if steps[1] > 0 else 1.0 / w
        cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
        cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
        cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
        # per-anchor half extents (reference: w = size*H/W*sqrt(r)/2,
        # h = size/sqrt(r)/2)
        hw, hh = [], []
        r0 = (ratios[0] if ratios else 1.0) ** 0.5
        for s in sizes:
            hw.append(s * h / w * r0 / 2.0)
            hh.append(s / r0 / 2.0)
        for r in ratios[1:]:
            rs = r ** 0.5
            hw.append(sizes[0] * h / w * rs / 2.0)
            hh.append(sizes[0] / rs / 2.0)
        hw = jnp.asarray(hw, jnp.float32)   # (A,)
        hh = jnp.asarray(hh, jnp.float32)
        xmin = cxg[..., None] - hw
        ymin = cyg[..., None] - hh
        xmax = cxg[..., None] + hw
        ymax = cyg[..., None] + hh
        out = jnp.stack([xmin, ymin, xmax, ymax], -1).reshape(1, -1, 4)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        return out

    return apply_op_flat("multibox_prior", fn, (data,), {})


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):  # noqa: ARG001
    """SSD training target assignment (reference:
    `src/operator/contrib/multibox_target.cc`).

    anchor (1, N, 4) corners; label (B, M, 5) rows [cls, x1, y1, x2, y2]
    with cls = -1 padding; cls_pred (B, num_cls+1, N) provides the
    confidence ranking for hard negative mining (reference
    multibox_target.cc: negatives ranked by max non-background score;
    only the top `negative_mining_ratio × num_pos` — at least
    `minimum_negative_samples` — stay trainable background, the rest get
    `ignore_label`). Returns (loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N)) where cls_target is gt_class+1 (0 = background).

    Divergence: the reference matches gts to anchors sequentially
    (best-remaining pair each round); this op runs TWO simultaneous
    scatter rounds, which is exact unless >2 gts share one best anchor."""
    def fn(anc, lab, pred):
        jnp = _jnp()
        a = anc.reshape(-1, 4)
        n = a.shape[0]
        var = jnp.asarray(variances, jnp.float32)

        def one(gt, scores):
            cls = gt[:, 0]
            boxes = gt[:, 1:5]
            m_rows = gt.shape[0]
            valid = cls >= 0  # (M,)
            iou = _iou_corner(a, boxes)  # (N, M)
            iou = jnp.where(valid[None, :], iou, -1.0)
            best_gt = jnp.argmax(iou, axis=1)          # (N,)
            best_iou = jnp.take_along_axis(iou, best_gt[:, None],
                                           1)[:, 0]   # (N,)
            matched = best_iou >= overlap_threshold
            # force-match round 1: each VALID gt claims its best anchor.
            # Padding rows (cls=-1) route to dummy slot n so their scatter
            # can neither claim an anchor nor clobber a valid gt's claim.
            gt_range = jnp.arange(m_rows, dtype=jnp.int32)
            best_anchor = jnp.argmax(iou, axis=0)       # (M,)
            scatter_idx = jnp.where(valid, best_anchor, n)
            forced = jnp.zeros((n + 1,), bool).at[scatter_idx].set(True)[:n]
            forced_gt = jnp.zeros((n + 1,), jnp.int32).at[scatter_idx].set(
                gt_range)[:n]
            # round 2: gts that LOST the round-1 scatter (another gt wrote
            # the same anchor) claim their best anchor among unclaimed ones
            won = valid & (forced_gt[jnp.where(valid, best_anchor, 0)]
                           == gt_range) & forced[
                               jnp.where(valid, best_anchor, 0)]
            lost = valid & ~won
            iou2 = jnp.where(forced[:, None], -1.0, iou)  # mask claimed
            best_anchor2 = jnp.argmax(iou2, axis=0)
            scatter2 = jnp.where(lost, best_anchor2, n)
            forced2 = jnp.zeros((n + 1,), bool).at[scatter2].set(True)[:n]
            forced_gt2 = jnp.zeros((n + 1,), jnp.int32).at[scatter2].set(
                gt_range)[:n]
            forced_gt = jnp.where(forced2 & ~forced, forced_gt2, forced_gt)
            forced = forced | forced2
            gt_idx = jnp.where(forced, forced_gt, best_gt)
            matched = matched | forced
            mb = boxes[gt_idx]                          # (N, 4)
            # encode center-size offsets (reference TransformLocations)
            aw = a[:, 2] - a[:, 0]
            ah = a[:, 3] - a[:, 1]
            acx = (a[:, 0] + a[:, 2]) / 2
            acy = (a[:, 1] + a[:, 3]) / 2
            gw = jnp.maximum(mb[:, 2] - mb[:, 0], 1e-12)
            gh = jnp.maximum(mb[:, 3] - mb[:, 1], 1e-12)
            gcx = (mb[:, 0] + mb[:, 2]) / 2
            gcy = (mb[:, 1] + mb[:, 3]) / 2
            t = jnp.stack([(gcx - acx) / aw / var[0],
                           (gcy - acy) / ah / var[1],
                           jnp.log(gw / aw) / var[2],
                           jnp.log(gh / ah) / var[3]], -1)  # (N, 4)
            loc_t = jnp.where(matched[:, None], t, 0.0).reshape(-1)
            loc_m = jnp.where(matched[:, None],
                              jnp.ones((n, 4), jnp.float32),
                              0.0).reshape(-1)
            cls_t = jnp.where(matched, cls[gt_idx] + 1.0, 0.0)
            if negative_mining_ratio > 0:
                # hard negative mining: candidates are unmatched anchors
                # whose best IoU < negative_mining_thresh (the reference's
                # in-between band [thresh, overlap) is never trained as
                # background); top-k by max non-background confidence stay
                # background(0), every other unmatched anchor is ignored
                conf = scores[1:].max(axis=0) if scores.shape[0] > 1 \
                    else scores[0]
                neg = ~matched & (best_iou < negative_mining_thresh)
                num_pos = matched.sum()
                k = jnp.maximum(
                    (negative_mining_ratio * num_pos).astype(jnp.int32),
                    jnp.int32(minimum_negative_samples))
                neg_conf = jnp.where(neg, conf, -jnp.inf)
                rank = jnp.argsort(jnp.argsort(-neg_conf))  # 0 = hardest
                keep_neg = neg & (rank < k)
                cls_t = jnp.where(~matched & ~keep_neg,
                                  jnp.float32(ignore_label), cls_t)
            return loc_t, loc_m, cls_t

        import jax

        loc_t, loc_m, cls_t = jax.vmap(one)(lab, pred)
        return loc_t, loc_m, cls_t

    return apply_op_flat("multibox_target", fn, (anchor, label, cls_pred),
                         {}, n_outputs=3)


def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD detection decode + per-class NMS (reference:
    `src/operator/contrib/multibox_detection.cc`).

    cls_prob (B, num_cls+1, N) softmax class scores (bg at background_id);
    loc_pred (B, N*4); anchor (1, N, 4). Output (B, N, 6) rows
    [cls_id, score, x1, y1, x2, y2], suppressed rows -1 (reference
    convention)."""
    def fn(cp, lp, anc):
        jnp = _jnp()
        a = anc.reshape(-1, 4)
        n = a.shape[0]
        var = jnp.asarray(variances, jnp.float32)

        def one(scores, loc):
            loc = loc.reshape(n, 4)
            aw = a[:, 2] - a[:, 0]
            ah = a[:, 3] - a[:, 1]
            acx = (a[:, 0] + a[:, 2]) / 2
            acy = (a[:, 1] + a[:, 3]) / 2
            cx = loc[:, 0] * var[0] * aw + acx
            cy = loc[:, 1] * var[1] * ah + acy
            wdt = jnp.exp(loc[:, 2] * var[2]) * aw
            hgt = jnp.exp(loc[:, 3] * var[3]) * ah
            boxes = jnp.stack([cx - wdt / 2, cy - hgt / 2,
                               cx + wdt / 2, cy + hgt / 2], -1)
            if clip:
                boxes = jnp.clip(boxes, 0.0, 1.0)
            # winning non-background class: mask out the background row
            masked = scores.at[background_id].set(-1.0)
            cls_id = jnp.argmax(masked, axis=0)             # (N,)
            score = jnp.take_along_axis(masked, cls_id[None, :],
                                        0)[0]
            # reference id convention: background excluded from the output
            # id space (multibox_detection.cc: id = argmax shifted past bg)
            out_id = (cls_id - (cls_id > background_id).astype(cls_id.dtype)
                      ).astype(jnp.float32)
            keep = score > threshold
            rows = jnp.concatenate(
                [jnp.where(keep, out_id, -1.0)[:, None],
                 jnp.where(keep, score, -1.0)[:, None], boxes], -1)
            return rows

        import jax

        rows = jax.vmap(one)(cp, lp)
        # shared jax-level NMS core (no nested funnel call inside this fn)
        return _nms_core(rows, nms_threshold, threshold, nms_topk,
                         coord_start=2, score_index=1, id_index=0,
                         background_id=-1, force_suppress=force_suppress,
                         in_format="corner", out_format="corner")

    return apply_op_flat("multibox_detection", fn, (cls_prob, loc_pred,
                                                    anchor), {})
