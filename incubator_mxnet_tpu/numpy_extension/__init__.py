"""`mx.npx` — numpy-extension namespace: the NN operator corpus.

Reference: `python/mxnet/numpy_extension/` + kernels under `src/operator/nn/`
(Convolution, FullyConnected, BatchNorm, Pooling, softmax family, Dropout —
see SURVEY.md §2.3). TPU-native design notes:

- every op lowers to jax/lax primitives so XLA tiles matmuls/convs onto the
  MXU and fuses the elementwise epilogues (the role oneDNN/cuDNN fusion plays
  in the reference, `src/operator/subgraph/dnnl/`);
- ops that mutate auxiliary state (BatchNorm running stats — FMutateInputs in
  the reference) funnel through `utils.trace.register_aux_update` so they
  functionalize correctly under jit;
- dropout/random ops draw from the global RNG (`random.next_key`), which
  remains fresh under jit tracing (traced key + fold-in counter).
"""
from __future__ import annotations

import math

import numpy as onp

from .. import autograd
from ..base import np_dtype
from ..ndarray.ndarray import NDArray, apply_op, apply_op_flat
from ..random import next_key
from ..utils.trace import register_aux_update

__all__ = [
    "activation", "relu", "sigmoid", "softmax", "log_softmax", "masked_softmax",
    "masked_log_softmax", "leaky_relu", "fully_connected", "convolution",
    "deconvolution", "pooling", "batch_norm", "layer_norm", "group_norm",
    "residual_dropout_ln",
    "instance_norm", "l2_normalization", "dropout", "embedding", "one_hot",
    "pick", "topk", "batch_dot", "flash_attention", "sharding_constraint",
    "gather_nd", "scatter_nd", "sequence_mask",
    "sequence_last", "sequence_reverse", "rnn", "erf", "erfinv", "gamma",
    "gammaln", "digamma", "cast", "reshape", "arange_like", "shape_array",
    "stop_gradient", "foreach", "while_loop", "cond", "set_np", "reset_np",
    "is_np_array", "is_np_shape", "waitall", "load", "save", "seed",
    "gelu", "smooth_l1", "clip_global_norm",
    "box_iou", "box_nms", "box_encode", "box_decode", "bipartite_matching",
    "roi_align", "slice_like", "broadcast_like", "batch_take",
    # contrib corpus (_contrib_misc / _transformer)
    "quadratic", "index_copy", "index_array", "gradientmultiplier",
    "dynamic_reshape", "count_sketch", "hawkesll", "round_ste", "sign_ste",
    "all_finite", "multi_all_finite", "ctc_loss", "adaptive_avg_pooling2d",
    "bilinear_resize2d", "batch_norm_with_relu", "sync_batch_norm",
    "softsign", "pad", "norm", "slice", "slice_channel", "add_n",
    "interleaved_matmul_selfatt_qk", "interleaved_matmul_selfatt_valatt",
    "interleaved_matmul_encdec_qk", "interleaved_matmul_encdec_valatt",
    "div_sqrt_dim", "sldwin_atten_score", "sldwin_atten_context",
    "sldwin_atten_mask_like",
]


from ._boxes import (  # noqa: F401
    batch_take, bipartite_matching, box_decode, box_encode, box_iou,
    box_nms, broadcast_like, multibox_detection, multibox_prior,
    multibox_target, roi_align, slice_like,
)
from ._contrib_misc import (  # noqa: F401
    adaptive_avg_pooling2d, add_n, all_finite, batch_norm_with_relu,
    bilinear_resize2d, count_sketch, ctc_loss, dynamic_reshape,
    gradientmultiplier, hawkesll, index_array, index_copy,
    multi_all_finite, norm, pad, quadratic, round_ste, sign_ste,
    slice, slice_channel, softsign, sync_batch_norm,
)
from ._detection import (  # noqa: F401
    deformable_psroi_pooling, mrcnn_mask_target, multi_proposal,
    proposal, psroi_pooling, rroi_align,
)
from ._graph import (  # noqa: F401
    dgl_adjacency, dgl_csr_neighbor_non_uniform_sample,
    dgl_csr_neighbor_uniform_sample, dgl_graph_compact, dgl_subgraph,
    edge_id, getnnz,
)
from ._spatial import (  # noqa: F401
    bilinear_sampler, correlation, deformable_convolution, fft,
    grid_generator, ifft, modulated_deformable_convolution, roi_pooling,
    spatial_transformer,
)
from ._transformer import (  # noqa: F401
    div_sqrt_dim, interleaved_matmul_encdec_qk,
    interleaved_matmul_encdec_valatt, interleaved_matmul_selfatt_qk,
    interleaved_matmul_selfatt_valatt, sldwin_atten_context,
    sldwin_atten_mask_like, sldwin_atten_score,
)


def __getattr__(name):
    if name == "Custom":  # lazy: operator.py imports back into this package
        from ..operator import Custom

        return Custom
    if name == "image":
        # npx.image = the op namespace (to_tensor/normalize/resize/...,
        # reference `src/operator/image/`) PLUS the imperative augmenter
        # classes re-exported for back-compat (`mx.image`)
        import importlib
        import types

        from .. import image as _imperative

        # importlib (not `from . import image`): the relative import form
        # re-enters this __getattr__ and recurses
        _ops = importlib.import_module(
            "incubator_mxnet_tpu.numpy_extension.image")

        mod = types.ModuleType("incubator_mxnet_tpu.npx.image")
        for src in (_imperative, _ops):
            for n in dir(src):
                if not n.startswith("_"):
                    setattr(mod, n, getattr(src, n))
        globals()["image"] = mod          # cache: resolve once
        return mod
    raise AttributeError(f"module 'npx' has no attribute {name!r}")


def _safe_accumulation():
    """MXNET_SAFE_ACCUMULATION=1 → fp32 accumulation for low-precision
    inputs in softmax/norm reductions (reference env_var.md; matmul
    accumulation is fp32 on the MXU regardless)."""
    import os

    return os.environ.get("MXNET_SAFE_ACCUMULATION") == "1"


def _jnp():
    import jax.numpy as jnp

    return jnp


def _lax():
    import jax.lax as lax

    return lax


def _tuple(x, n):
    if x is None:
        return (1,) * n
    if isinstance(x, int):
        return (x,) * n
    return tuple(x)


# ---------------------------------------------------------------------------
# activations / softmax family
# ---------------------------------------------------------------------------

def relu(data):
    return apply_op("relu", lambda x: _jnp().maximum(x, 0), (data,))


def sigmoid(data):
    import jax

    return apply_op("sigmoid", jax.nn.sigmoid, (data,))


def gelu(data, approximate=True):
    import jax

    return apply_op("gelu", lambda x: jax.nn.gelu(x, approximate=approximate), (data,))


def activation(data, act_type="relu", **kwargs):  # noqa: ARG001
    import jax

    fns = {
        "relu": lambda x: _jnp().maximum(x, 0),
        "sigmoid": jax.nn.sigmoid,
        "tanh": _jnp().tanh,
        "softrelu": jax.nn.softplus,
        "softsign": lambda x: x / (1 + _jnp().abs(x)),
        "log_sigmoid": jax.nn.log_sigmoid,
        "mish": lambda x: x * _jnp().tanh(jax.nn.softplus(x)),
        "gelu": jax.nn.gelu,
        "silu": jax.nn.silu,
    }
    if act_type not in fns:
        raise ValueError(f"unknown activation {act_type!r}")
    return apply_op(f"activation.{act_type}", fns[act_type], (data,))


def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, **kwargs):  # noqa: ARG001
    import jax

    jnp = _jnp()
    if act_type == "leaky":
        return apply_op("leaky_relu", lambda x: jnp.where(x >= 0, x, slope * x), (data,))
    if act_type == "elu":
        return apply_op("elu", lambda x: jax.nn.elu(x, alpha=slope), (data,))
    if act_type == "selu":
        return apply_op("selu", jax.nn.selu, (data,))
    if act_type == "gelu":
        return apply_op("gelu", lambda x: jax.nn.gelu(x, approximate=False), (data,))
    if act_type == "prelu":
        def f(x, g):
            g2 = g.reshape((1, -1) + (1,) * (x.ndim - 2)) if g.ndim == 1 and x.ndim > 2 else g
            return jnp.where(x >= 0, x, g2 * x)

        return apply_op("prelu", f, (data, gamma))
    if act_type == "rrelu":
        if autograd.is_training():
            import jax.random as jr

            def f(x):
                u = jr.uniform(next_key(), x.shape, minval=lower_bound,
                               maxval=upper_bound)
                return jnp.where(x >= 0, x, u * x)

            return apply_op("rrelu", f, (data,))
        mid = (lower_bound + upper_bound) / 2.0
        return apply_op("rrelu", lambda x: jnp.where(x >= 0, x, mid * x), (data,))
    raise ValueError(f"unknown leaky_relu act_type {act_type!r}")


def softmax(data, axis=-1, length=None, temperature=None, use_length=False,
            dtype=None, **kwargs):  # noqa: ARG001
    import jax

    jnp = _jnp()
    safe = _safe_accumulation()

    def f(x, ln):
        in_dt = x.dtype
        if safe and str(in_dt) in ("float16", "bfloat16"):
            # MXNET_SAFE_ACCUMULATION: reduce in fp32 (reference
            # softmax.cc AType promotion), cast back unless dtype= says
            # otherwise
            x = x.astype("float32")
        if temperature is not None and temperature != 1.0:
            x = x / temperature
        if ln is not None:
            idx = jnp.arange(x.shape[axis])
            shape = [1] * x.ndim
            shape[axis] = -1
            mask = idx.reshape(shape) < jnp.expand_dims(ln, axis=axis)
            x = jnp.where(mask, x, -jnp.inf)
            out = jax.nn.softmax(x, axis=axis)
            out = jnp.where(mask, out, 0.0)
        else:
            out = jax.nn.softmax(x, axis=axis)
        if dtype:
            return out.astype(np_dtype(dtype))
        return out.astype(in_dt) if safe else out

    ln = length if (use_length or length is not None) else None
    return apply_op("softmax", f,
                    (data, ln) if ln is not None else (data, None),
                    static_info={"axis": axis})


def batch_flatten(data, **kwargs):  # noqa: ARG001
    """Collapse all non-batch dims to 2-D (reference `Flatten` op,
    `src/operator/tensor/matrix_op.cc` — output (batch, -1))."""
    return apply_op("batch_flatten",
                    lambda x: x.reshape(x.shape[0], -1), (data,))


def softmin(data, axis=-1, temperature=None, dtype=None, **kwargs):  # noqa: ARG001
    """softmax of the negated input (reference: `src/operator/nn/softmax.cc`
    softmin registration)."""
    import jax

    def f(x):
        if temperature is not None and temperature != 1.0:
            x = x / temperature
        out = jax.nn.softmax(-x, axis=axis)
        return out.astype(np_dtype(dtype)) if dtype else out

    return apply_op("softmin", f, (data,))


def reshape_like(lhs, rhs, lhs_begin=None, lhs_end=None, rhs_begin=None,
                 rhs_end=None, **kwargs):  # noqa: ARG001
    """Reshape lhs to rhs's shape (reference:
    `src/operator/tensor/elemwise_unary_op_basic.cc` reshape_like).
    The range form replaces lhs.shape[lhs_begin:lhs_end] with
    rhs.shape[rhs_begin:rhs_end] (reference ReshapeLikeParam)."""
    lshape = tuple((lhs._data if hasattr(lhs, "_data") else lhs).shape)
    rshape = tuple((rhs._data if hasattr(rhs, "_data") else rhs).shape)
    lb = 0 if lhs_begin is None else lhs_begin
    le = len(lshape) if lhs_end is None else lhs_end
    rb = 0 if rhs_begin is None else rhs_begin
    re_ = len(rshape) if rhs_end is None else rhs_end
    lb += len(lshape) if lb < 0 else 0
    le += len(lshape) if le < 0 else 0
    rb += len(rshape) if rb < 0 else 0
    re_ += len(rshape) if re_ < 0 else 0
    shape = lshape[:lb] + rshape[rb:re_] + lshape[le:]
    import math

    if math.prod(shape) != math.prod(lshape):
        raise ValueError(
            f"reshape_like: target shape {shape} has "
            f"{math.prod(shape)} elements, lhs has {math.prod(lshape)}")
    return apply_op("reshape_like", lambda x: x.reshape(shape), (lhs,))


def log_softmax(data, axis=-1, temperature=None, dtype=None, **kwargs):  # noqa: ARG001
    import jax

    def f(x):
        if temperature is not None and temperature != 1.0:
            x = x / temperature
        out = jax.nn.log_softmax(x, axis=axis)
        return out.astype(np_dtype(dtype)) if dtype else out

    return apply_op("log_softmax", f, (data,))


def masked_softmax(data, mask=None, axis=-1, temperature=1.0, **kwargs):  # noqa: ARG001
    import jax

    jnp = _jnp()

    def f(x, m):
        if temperature != 1.0:
            x = x / temperature
        if m is not None:
            x = jnp.where(m.astype(bool), x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        if m is not None:
            out = jnp.where(m.astype(bool), out, 0.0)
        return out

    return apply_op("masked_softmax", f, (data, mask))


def masked_log_softmax(data, mask=None, axis=-1, temperature=1.0):
    import jax

    jnp = _jnp()

    def f(x, m):
        if temperature != 1.0:
            x = x / temperature
        if m is not None:
            x = jnp.where(m.astype(bool), x, -jnp.inf)
        return jax.nn.log_softmax(x, axis=axis)

    return apply_op("masked_log_softmax", f, (data, mask))


# ---------------------------------------------------------------------------
# dense / conv / pooling  (the MXU path)
# ---------------------------------------------------------------------------

def fully_connected(x, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    def f(x, w, b):
        from ..amp import amp_active, cast_for_matmul

        if flatten and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        if amp_active():
            x, w = cast_for_matmul(x, w)
        y = jnp.matmul(x, w.T) if not flatten or x.ndim <= 2 else x @ w.T
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    if no_bias or bias is None:
        return apply_op("fully_connected", lambda x, w: f(x, w, None), (x, weight))
    return apply_op("fully_connected", f, (x, weight, bias))


def _conv_dn(ndim, layout):
    if layout is None:
        layout = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[ndim]
    kernel_layout = {"NCW": "OIW", "NCHW": "OIHW", "NCDHW": "OIDHW",
                     "NWC": "WIO", "NHWC": "HWIO", "NDHWC": "DHWIO"}[layout]
    return layout, kernel_layout


def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                layout=None, **kwargs):  # noqa: ARG001
    lax = _lax()
    ndim = len(kernel) if kernel is not None else data.ndim - 2
    stride = _tuple(stride, ndim)
    dilate = _tuple(dilate, ndim)
    pad = _tuple(pad, ndim) if pad is not None else (0,) * ndim
    lhs_l, rhs_l = _conv_dn(ndim, layout)

    def f(x, w, b):
        from ..amp import amp_active, cast_for_matmul

        if amp_active():
            x, w = cast_for_matmul(x, w)
        y = lax.conv_general_dilated(
            x, w, window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=(lhs_l, rhs_l, lhs_l),
            feature_group_count=num_group,
            preferred_element_type=None,
        )
        if b is not None:
            c_axis = lhs_l.index("C")
            shape = [1] * y.ndim
            shape[c_axis] = -1
            y = y + b.reshape(shape).astype(y.dtype)
        return y

    if no_bias or bias is None:
        return apply_op("convolution", lambda x, w: f(x, w, None), (data, weight))
    return apply_op("convolution", f, (data, weight, bias))


def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, num_filter=None, num_group=1, no_bias=False,
                  layout=None, target_shape=None, **kwargs):  # noqa: ARG001
    lax = _lax()
    ndim = len(kernel) if kernel is not None else data.ndim - 2
    stride = _tuple(stride, ndim)
    dilate = _tuple(dilate, ndim)
    pad = _tuple(pad, ndim) if pad is not None else (0,) * ndim
    lhs_l, rhs_l = _conv_dn(ndim, layout)

    def f(x, w, b):
        # transposed conv: weight stored as (in, out/g, *k) in the reference
        y = lax.conv_transpose(
            x, w, strides=stride,
            padding=[(d * (k - 1) - p, d * (k - 1) - p)
                     for k, p, d in zip(kernel, pad, dilate)],
            rhs_dilation=dilate,
            dimension_numbers=(lhs_l, rhs_l.replace("O", "X").replace("I", "O").replace("X", "I"), lhs_l),
            transpose_kernel=True,
        )
        if b is not None:
            c_axis = lhs_l.index("C")
            shape = [1] * y.ndim
            shape[c_axis] = -1
            y = y + b.reshape(shape)
        return y

    if no_bias or bias is None:
        return apply_op("deconvolution", lambda x, w: f(x, w, None), (data, weight))
    return apply_op("deconvolution", f, (data, weight, bias))


def pooling(data, kernel=None, stride=None, pad=None, pool_type="max",
            global_pool=False, layout=None, count_include_pad=True,
            pooling_convention="valid", **kwargs):  # noqa: ARG001
    jnp = _jnp()
    lax = _lax()
    ndim = data.ndim - 2
    lhs_l, _ = _conv_dn(ndim, layout)
    spatial_axes = tuple(i for i, c in enumerate(lhs_l) if c not in ("N", "C"))

    if global_pool:
        red = {"max": jnp.max, "avg": jnp.mean, "sum": jnp.sum,
               "lp": lambda x, axis, keepdims: jnp.power(
                   jnp.sum(jnp.power(jnp.abs(x), 2), axis=axis, keepdims=keepdims), 0.5)}
        fn = red[pool_type]
        return apply_op("global_pool",
                        lambda x: fn(x, axis=spatial_axes, keepdims=True), (data,))

    kernel = _tuple(kernel, ndim)
    stride = _tuple(stride, ndim)
    pad = _tuple(pad, ndim) if pad is not None else (0,) * ndim
    window = [1] * data.ndim
    strides = [1] * data.ndim
    padding = [(0, 0)] * data.ndim
    for ax, k, s, p in zip(spatial_axes, kernel, stride, pad):
        window[ax] = k
        strides[ax] = s
        padding[ax] = (p, p)

    def _pad_for(x):
        # 'full' = ceil-mode output shape (reference PoolingParam
        # pooling_convention, `src/operator/nn/pooling-inl.h`): extend the
        # high-side padding so a partial final window is still emitted
        if pooling_convention != "full":
            return padding
        padl = list(padding)
        for ax, k, s, p in zip(spatial_axes, kernel, stride, pad):
            span = x.shape[ax] + 2 * p - k
            rem = span % s
            if rem:
                lo, hi = padl[ax]
                padl[ax] = (lo, hi + (s - rem))
        return tuple(padl)

    if pool_type == "max":
        def f(x):
            # integer identity for int inputs (int8 requantize chains pool
            # their CODES — max commutes with the monotone quantization)
            init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                    else jnp.asarray(jnp.iinfo(x.dtype).min, x.dtype))
            return lax.reduce_window(x, init, lax.max, tuple(window),
                                     tuple(strides), _pad_for(x))
    elif pool_type in ("avg", "sum"):
        def f(x):
            pads = _pad_for(x)
            s = lax.reduce_window(x, 0.0, lax.add, tuple(window),
                                  tuple(strides), pads)
            if pool_type == "sum":
                return s
            if count_include_pad and pooling_convention != "full":
                return s / float(onp.prod(kernel))
            ones = jnp.ones(x.shape, x.dtype)
            if count_include_pad:
                # 'full' + include_pad: the reference divides a partial
                # final window by its size CLIPPED to height+pad
                # (pool.h hend=min(hstart+k, height+pad)), so pad cells
                # count but the ceil-extension does not — pre-pad the
                # ones with the REAL padding and reduce with only the
                # ceil extension as window padding
                np_pad = [(0, 0)] * x.ndim
                extra = [(0, 0)] * x.ndim
                for ax, (lo, hi) in enumerate(pads):
                    rl, rh = padding[ax]
                    np_pad[ax] = (rl, rh)
                    extra[ax] = (lo - rl, hi - rh)
                ones = jnp.pad(ones, np_pad, constant_values=1)
                cnt = lax.reduce_window(ones, 0.0, lax.add, tuple(window),
                                        tuple(strides), tuple(extra))
            else:
                cnt = lax.reduce_window(ones, 0.0, lax.add, tuple(window),
                                        tuple(strides), pads)
            return s / cnt
    else:
        raise ValueError(f"unsupported pool_type {pool_type!r}")
    return apply_op(f"pooling.{pool_type}", f, (data,))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

def batch_norm(x, gamma, beta, running_mean, running_var, eps=1e-5,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, **kwargs):  # noqa: ARG001
    jnp = _jnp()
    training = autograd.is_training() and not use_global_stats
    reduce_axes = tuple(i for i in range(x.ndim) if i != axis)
    shape = [1] * x.ndim
    shape[axis] = -1

    if training:
        def f(xv, g, b, rm, rv):
            mean = jnp.mean(xv, axis=reduce_axes)
            var = jnp.var(xv, axis=reduce_axes)
            gg = jnp.ones_like(g) if fix_gamma else g
            inv = gg * (1.0 / jnp.sqrt(var + eps))
            out = (xv - mean.reshape(shape)) * inv.reshape(shape) + b.reshape(shape)
            return out, mean, var

        out, bmean, bvar = apply_op("batch_norm", f,
                                    (x, gamma, beta, running_mean, running_var),
                                    n_outputs=3)
        # running-stat update (FMutateInputs semantics), functionalized under jit
        m = momentum
        register_aux_update(running_mean,
                            running_mean._data * m + bmean._data * (1 - m))
        register_aux_update(running_var,
                            running_var._data * m + bvar._data * (1 - m))
        if output_mean_var:
            return out, bmean, bvar
        return out

    def f(xv, g, b, rm, rv):
        gg = jnp.ones_like(g) if fix_gamma else g
        inv = gg * (1.0 / jnp.sqrt(rv + eps))
        return (xv - rm.reshape(shape)) * inv.reshape(shape) + b.reshape(shape)

    out = apply_op("batch_norm", f, (x, gamma, beta, running_mean, running_var))
    if output_mean_var:
        return out, running_mean, running_var
    return out


def _placed_on_cpu(a):
    """True when an EAGER jax array is committed to cpu devices (the
    check_consistency cpu leg on a chip host); tracers follow the
    process default backend."""
    try:
        return all(d.platform == "cpu" for d in a.devices())
    except Exception:
        return False


def layer_norm(data, gamma=None, beta=None, axis=-1, eps=1e-5, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    if gamma is not None and beta is not None:
        import jax as _jax

        from ..ops import layer_norm as _ln

        xv = data._data if isinstance(data, NDArray) else data
        if (_jax.default_backend() == "tpu" and not _placed_on_cpu(xv)
                and _ln.supports(xv.shape, axis, xv.shape[-1])
                and jnp.issubdtype(xv.dtype, jnp.floating)):
            # fused pallas path: one HBM pass fwd, fused bwd with row-stat
            # residuals (see ops/layer_norm.py)
            return apply_op(
                "layer_norm",
                lambda x, g, b: _ln.layer_norm(x, g, b, eps=eps),
                (data, gamma, beta))

    def f(x, g, b):
        # dtype-preserving with f32 internal math: the statistics and the
        # normalize are always computed in float32 (the reference's
        # FP32_FUNCS discipline), but the output is written back in the
        # input dtype — under bf16 AMP this halves LN HBM traffic, which
        # profiling shows dominates the op (the math itself is free)
        import jax as _jax

        xd = x.dtype
        x = x.astype(jnp.float32)
        mean = jnp.mean(x, axis=axis, keepdims=True)
        var = jnp.var(x, axis=axis, keepdims=True)
        out = (x - mean) * _jax.lax.rsqrt(var + eps)
        if g is not None:
            g = g.astype(jnp.float32)
            out = out * jnp.expand_dims(g, tuple(i for i in range(x.ndim)
                                                 if i != (axis % x.ndim))) \
                if g.ndim == 1 and x.ndim > 1 else out * g
        if b is not None:
            b = b.astype(jnp.float32)
            out = out + (jnp.expand_dims(b, tuple(i for i in range(x.ndim)
                                                  if i != (axis % x.ndim)))
                         if b.ndim == 1 and x.ndim > 1 else b)
        return out.astype(xd)

    return apply_op("layer_norm", f, (data, gamma, beta))


def group_norm(data, gamma=None, beta=None, num_groups=1, eps=1e-5, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    def f(x, g, b):
        n, c = x.shape[0], x.shape[1]
        rest = x.shape[2:]
        xg = x.reshape((n, num_groups, c // num_groups) + rest)
        axes = tuple(range(2, xg.ndim))
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        out = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
        shape = [1, c] + [1] * len(rest)
        if g is not None:
            out = out * g.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out

    return apply_op("group_norm", f, (data, gamma, beta))


def instance_norm(data, gamma=None, beta=None, eps=1e-5, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    def f(x, g, b):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        out = (x - mean) / jnp.sqrt(var + eps)
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
        if g is not None:
            out = out * g.reshape(shape)
        if b is not None:
            out = out + b.reshape(shape)
        return out

    return apply_op("instance_norm", f, (data, gamma, beta))


def l2_normalization(data, eps=1e-10, mode="instance"):
    jnp = _jnp()

    def f(x):
        if mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif mode == "channel":
            axes = (1,)
        elif mode == "spatial":
            axes = tuple(range(2, x.ndim))
        else:
            raise ValueError(mode)
        norm = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + eps)
        return x / norm

    return apply_op("l2_normalization", f, (data,))


# ---------------------------------------------------------------------------
# dropout / embedding / indexing helpers
# ---------------------------------------------------------------------------

def dropout(data, p=0.5, axes=(), mode="training", **kwargs):  # noqa: ARG001
    jnp = _jnp()
    apply = (mode == "always") or autograd.is_training()
    if not apply or p == 0:
        return data if isinstance(data, NDArray) else NDArray(data)
    import jax.random as jr

    from ..ops import dropout as _hw

    key = next_key()
    dshape = tuple((data._data if isinstance(data, NDArray) else data).shape)
    ddtype = (data._data if isinstance(data, NDArray) else data).dtype
    if _hw.supports(dshape, axes, ddtype, p) and _hw.use_kernel(key):
        # hardware-RNG pallas kernel: rescues the threefry-keyed path from
        # VPU bit-gen cost (see ops/dropout.py `use_kernel` for the
        # measured dispatch policy)
        def f(x):
            return _hw.dropout(x, key, p)

        return apply_op("dropout", f, (data,))

    def f(x):
        shape = list(x.shape)
        if axes:
            for ax in axes:
                shape[ax] = 1
        keep = jr.bernoulli(key, 1.0 - p, tuple(shape))
        return jnp.where(keep, x / (1.0 - p), 0.0)

    return apply_op("dropout", f, (data,))


def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    def f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        return out.astype(np_dtype(dtype)) if dtype else out

    from ..ndarray.ndarray import _is_tracer

    if not sparse_grad or _is_tracer(getattr(data, "_data", data)) \
            or _is_tracer(weight._data):
        # dense path; under a hybridize/jit trace XLA's scatter-add IS the
        # efficient embedding gradient, so sparse bookkeeping is eager-only
        return apply_op("embedding", f, (data, weight))

    # sparse_grad=True (reference: EmbeddingOp row_sparse gradient,
    # `src/operator/tensor/indexing_op.cc`): custom tape node whose backward
    # emits a RowSparseNDArray cotangent for `weight` — only the looked-up
    # rows are stored, never a (vocab, dim) dense buffer.
    from .. import autograd as _ag
    from ..autograd import TapeNode
    from ..ndarray.ndarray import _ShapeDtype
    from ..ndarray.sparse import RowSparseNDArray

    idx_val = data._data if isinstance(data, NDArray) else jnp.asarray(data)
    w_arr = weight
    out = NDArray(f(idx_val, w_arr._data))

    if _ag.is_recording() and (w_arr._node is not None
                               or w_arr._grad is not None):
        w_shape = tuple(w_arr.shape)

        def vjp_fn(cot):
            cot = cot[0] if isinstance(cot, tuple) else cot
            flat_idx = idx_val.reshape(-1).astype(jnp.int32)
            flat_cot = cot.reshape(-1, cot.shape[-1])
            return (None,
                    RowSparseNDArray(flat_cot, flat_idx, w_shape))

        node = TapeNode(None, [idx_val, w_arr._data],
                        [data if isinstance(data, NDArray) else NDArray(idx_val),
                         w_arr],
                        1, "embedding_sparse", vjp_fn=vjp_fn)
        node.out_avals = [_ShapeDtype(out._data)]
        node.tuple_out = False
        out._node = node
        out._out_idx = 0
    return out


def one_hot(data, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    import jax

    def f(idx):
        oh = jax.nn.one_hot(idx.astype("int32"), depth, dtype=np_dtype(dtype))
        return oh * (on_value - off_value) + off_value

    return apply_op("one_hot", f, (data,))


def pick(data, index, axis=-1, mode="clip", keepdims=False):
    jnp = _jnp()

    def f(x, idx):
        idx = idx.astype(jnp.int32)
        if mode == "clip":
            idx = jnp.clip(idx, 0, x.shape[axis] - 1)
        else:
            idx = idx % x.shape[axis]
        out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis=axis), axis=axis)
        return out if keepdims else jnp.squeeze(out, axis=axis)

    return apply_op("pick", f, (data, index))


def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    jnp = _jnp()
    lax = _lax()

    def f(x):
        xm = jnp.moveaxis(x, axis, -1)
        vals, idx = lax.top_k(-xm if is_ascend else xm, k)
        if is_ascend:
            vals = -vals
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
        if ret_typ == "value":
            return vals
        if ret_typ == "indices":
            return idx.astype(np_dtype(dtype))
        if ret_typ == "both":
            return vals, idx.astype(np_dtype(dtype))
        if ret_typ == "mask":
            m = jnp.zeros(xm.shape, dtype=np_dtype(dtype))
            m = m.at[..., idx].set(1)  # approximate
            return jnp.moveaxis(m, -1, axis)
        raise ValueError(ret_typ)

    n_outputs = 2 if ret_typ == "both" else 1
    return apply_op("topk", f, (data,), n_outputs=n_outputs)


def flash_attention(query, key, value, valid_length=None, causal=False,
                    sm_scale=None, layout="bhtd"):
    """Fused memory-linear attention — the pallas kernel in
    `ops/flash_attention.py` (reference role:
    `src/operator/subgraph/dnnl/dnnl_transformer_qk_property.h`).

    `layout`: "bhtd" for (B, H, T, D) tensors, "bthd" for (B, T, H, D) —
    the fused-qkv projection layout; passing it directly avoids
    materializing head transposes on the XLA path.
    `valid_length`: (B,) valid sequence lengths (replaces a dense mask).
    Differentiable (flash backward kernels via custom_vjp)."""
    from ..ops.flash_attention import flash_attention as _flash

    if valid_length is None:
        return apply_op(
            "flash_attention",
            lambda q, k, v: _flash(q, k, v, causal=causal, sm_scale=sm_scale,
                                   layout=layout),
            (query, key, value))
    return apply_op(
        "flash_attention",
        lambda q, k, v, vl: _flash(q, k, v, lengths=vl, causal=causal,
                                   sm_scale=sm_scale, layout=layout),
        (query, key, value, valid_length))


def residual_dropout_ln(x, h, gamma, beta, p=0.0, eps=1e-5, axis=-1):
    """``layer_norm(x + dropout_p(h))`` — the post-LN transformer residual
    site, fused into ONE pallas pass on TPU (`ops/fused_block.py`; 24
    such sites in BERT-base cost ~45 ms/step unfused at seq 512). Off
    TPU, or for unsupported layouts, falls back to the composed ops with
    identical semantics."""
    import jax as _jax

    from .. import autograd
    from ..ops import fused_block as _fb

    jnp = _jnp()
    p_eff = float(p) if autograd.is_training() else 0.0
    xv = x._data if isinstance(x, NDArray) else x
    hv = h._data if isinstance(h, NDArray) else h
    ndim = len(xv.shape)
    if (_jax.default_backend() == "tpu" and axis in (-1, ndim - 1)
            and not _placed_on_cpu(xv)
            and _fb.supports(xv.shape, xv.shape[-1])
            and tuple(xv.shape) == tuple(hv.shape)  # kernel can't broadcast
            and p_eff < 1.0                         # p=1: composed path
            and jnp.issubdtype(xv.dtype, jnp.floating)):
        if p_eff > 0:
            key = next_key()
            raw = _jax.random.key_data(key) if jnp.issubdtype(
                getattr(key, "dtype", None), _jax.dtypes.prng_key) else key
            seeds = raw.reshape(-1)[:2].astype(jnp.int32)
        else:
            # no key consumed when nothing is random — keeps seeded runs
            # bit-identical with the composed fallback (which also draws
            # none) across backends and across eval passes
            seeds = jnp.zeros((2,), jnp.int32)

        def f(xa, ha, g, b, s):
            return _fb.residual_dropout_ln(xa, ha, g, b, p_eff, s, eps=eps)

        return apply_op("residual_dropout_ln", f,
                        (x, h, gamma, beta, NDArray(seeds)))
    d = dropout(h, p=p) if p else h
    return layer_norm(x + d, gamma, beta, axis=axis, eps=eps)


def gelu_dropout(data, p=0.0, impl="auto"):
    """``dropout_p(gelu(x))``.

    impl="auto"/"xla": the composed ops — measured FASTEST on TPU when
    the input is a matmul output (XLA fuses gelu+mask into the matmul
    epilogue, so a pallas kernel boundary here COSTS ~2 ms/step on
    BERT-base: it forces the 402 MB hidden activation to materialize).
    impl="pallas": the in-VMEM-RNG kernel (`ops/fused_block.py`
    gelu_dropout) for call sites where the input is NOT epilogue-fusable
    (e.g. already materialized by a collective or a concat)."""
    import jax as _jax

    from .. import autograd
    from ..ops import fused_block as _fb

    jnp = _jnp()
    p_eff = float(p) if autograd.is_training() else 0.0
    xv = data._data if isinstance(data, NDArray) else data
    if (impl == "pallas" and _jax.default_backend() == "tpu"
            and 0 < p_eff < 1.0 and not _placed_on_cpu(xv)
            and len(xv.shape) >= 2 and xv.shape[-1] % 128 == 0
            and jnp.issubdtype(xv.dtype, jnp.floating)):
        key = next_key()
        raw = _jax.random.key_data(key) if jnp.issubdtype(
            getattr(key, "dtype", None), _jax.dtypes.prng_key) else key
        seeds = raw.reshape(-1)[:2].astype(jnp.int32)

        def f(u, s):
            return _fb.gelu_dropout(u, p_eff, s)

        return apply_op("gelu_dropout", f, (data, NDArray(seeds)))
    out = gelu(data, approximate=False)
    return dropout(out, p=p) if p else out


def sharding_constraint(data, spec):
    """Annotate an activation with a mesh sharding (sequence/tensor parallel
    layout hints inside a traced step). Identity when no mesh is active or
    when executing eagerly — the constraint only matters under jit where
    GSPMD propagates it. Axes not present in the active mesh are dropped,
    so model code can name 'sp'/'tp' axes unconditionally."""
    import jax

    from ..parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return data
    P = jax.sharding.PartitionSpec
    spec = spec if isinstance(spec, P) else P(*spec)
    names = set(mesh.axis_names)

    def _clean(axis):
        if axis is None:
            return None
        if isinstance(axis, (list, tuple)):
            kept = [a for a in axis if a in names]
            return tuple(kept) if kept else None
        return axis if axis in names else None

    cleaned = P(*[_clean(a) for a in spec])
    sharding = jax.sharding.NamedSharding(mesh, cleaned)

    def f(x):
        if not isinstance(x, jax.core.Tracer):
            return x  # eager: placement is the runtime's business
        return jax.lax.with_sharding_constraint(x, sharding)

    return apply_op("sharding_constraint", f, (data,))


def batch_dot(a, b, transpose_a=False, transpose_b=False, **kwargs):  # noqa: ARG001
    jnp = _jnp()

    def f(x, y):
        from ..amp import amp_active, cast_for_matmul

        if amp_active():
            x, y = cast_for_matmul(x, y)
        if transpose_a:
            x = jnp.swapaxes(x, -1, -2)
        if transpose_b:
            y = jnp.swapaxes(y, -1, -2)
        return jnp.matmul(x, y)

    # transpose flags ride in the eqn name so partition-backend guards
    # (e.g. flash attention's QK-stage check) can see them — shapes alone
    # cannot distinguish q@k^T from q@k when k is square (r3 ADVICE)
    return apply_op("batch_dot", f, (a, b),
                    static_info={"transpose_a": bool(transpose_a),
                                 "transpose_b": bool(transpose_b)})


def gather_nd(data, indices):
    jnp = _jnp()

    def f(x, idx):
        idx = idx.astype(jnp.int32)
        return x[tuple(idx[i] for i in range(idx.shape[0]))]

    return apply_op("gather_nd", f, (data, indices))


def scatter_nd(data, indices, shape):
    jnp = _jnp()

    def f(d, idx):
        idx = idx.astype(jnp.int32)
        out = jnp.zeros(shape, d.dtype)
        return out.at[tuple(idx[i] for i in range(idx.shape[0]))].add(d)

    return apply_op("scatter_nd", f, (data, indices))


# ---------------------------------------------------------------------------
# sequence ops (reference: src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------

def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    jnp = _jnp()
    if not use_sequence_length or sequence_length is None:
        return data if isinstance(data, NDArray) else NDArray(data)

    def f(x, ln):
        steps = jnp.arange(x.shape[axis])
        batch_axis = 1 - axis  # sequence ops are (T, N, ...) or (N, T, ...)
        shape = [1] * x.ndim
        shape[axis] = -1
        steps = steps.reshape(shape)
        lshape = [1] * x.ndim
        lshape[batch_axis] = -1
        mask = steps < ln.reshape(lshape)
        return jnp.where(mask, x, value)

    return apply_op("sequence_mask", f, (data, sequence_length))


def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()

    def f(x, ln):
        if ln is None:
            return jnp.take(x, -1, axis=axis)
        idx = (ln - 1).astype(jnp.int32)
        return jnp.take_along_axis(
            x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)) if axis == 0
            else idx.reshape((-1, 1) + (1,) * (x.ndim - 2)),
            axis=axis).squeeze(axis)

    ln = sequence_length if use_sequence_length else None
    return apply_op("sequence_last", f, (data, ln))


def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    jnp = _jnp()

    def f(x, ln):
        if ln is None:
            return jnp.flip(x, axis=axis)
        T = x.shape[axis]
        steps = jnp.arange(T)
        ln_i = ln.astype(jnp.int32)
        # reversed index within each valid prefix, identity beyond
        rev = jnp.where(steps[None, :] < ln_i[:, None],
                        ln_i[:, None] - 1 - steps[None, :], steps[None, :])
        # data is (T, N, ...): gather along time per batch
        xm = jnp.moveaxis(x, axis, 0)
        out = jnp.take_along_axis(
            xm, jnp.moveaxis(rev, -1, 0).reshape((T, -1) + (1,) * (xm.ndim - 2)),
            axis=0)
        return jnp.moveaxis(out, 0, axis)

    ln = sequence_length if use_sequence_length else None
    return apply_op("sequence_reverse", f, (data, ln))


# ---------------------------------------------------------------------------
# fused RNN (reference: src/operator/rnn.cc:296 — LSTM/GRU/vanilla over a
# packed parameter vector). TPU design: lax.scan over time, weights unpacked
# from the flat vector with cuDNN-compatible gate order (LSTM: i f g o,
# GRU: r z n), so checkpoints trained on the reference load bit-compatibly.
# ---------------------------------------------------------------------------

def _rnn_gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def _unpack_rnn_params(params, mode, num_layers, input_size, state_size, bidirectional,
                       projection_size=None):  # noqa: ARG001
    jnp = _jnp()
    ngates = _rnn_gates(mode)
    dirs = 2 if bidirectional else 1
    layers = []
    pos = 0
    for layer in range(num_layers):
        lsize = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            w_i2h = _lax().dynamic_slice(params, (pos,), (ngates * state_size * lsize,)) \
                .reshape(ngates * state_size, lsize)
            pos += ngates * state_size * lsize
            w_h2h = _lax().dynamic_slice(params, (pos,), (ngates * state_size * state_size,)) \
                .reshape(ngates * state_size, state_size)
            pos += ngates * state_size * state_size
            layers.append([w_i2h, w_h2h, None, None])
    idx = 0
    for layer in range(num_layers):
        for _ in range(dirs):
            b_i2h = _lax().dynamic_slice(params, (pos,), (ngates * state_size,))
            pos += ngates * state_size
            b_h2h = _lax().dynamic_slice(params, (pos,), (ngates * state_size,))
            pos += ngates * state_size
            layers[idx][2] = b_i2h
            layers[idx][3] = b_h2h
            idx += 1
    del jnp
    return layers


def rnn_param_size(mode, num_layers, input_size, state_size, bidirectional=False):
    ngates = _rnn_gates(mode)
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        lsize = input_size if layer == 0 else state_size * dirs
        total += dirs * ngates * state_size * (lsize + state_size + 2)
    return total


def _cell_step(mode, x_t, h, c, w_i2h, w_h2h, b_i2h, b_h2h):
    import jax

    jnp = _jnp()
    gates = x_t @ w_i2h.T + b_i2h + h @ w_h2h.T + b_h2h
    H = h.shape[-1]
    if mode == "lstm":
        i, f, g, o = (gates[..., :H], gates[..., H:2 * H], gates[..., 2 * H:3 * H],
                      gates[..., 3 * H:])
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    if mode == "gru":
        # cuDNN-style gru: r, z from combined; n uses r * (h W_hn + b_hn)
        xr, xz, xn = jnp.split(x_t @ w_i2h.T + b_i2h, 3, axis=-1)
        hr, hz, hn = jnp.split(h @ w_h2h.T + b_h2h, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, c
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))
    h_new = act(gates)
    return h_new, c


def rnn(data=None, parameters=None, state=None, state_cell=None, mode="lstm",
        state_size=None, num_layers=1, bidirectional=False, p=0.0,
        state_outputs=False, projection_size=None, lstm_state_clip_min=None,
        lstm_state_clip_max=None, sequence_length=None, use_sequence_length=False,
        **kwargs):  # noqa: ARG001
    """Fused multi-layer RNN over time-major input (T, N, C)."""
    import jax

    jnp = _jnp()
    lax = _lax()
    dirs = 2 if bidirectional else 1
    input_size = data.shape[-1]

    dropout_keys = [next_key() for _ in range(max(0, num_layers - 1))] if p > 0 else []

    def f(x, params, h0, c0):
        layers = _unpack_rnn_params(params, mode, num_layers, input_size,
                                    state_size, bidirectional)
        out = x
        h_finals, c_finals = [], []
        for layer in range(num_layers):
            layer_outs = []
            for d in range(dirs):
                li = layer * dirs + d
                w_i2h, w_h2h, b_i2h, b_h2h = layers[li]
                h_init = h0[li]
                c_init = c0[li] if c0 is not None else jnp.zeros_like(h_init)
                seq = out if d == 0 else jnp.flip(out, axis=0)

                def step(carry, x_t, _w_i2h=w_i2h, _w_h2h=w_h2h, _b_i2h=b_i2h,
                         _b_h2h=b_h2h):
                    h, c = carry
                    h2, c2 = _cell_step(mode, x_t, h, c, _w_i2h, _w_h2h, _b_i2h,
                                        _b_h2h)
                    if mode == "lstm" and lstm_state_clip_min is not None:
                        c2 = jnp.clip(c2, lstm_state_clip_min, lstm_state_clip_max)
                    return (h2, c2), h2

                (h_f, c_f), ys = lax.scan(step, (h_init, c_init), seq)
                if d == 1:
                    ys = jnp.flip(ys, axis=0)
                layer_outs.append(ys)
                h_finals.append(h_f)
                c_finals.append(c_f)
            out = layer_outs[0] if dirs == 1 else jnp.concatenate(layer_outs, axis=-1)
            if p > 0 and layer < num_layers - 1:
                keep = jax.random.bernoulli(dropout_keys[layer], 1.0 - p, out.shape) \
                    if autograd.is_training() else None
                if keep is not None:
                    out = jnp.where(keep, out / (1.0 - p), 0.0)
        h_out = jnp.stack(h_finals, axis=0)
        if mode == "lstm":
            c_out = jnp.stack(c_finals, axis=0)
            return out, h_out, c_out
        return out, h_out

    n_outputs = 3 if mode == "lstm" else 2
    outs = apply_op("rnn", f, (data, parameters, state, state_cell),
                    n_outputs=n_outputs)
    if state_outputs:
        return outs
    return outs[0]


# ---------------------------------------------------------------------------
# scalar special functions
# ---------------------------------------------------------------------------

def erf(data):
    import jax

    return apply_op("erf", jax.scipy.special.erf, (data,))


def erfinv(data):
    import jax

    return apply_op("erfinv", jax.scipy.special.erfinv, (data,))


def gamma(data):
    import jax

    return apply_op("gamma", lambda x: _jnp().exp(jax.scipy.special.gammaln(x)), (data,))


def gammaln(data):
    import jax

    return apply_op("gammaln", jax.scipy.special.gammaln, (data,))


def digamma(data):
    import jax

    return apply_op("digamma", jax.scipy.special.digamma, (data,))


def smooth_l1(data, scalar=1.0):
    jnp = _jnp()
    s2 = scalar * scalar

    def f(x):
        return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                         jnp.abs(x) - 0.5 / s2)

    return apply_op("smooth_l1", f, (data,))


# ---------------------------------------------------------------------------
# shape utilities
# ---------------------------------------------------------------------------

def cast(data, dtype):
    return data.astype(dtype)


def reshape(data, newshape, reverse=False, **kwargs):  # noqa: ARG001
    """npx.reshape with MXNet magic codes (-2 copy rest, -3 merge two,
    -4 split, -5 merge all remaining, -6 split into two)."""
    shape = list(newshape) if isinstance(newshape, (list, tuple)) else [newshape]
    in_shape = list(data.shape)
    if all(isinstance(s, int) and s >= -1 for s in shape):
        # handle 0 = copy input dim (MXNet legacy reshape semantic)
        out = [in_shape[i] if s == 0 and i < len(in_shape) else s
               for i, s in enumerate(shape)]
        return data.reshape(tuple(out))
    out = []
    i = 0
    it = iter(range(len(shape)))
    for si in it:
        s = shape[si]
        if s == -2:
            out.extend(in_shape[i:])
            i = len(in_shape)
        elif s == -3:
            out.append(in_shape[i] * in_shape[i + 1])
            i += 2
        elif s == -5:
            prod = 1
            for d in in_shape[i:]:
                prod *= d
            out.append(prod)
            i = len(in_shape)
        elif s == -4:
            d1 = shape[si + 1]
            d2 = shape[si + 2]
            next(it)
            next(it)
            if d1 == -1:
                d1 = in_shape[i] // d2
            if d2 == -1:
                d2 = in_shape[i] // d1
            out.extend([d1, d2])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == 0:
            out.append(in_shape[i])
            i += 1
        else:
            out.append(s)
            i += 1
    return data.reshape(tuple(out))


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):  # noqa: ARG001
    jnp = _jnp()
    if axis is None:
        n = data.size
        return NDArray(jnp.arange(start, start + step * n, step,
                                  dtype=data._data.dtype).reshape(data.shape))
    n = data.shape[axis]
    return NDArray(jnp.arange(start, start + step * n, step, dtype=data._data.dtype))


def shape_array(data):
    jnp = _jnp()
    return NDArray(jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32))


def stop_gradient(data):
    return data.detach()


# ---------------------------------------------------------------------------
# control flow (reference: src/operator/control_flow.cc — foreach/_while_loop/
# _cond as stateful sub-graph ops). TPU-native: in eager mode these run as
# Python loops (tape-friendly); under a jit trace (hybridized block) they
# lower to lax.scan / lax.while_loop / lax.cond so the compiled program
# contains real XLA loop constructs instead of a fully unrolled graph.
# ---------------------------------------------------------------------------

def _is_tracer(x):
    import jax

    from ..ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _any_traced(*vals):
    for v in vals:
        if isinstance(v, (list, tuple)):
            if any(_is_tracer(x) for x in v):
                return True
        elif _is_tracer(v):
            return True
    return False


def foreach(body, data, init_states):
    """Run body over axis-0 slices, threading states
    (reference: control_flow.cc foreach ≈ lax.scan; lowers to a real
    lax.scan when traced)."""
    from ..ndarray.ndarray import NDArray

    multi_data = isinstance(data, (list, tuple))
    multi_state = isinstance(init_states, (list, tuple))
    states = list(init_states) if multi_state else [init_states]

    if _any_traced(data, init_states):
        import jax.lax as lax

        xs = ([d._data for d in data] if multi_data else data._data)

        def scan_body(carry, x):
            st = [NDArray(c) for c in carry]
            xi = ([NDArray(v) for v in x] if multi_data else NDArray(x))
            out, new_st = body(xi, st if multi_state else st[0])
            new_st = (list(new_st) if isinstance(new_st, (list, tuple))
                      else [new_st])
            if isinstance(out, (list, tuple)):
                out_vals = tuple(o._data for o in out)
            else:
                out_vals = out._data
            return tuple(s._data for s in new_st), out_vals

        carry0 = tuple(s._data for s in states)
        carry, ys = lax.scan(scan_body, carry0, xs)
        stacked = ([NDArray(y) for y in ys] if isinstance(ys, tuple)
                   else NDArray(ys))
        final = [NDArray(c) for c in carry]
        return stacked, (final if multi_state else final[0])

    outputs = []
    n = data[0].shape[0] if multi_data else data.shape[0]
    for i in range(n):
        x_i = [d[i] for d in data] if multi_data else data[i]
        out, states = body(x_i, states if multi_state else states[0])
        states = (list(states) if isinstance(states, (list, tuple))
                  else [states])
        outputs.append(out)
    from .. import numpy as np_mod

    if outputs and isinstance(outputs[0], (list, tuple)):
        stacked = [np_mod.stack([o[j] for o in outputs])
                   for j in range(len(outputs[0]))]
    else:
        stacked = np_mod.stack(outputs)
    return stacked, (states if multi_state else states[0])


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Loop func while cond holds (reference: control_flow.cc _while_loop).
    Traced: lowers to lax.while_loop; per the reference contract, the
    stacked per-step outputs require `max_iterations` (the output buffer is
    preallocated to that length, tail zeros).

    `cond` and `func` must be PURE (the reference builds them into
    sub-graphs, src/operator/control_flow.cc): with `max_iterations` set,
    `func` may be invoked once as a shape probe even when the loop runs
    zero iterations, so its output shape can match the traced path's
    preallocated buffers."""
    from ..ndarray.ndarray import NDArray

    loop_vars = list(loop_vars)
    if _any_traced(loop_vars):
        import jax
        import jax.lax as lax
        import jax.numpy as jnp

        vals0 = tuple(v._data for v in loop_vars)

        # probe func's output structure with abstract eval
        def _func_flat(*vals):
            out, new_vars = func(*[NDArray(v) for v in vals])
            new_vals = tuple(v._data for v in new_vars)
            if out is None:
                return None, new_vals
            out_vals = (tuple(o._data for o in out)
                        if isinstance(out, (list, tuple)) else out._data)
            return out_vals, new_vals

        out_shape, _ = jax.eval_shape(_func_flat, *vals0)
        has_out = out_shape is not None
        if has_out and max_iterations is None:
            raise ValueError("while_loop with per-step outputs requires "
                             "max_iterations under jit (static buffer size)")

        def cond_fn(carry):
            step, vals, _ = carry
            c = cond(*[NDArray(v) for v in vals])
            c = c._data if isinstance(c, NDArray) else c
            c = jnp.squeeze(c).astype(bool)
            if max_iterations is not None:
                c = jnp.logical_and(c, step < max_iterations)
            return c

        def body_fn(carry):
            step, vals, bufs = carry
            out_vals, new_vals = _func_flat(*vals)
            if has_out:
                if not isinstance(out_vals, tuple):
                    out_vals = (out_vals,)
                bufs = tuple(
                    lax.dynamic_update_index_in_dim(b, o, step, 0)
                    for b, o in zip(bufs, out_vals))
            return step + 1, new_vals, bufs

        if has_out:
            outs = (out_shape if isinstance(out_shape, tuple)
                    else (out_shape,))
            bufs0 = tuple(jnp.zeros((max_iterations,) + o.shape, o.dtype)
                          for o in outs)
        else:
            bufs0 = ()
        steps, vals, bufs = lax.while_loop(
            cond_fn, body_fn, (jnp.asarray(0, jnp.int32), vals0, bufs0))
        new_loop_vars = [NDArray(v) for v in vals]
        if not has_out:
            return None, new_loop_vars
        stacked = [NDArray(b) for b in bufs]
        if not isinstance(out_shape, tuple):
            stacked = stacked[0]
        return stacked, new_loop_vars

    steps = 0
    outputs = []
    while bool(cond(*loop_vars)):
        if max_iterations is not None and steps >= max_iterations:
            break
        out, loop_vars = func(*loop_vars)
        if out is not None:
            outputs.append(out)
        steps += 1
    from .. import numpy as np_mod

    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray as _ND

    if not outputs:
        if max_iterations is None:
            return None, loop_vars
        # zero iterations but a padded-output contract: probe func (pure by
        # the reference contract) for the per-step output structure so the
        # eager result matches the traced path's zero-filled buffers
        probe_out, _ = func(*loop_vars)
        if probe_out is None:
            return None, loop_vars
        outs = (probe_out if isinstance(probe_out, (list, tuple))
                else [probe_out])
        zeros = [_ND(jnp.zeros((max_iterations,) + tuple(o.shape),
                               o._data.dtype)) for o in outs]
        if isinstance(probe_out, (list, tuple)):
            return zeros, loop_vars
        return zeros[0], loop_vars
    stacked = np_mod.stack(outputs)
    if max_iterations is not None and len(outputs) < max_iterations:
        # pad to max_iterations so eager and traced (lax.while_loop with a
        # preallocated buffer) agree on the output shape — the reference
        # contract: outputs have length max_iterations, tail zeros
        pad_n = max_iterations - len(outputs)
        pad_shape = (pad_n,) + tuple(stacked.shape[1:])
        stacked = np_mod.concatenate(
            [stacked, _ND(jnp.zeros(pad_shape, stacked._data.dtype))])
    return stacked, loop_vars


def cond(pred, then_func, else_func):
    """Conditional (reference: control_flow.cc _cond). Traced: lax.cond."""
    from ..ndarray.ndarray import NDArray

    if _is_tracer(pred):
        import jax.lax as lax
        import jax.numpy as jnp
        import jax.tree_util as jtu

        is_leaf = lambda x: isinstance(x, NDArray)  # noqa: E731
        cell = {}  # captures the output treedef while lax.cond traces

        def leaf_val(o):
            return o._data if isinstance(o, NDArray) else jnp.asarray(o)

        def then_branch(_):
            flat, tree = jtu.tree_flatten(then_func(), is_leaf=is_leaf)
            cell["tree"] = tree
            return tuple(leaf_val(o) for o in flat)

        def else_branch(_):
            flat, _ = jtu.tree_flatten(else_func(), is_leaf=is_leaf)
            return tuple(leaf_val(o) for o in flat)

        p = pred._data if isinstance(pred, NDArray) else pred
        p = jnp.squeeze(p).astype(bool)
        vals = lax.cond(p, then_branch, else_branch, None)
        return jtu.tree_unflatten(cell["tree"], [NDArray(v) for v in vals])

    return then_func() if bool(pred) else else_func()


# ---------------------------------------------------------------------------
# misc module-level utilities
# ---------------------------------------------------------------------------

def boolean_mask(data, index, axis=0):
    """Select rows where index != 0 (reference:
    `src/operator/contrib/boolean_mask.cc` _contrib_boolean_mask — it has a
    backward, so this must too).

    Output shape is data-dependent → the mask is resolved eagerly (like the
    reference's dynamic-shape NaiveRunGraph fallback, SURVEY §7 hard parts),
    then the selection itself is a static gather through the funnel, so
    gradients scatter back into the kept rows. Under jit use
    `np.where`-style masking instead."""
    import numpy as onp

    from ..ndarray.ndarray import NDArray, apply_op_flat

    m = index._data if isinstance(index, NDArray) else index
    m = onp.asarray(m)
    data = data if isinstance(data, NDArray) else NDArray(data)
    if m.shape[0] != data.shape[axis]:
        raise ValueError(
            f"boolean_mask: mask length {m.shape[0]} != data.shape[{axis}] "
            f"= {data.shape[axis]}")
    keep = onp.flatnonzero(m)  # host sync: dynamic shape

    def fn(x):
        import jax.numpy as jnp

        return jnp.take(x, jnp.asarray(keep), axis=axis)

    return apply_op_flat("boolean_mask", fn, (data,))


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays in-place so their global L2 norm ≤ max_norm
    (reference: gluon/utils.py clip_global_norm)."""
    jnp = _jnp()
    total = sum(float(jnp.sum(a._data.astype(jnp.float32) ** 2)) for a in arrays)
    total_norm = math.sqrt(total)
    if check_isfinite and not math.isfinite(total_norm):
        raise ValueError("global norm is not finite")
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._set_data(a._data * scale)
    return total_norm


def set_np(shape=True, array=True, dtype=False):  # noqa: ARG001
    """No-op for parity: this framework is numpy-semantics-native."""
    return True


def reset_np():
    return True


def is_np_array():
    return True


def is_np_shape():
    return True


def waitall():
    from ..ndarray.ndarray import waitall as _w

    _w()


def seed(s):
    from ..random import seed as _s

    _s(s)


def load(fname):
    from ..ndarray import load as _load

    return _load(fname)


def save(fname, data):
    from ..ndarray import save as _save

    return _save(fname, data)
