"""`npx.image` op namespace (reference: `src/operator/image/` registered
image ops — to_tensor/normalize/resize/crop/flips — the ops gluon's
vision transforms call, `python/mxnet/gluon/data/vision/transforms/
image.py:86,140,314`).

TPU-native: thin autograd-aware jnp bodies through the funnel; resize
uses `jax.image.resize` (bilinear) instead of OpenCV so it is jit-safe
and differentiable. The imperative augmenter classes stay in
`incubator_mxnet_tpu.image` and remain re-exported for back-compat."""
from __future__ import annotations

from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["to_tensor", "normalize", "resize", "crop", "flip_left_right",
           "flip_top_bottom", "random_flip_left_right",
           "random_flip_top_bottom"]
# NOTE: random_crop / random_size_crop stay the IMPERATIVE helpers
# (`incubator_mxnet_tpu.image`) in the merged npx.image namespace — their
# (src, size) signature predates this module and shadowing it with the
# reference op's (data, xrange, ...) form silently mis-parsed old calls.


def _jnp():
    import jax.numpy as jnp

    return jnp


def to_tensor(data):
    """(H, W, C) [or (N, H, W, C)] uint8 → (C, H, W) float32 in [0, 1]."""
    jnp = _jnp()

    def f(x):
        y = x.astype(jnp.float32) / 255.0
        axes = (2, 0, 1) if y.ndim == 3 else (0, 3, 1, 2)
        return jnp.transpose(y, axes)

    return apply_op("image_to_tensor", f, (data,))


def normalize(data, mean=0.0, std=1.0):
    """Channel-wise (x - mean) / std on (C, H, W) [or (N, C, H, W)]."""
    jnp = _jnp()

    def f(x):
        nch = x.shape[0] if x.ndim == 3 else x.shape[1]
        m = jnp.broadcast_to(jnp.asarray(mean, jnp.float32), (nch,))
        s = jnp.broadcast_to(jnp.asarray(std, jnp.float32), (nch,))
        shape = (-1, 1, 1) if x.ndim == 3 else (1, -1, 1, 1)
        return (x - m.reshape(shape)) / s.reshape(shape)

    return apply_op("image_normalize", f, (data,))


def resize(data, size, keep_ratio=False, interp=1):  # noqa: ARG001
    """Resize (H, W, C) or batched (N, H, W, C) to `size` — int (short
    edge when keep_ratio, else square) or (w, h) tuple (the reference's
    cv2 convention)."""
    import jax

    jnp = _jnp()
    batched = data.ndim == 4
    h_ax = 1 if batched else 0
    h, w = int(data.shape[h_ax]), int(data.shape[h_ax + 1])
    if isinstance(size, int):
        if keep_ratio:
            if h < w:
                new_h, new_w = size, max(1, round(w * size / h))
            else:
                new_h, new_w = max(1, round(h * size / w)), size
        else:
            new_h = new_w = size
    else:
        new_w, new_h = int(size[0]), int(size[1])

    def f(x):
        shape = ((x.shape[0], new_h, new_w) + tuple(x.shape[3:])) \
            if batched else ((new_h, new_w) + tuple(x.shape[2:]))
        y = jax.image.resize(x.astype(jnp.float32), shape,
                             method="bilinear")
        return jnp.clip(jnp.rint(y), 0, 255).astype(x.dtype) \
            if jnp.issubdtype(x.dtype, jnp.integer) else y.astype(x.dtype)

    return apply_op("image_resize", f, (data,))


def crop(data, x, y, width, height):
    """Fixed crop at (x, y) of size (width, height) — (H, W, C) layout."""
    def f(im):
        return im[y:y + height, x:x + width]

    return apply_op("image_crop", f, (data,))


def flip_left_right(data):
    return apply_op("image_flip_lr", lambda x: x[:, ::-1], (data,))


def flip_top_bottom(data):
    return apply_op("image_flip_tb", lambda x: x[::-1], (data,))


def random_flip_left_right(data, p=0.5):
    import numpy as onp

    from .. import random as mxrandom

    del mxrandom  # host-side coin matches the reference's eager augmenters
    return flip_left_right(data) if onp.random.uniform() < p else \
        (data if isinstance(data, NDArray) else NDArray(data))


def random_flip_top_bottom(data, p=0.5):
    import numpy as onp

    return flip_top_bottom(data) if onp.random.uniform() < p else \
        (data if isinstance(data, NDArray) else NDArray(data))


