"""`npx.image` op namespace (reference: `src/operator/image/` registered
image ops — to_tensor/normalize/resize/crop/flips — the ops gluon's
vision transforms call, `python/mxnet/gluon/data/vision/transforms/
image.py:86,140,314`).

TPU-native: thin autograd-aware jnp bodies through the funnel; resize
uses `jax.image.resize` (bilinear) instead of OpenCV so it is jit-safe
and differentiable. The imperative augmenter classes stay in
`incubator_mxnet_tpu.image` and remain re-exported for back-compat."""
from __future__ import annotations

from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["to_tensor", "normalize", "resize", "crop", "flip_left_right",
           "flip_top_bottom", "random_flip_left_right",
           "random_flip_top_bottom"]
# NOTE: random_crop / random_size_crop stay the IMPERATIVE helpers
# (`incubator_mxnet_tpu.image`) in the merged npx.image namespace — their
# (src, size) signature predates this module and shadowing it with the
# reference op's (data, xrange, ...) form silently mis-parsed old calls.


def _jnp():
    import jax.numpy as jnp

    return jnp


def to_tensor(data):
    """(H, W, C) [or (N, H, W, C)] uint8 → (C, H, W) float32 in [0, 1]."""
    jnp = _jnp()

    def f(x):
        y = x.astype(jnp.float32) / 255.0
        axes = (2, 0, 1) if y.ndim == 3 else (0, 3, 1, 2)
        return jnp.transpose(y, axes)

    return apply_op("image_to_tensor", f, (data,))


def normalize(data, mean=0.0, std=1.0):
    """Channel-wise (x - mean) / std on (C, H, W) [or (N, C, H, W)]."""
    jnp = _jnp()

    def f(x):
        nch = x.shape[0] if x.ndim == 3 else x.shape[1]
        m = jnp.broadcast_to(jnp.asarray(mean, jnp.float32), (nch,))
        s = jnp.broadcast_to(jnp.asarray(std, jnp.float32), (nch,))
        shape = (-1, 1, 1) if x.ndim == 3 else (1, -1, 1, 1)
        return (x - m.reshape(shape)) / s.reshape(shape)

    return apply_op("image_normalize", f, (data,))


def resize(data, size, keep_ratio=False, interp=1):  # noqa: ARG001
    """Resize (H, W, C) or batched (N, H, W, C) to `size` — int (short
    edge when keep_ratio, else square) or (w, h) tuple (the reference's
    cv2 convention)."""
    import jax

    jnp = _jnp()
    batched = data.ndim == 4
    h_ax = 1 if batched else 0
    h, w = int(data.shape[h_ax]), int(data.shape[h_ax + 1])
    if isinstance(size, int):
        if keep_ratio:
            if h < w:
                new_h, new_w = size, max(1, round(w * size / h))
            else:
                new_h, new_w = max(1, round(h * size / w)), size
        else:
            new_h = new_w = size
    else:
        new_w, new_h = int(size[0]), int(size[1])

    def f(x):
        shape = ((x.shape[0], new_h, new_w) + tuple(x.shape[3:])) \
            if batched else ((new_h, new_w) + tuple(x.shape[2:]))
        y = jax.image.resize(x.astype(jnp.float32), shape,
                             method="bilinear")
        return jnp.clip(jnp.rint(y), 0, 255).astype(x.dtype) \
            if jnp.issubdtype(x.dtype, jnp.integer) else y.astype(x.dtype)

    return apply_op("image_resize", f, (data,))


def crop(data, x, y, width, height):
    """Fixed crop at (x, y) of size (width, height) — (H, W, C) layout."""
    def f(im):
        return im[y:y + height, x:x + width]

    return apply_op("image_crop", f, (data,))


def flip_left_right(data):
    return apply_op("image_flip_lr", lambda x: x[:, ::-1], (data,))


def flip_top_bottom(data):
    return apply_op("image_flip_tb", lambda x: x[::-1], (data,))


def random_flip_left_right(data, p=0.5):
    import numpy as onp

    from .. import random as mxrandom

    del mxrandom  # host-side coin matches the reference's eager augmenters
    return flip_left_right(data) if onp.random.uniform() < p else \
        (data if isinstance(data, NDArray) else NDArray(data))


def random_flip_top_bottom(data, p=0.5):
    import numpy as onp

    return flip_top_bottom(data) if onp.random.uniform() < p else \
        (data if isinstance(data, NDArray) else NDArray(data))




def _uniform_factor(lo, hi):
    import numpy as onp

    return float(onp.random.uniform(lo, hi))


def random_brightness(data, min_factor, max_factor):
    """Scale pixel values by U(min,max) (reference
    `src/operator/image/image_random.cc` RandomBrightness)."""
    f = _uniform_factor(min_factor, max_factor)
    return apply_op("image_random_brightness", lambda x: x * f, (data,),
                    static_info=("f", f))


def random_contrast(data, min_factor, max_factor):
    """Blend with the mean gray value (reference RandomContrast)."""
    f = _uniform_factor(min_factor, max_factor)

    def fn(x):
        jnp = _jnp()
        coef = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
        gray = (x * coef).sum(axis=-1, keepdims=True).mean()
        return f * x + (1.0 - f) * gray

    return apply_op("image_random_contrast", fn, (data,),
                    static_info=("f", f))


def random_saturation(data, min_factor, max_factor):
    """Blend with the per-pixel gray image (reference
    RandomSaturation)."""
    f = _uniform_factor(min_factor, max_factor)

    def fn(x):
        jnp = _jnp()
        coef = jnp.asarray([0.299, 0.587, 0.114], x.dtype)
        gray = (x * coef).sum(axis=-1, keepdims=True)
        return f * x + (1.0 - f) * gray

    return apply_op("image_random_saturation", fn, (data,),
                    static_info=("f", f))


def random_hue(data, min_factor, max_factor):
    """Rotate hue via the YIQ linear approximation the reference kernel
    uses (image_random-inl.h RandomHue)."""
    import math

    f = _uniform_factor(min_factor, max_factor)
    alpha = math.pi * f

    def fn(x):
        jnp = _jnp()
        u, w = math.cos(alpha), math.sin(alpha)
        t_yiq = jnp.asarray([[0.299, 0.587, 0.114],
                             [0.596, -0.274, -0.321],
                             [0.211, -0.523, 0.311]], x.dtype)
        t_rgb = jnp.asarray([[1.0, 0.956, 0.621],
                             [1.0, -0.272, -0.647],
                             [1.0, -1.107, 1.705]], x.dtype)
        rot = jnp.asarray([[1.0, 0.0, 0.0],
                           [0.0, u, -w],
                           [0.0, w, u]], x.dtype)
        m = t_rgb @ rot @ t_yiq
        return x @ m.T

    return apply_op("image_random_hue", fn, (data,),
                    static_info=("f", f))


def random_color_jitter(data, brightness=0.0, contrast=0.0,
                        saturation=0.0, hue=0.0):
    """Brightness/contrast/saturation/hue jitter in random order
    (reference RandomColorJitter)."""
    import numpy as onp

    augs = []
    if brightness > 0:
        augs.append(lambda d: random_brightness(
            d, 1 - brightness, 1 + brightness))
    if contrast > 0:
        augs.append(lambda d: random_contrast(d, 1 - contrast,
                                              1 + contrast))
    if saturation > 0:
        augs.append(lambda d: random_saturation(d, 1 - saturation,
                                                1 + saturation))
    if hue > 0:
        augs.append(lambda d: random_hue(d, -hue, hue))
    for i in onp.random.permutation(len(augs)):
        data = augs[int(i)](data)
    return data


def adjust_lighting(data, alpha):
    """AlexNet-style PCA lighting shift (reference AdjustLighting):
    alpha (3,) weights on the fixed RGB eigenbasis."""
    def fn(x, al):
        jnp = _jnp()
        eigval = jnp.asarray([55.46, 4.794, 1.148], x.dtype)
        eigvec = jnp.asarray([[-0.5675, 0.7192, 0.4009],
                              [-0.5808, -0.0045, -0.8140],
                              [-0.5836, -0.6948, 0.4203]], x.dtype)
        shift = (eigvec * (al * eigval)).sum(axis=1)
        return x + shift

    return apply_op("image_adjust_lighting", fn, (data, alpha))


def random_lighting(data, alpha_std=0.05):
    """adjust_lighting with alpha ~ N(0, alpha_std) (reference
    RandomLighting)."""
    import numpy as onp

    al = NDArray(_jnp().asarray(
        onp.random.normal(0.0, alpha_std, 3).astype("float32")))
    return adjust_lighting(data, al)


def random_resized_crop(data, size, scale=(0.08, 1.0),
                        ratio=(3 / 4, 4 / 3), interp=1):
    """Random area+aspect crop then resize (reference
    `_image_random_resized_crop` / gluon RandomResizedCrop semantics)."""
    import math

    import numpy as onp

    h, w = data.shape[0], data.shape[1]
    area = h * w
    out_w, out_h = (size, size) if isinstance(size, int) else size
    for _ in range(10):
        target = onp.random.uniform(*scale) * area
        log_r = onp.random.uniform(math.log(ratio[0]),
                                   math.log(ratio[1]))
        ar = math.exp(log_r)
        cw = int(round(math.sqrt(target * ar)))
        ch = int(round(math.sqrt(target / ar)))
        if cw <= w and ch <= h:
            x0 = onp.random.randint(0, w - cw + 1)
            y0 = onp.random.randint(0, h - ch + 1)
            patch = crop(data, x0, y0, cw, ch)
            return resize(patch, (out_w, out_h), interp=interp)
    # fallback: center crop at the valid aspect closest to requested
    cw, ch = min(w, h * ratio[1]), min(h, w / ratio[0])
    cw, ch = int(cw), int(ch)
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return resize(crop(data, x0, y0, cw, ch), (out_w, out_h),
                  interp=interp)


__all__ += ["random_brightness", "random_contrast", "random_saturation",
            "random_hue", "random_color_jitter", "adjust_lighting",
            "random_lighting", "random_resized_crop"]
