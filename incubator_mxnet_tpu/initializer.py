"""Weight initializers (reference: `python/mxnet/initializer.py`).

Same registry + `InitDesc`-style dispatch as the reference: parameter names
ending in specific suffixes get conventional defaults (bias→zero, gamma→one,
running_mean→zero, running_var→one) unless the initializer overrides.
"""
from __future__ import annotations

import math
import re

import numpy as onp

__all__ = [
    "Initializer", "Zero", "One", "Constant", "Uniform", "Normal", "Orthogonal",
    "Xavier", "MSRAPrelu", "Bilinear", "LSTMBias", "register", "create",
]

_REGISTRY: dict = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    if name is None:
        return Uniform()
    if callable(name) and not isinstance(name, type):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"unknown initializer {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key](**kwargs)


class Initializer:
    """Base initializer. Call with (name, NDArray) to fill in place."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr):
        self.init_weight(name, arr)

    def init_weight(self, name, arr):
        name = name or ""
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    # -- default fills ------------------------------------------------------
    def _init_zero(self, arr):
        import jax.numpy as jnp

        arr._set_data(jnp.zeros(arr.shape, arr._data.dtype))

    def _init_one(self, arr):
        import jax.numpy as jnp

        arr._set_data(jnp.ones(arr.shape, arr._data.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


Zeros = Zero
_REGISTRY["zeros"] = Zero


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


Ones = One
_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        v = self.value
        if hasattr(v, "asnumpy"):
            v = v.asnumpy()
        arr._set_data(jnp.broadcast_to(jnp.asarray(v, arr._data.dtype),
                                       arr.shape).copy())


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        import jax.random as jr

        from .random import next_key

        arr._set_data(jr.uniform(next_key(), arr.shape, arr._data.dtype,
                                 -self.scale, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        import jax.random as jr

        from .random import next_key

        arr._set_data(jr.normal(next_key(), arr.shape, arr._data.dtype) * self.sigma)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        import jax.numpy as jnp
        import jax.random as jr

        from .random import next_key

        nout = arr.shape[0]
        nin = int(onp.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = jr.uniform(next_key(), (nout, nin), minval=-1.0, maxval=1.0)
        else:
            tmp = jr.normal(next_key(), (nout, nin))
        u, _, v = jnp.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        arr._set_data((self.scale * q).reshape(arr.shape).astype(arr._data.dtype))


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        import jax.random as jr

        from .random import next_key

        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(f"Xavier requires ndim >= 2, got shape {shape} for {name}")
        if len(shape) > 2:
            hw_scale = float(onp.prod(shape[2:]))
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr._set_data(jr.uniform(next_key(), shape, arr._data.dtype,
                                     -scale, scale))
        else:
            arr._set_data(jr.normal(next_key(), shape, arr._data.dtype) * scale)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        shape = arr.shape
        weight = onp.zeros(int(onp.prod(shape)), dtype="float32")
        f = onp.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data(jnp.asarray(weight.reshape(shape), arr._data.dtype))


@register
class LSTMBias(Initializer):
    """Initialize LSTM biases with forget-gate bias = forget_bias."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        b = jnp.zeros(arr.shape, arr._data.dtype)
        num_hidden = arr.shape[0] // 4
        b = b.at[num_hidden:2 * num_hidden].set(self.forget_bias)
        arr._set_data(b)


class InitDesc(str):
    """Parameter-name descriptor carrying init attrs (reference parity)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


_NAME_RE = re.compile(r".*")
