"""Text embeddings and vocabulary (reference:
`python/mxnet/contrib/text/`)."""
from . import embedding, utils, vocab  # noqa: F401
from .vocab import Vocabulary  # noqa: F401
