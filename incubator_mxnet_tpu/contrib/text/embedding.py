"""Token embeddings (reference: `python/mxnet/contrib/text/embedding.py` —
`_TokenEmbedding` over `Vocabulary`, GloVe/FastText loaders, custom and
composite embeddings, registry with `register`/`create`).

TPU-hosts run with zero egress, so the download path of the reference
(`embedding.py:190 _get_pretrained_file`) becomes a local-file contract:
`GloVe`/`FastText` read `pretrained_file_path` from disk (same text format:
one token followed by elem_delim-separated floats per line) and raise a
clear error when the file is absent instead of downloading."""
from __future__ import annotations

import io
import os

import numpy as onp

from ...ndarray.ndarray import NDArray
from . import vocab as _vocab

__all__ = ["register", "create", "get_pretrained_file_names",
           "TokenEmbedding", "GloVe", "FastText", "CustomEmbedding",
           "CompositeEmbedding"]

_REGISTRY: dict = {}


def register(embedding_cls):
    """Register an embedding class by lowercase name (`embedding.py:40`)."""
    _REGISTRY[embedding_cls.__name__.lower()] = embedding_cls
    return embedding_cls


def create(embedding_name, **kwargs):
    """Instantiate a registered embedding (`embedding.py:63`)."""
    cls = _REGISTRY.get(embedding_name.lower())
    if cls is None:
        raise KeyError(f"unknown embedding {embedding_name!r}; "
                       f"registered: {sorted(_REGISTRY)}")
    return cls(**kwargs)


def get_pretrained_file_names(embedding_name=None):
    """Known pretrained file names (`embedding.py:90`). Local-file build:
    returns the conventional names users should place on disk."""
    table = {c: sorted(getattr(k, "pretrained_file_names", []))
             for c, k in _REGISTRY.items()}
    if embedding_name is not None:
        return table[embedding_name.lower()]
    return table


class TokenEmbedding(_vocab.Vocabulary):
    """Base embedding: vocabulary + idx_to_vec matrix
    (`embedding.py:133 _TokenEmbedding`)."""

    def __init__(self, init_unknown_vec=onp.zeros, **kwargs):
        super().__init__(**kwargs)
        self._init_unknown_vec = init_unknown_vec
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_file(self, path, elem_delim=" ", encoding="utf8"):
        """Parse `token<delim>v1<delim>v2...` lines; first occurrence of a
        token wins (`embedding.py:...` duplicate-skip behavior)."""
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"pretrained embedding file {path!r} not found. This build "
                f"runs without network access: place the file locally "
                f"(same text format as the reference) and pass its path.")
        tok_vecs = {}
        vec_len = None
        with io.open(path, encoding=encoding) as f:
            for line_num, line in enumerate(f):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue  # malformed line
                if len(parts) == 2 and line_num == 0:
                    try:  # fastText-style "count dim" header
                        int(parts[0]), int(parts[1])
                        continue
                    except ValueError:
                        pass
                tok, vals = parts[0], parts[1:]
                try:
                    vec = onp.asarray([float(v) for v in vals],
                                      dtype=onp.float32)
                except ValueError:
                    continue
                if vec_len is None:
                    vec_len = len(vec)
                elif len(vec) != vec_len:
                    raise ValueError(
                        f"line {line_num}: vector length {len(vec)} != "
                        f"{vec_len}")
                tok_vecs.setdefault(tok, vec)
        if vec_len is None:
            raise ValueError(f"no vectors parsed from {path!r}")
        self._vec_len = vec_len
        return tok_vecs

    def _build_vectors(self, tok_vecs, vocabulary=None):
        if vocabulary is None:
            # all file tokens become the index
            for tok in tok_vecs:
                if tok not in self._token_to_idx:
                    self._token_to_idx[tok] = len(self._idx_to_token)
                    self._idx_to_token.append(tok)
        mat = onp.tile(
            self._init_unknown_vec((self._vec_len,)).astype(onp.float32),
            (len(self), 1))
        for tok, vec in tok_vecs.items():
            idx = self._token_to_idx.get(tok)
            if idx is not None:
                mat[idx] = vec
        self._idx_to_vec = NDArray(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        """Embedding rows for token(s) (`embedding.py:316`)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idx.append(i if i is not None else _vocab.UNKNOWN_IDX)
        rows = self._idx_to_vec.asnumpy()[onp.asarray(idx)]
        out = NDArray(rows[0] if single else rows)
        return out

    def update_token_vectors(self, tokens, new_vectors):
        """Overwrite rows for known tokens (`embedding.py:360`)."""
        toks = [tokens] if isinstance(tokens, str) else tokens
        vals = new_vectors.asnumpy() if isinstance(new_vectors, NDArray) \
            else onp.asarray(new_vectors, dtype=onp.float32)
        vals = vals.reshape(len(toks), self._vec_len)
        mat = onp.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, v in zip(toks, vals):
            i = self._token_to_idx.get(t)
            if i is None:
                raise ValueError(f"token {t!r} is unknown; only known "
                                 f"tokens can be updated")
            mat[i] = v
        self._idx_to_vec = NDArray(mat)


@register
class GloVe(TokenEmbedding):
    """GloVe text-format loader (`embedding.py:481`) — local file only."""

    pretrained_file_names = ["glove.6B.50d.txt", "glove.6B.100d.txt",
                             "glove.6B.200d.txt", "glove.6B.300d.txt",
                             "glove.42B.300d.txt", "glove.840B.300d.txt"]

    def __init__(self, pretrained_file_path, vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        tok_vecs = self._load_embedding_file(pretrained_file_path, " ")
        if vocabulary is not None:
            self._adopt_vocab(vocabulary)
        self._build_vectors(tok_vecs, vocabulary)

    def _adopt_vocab(self, vocabulary):
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens


@register
class FastText(GloVe):
    """FastText .vec text-format loader (`embedding.py:553`) — the format
    is token + space-separated floats, identical parsing to GloVe text."""

    pretrained_file_names = ["wiki.simple.vec", "wiki.en.vec"]


@register
class CustomEmbedding(TokenEmbedding):
    """User-supplied embedding file with arbitrary delimiter
    (`embedding.py:635`)."""

    def __init__(self, pretrained_file_path, elem_delim=" ", encoding="utf8",
                 vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        tok_vecs = self._load_embedding_file(pretrained_file_path, elem_delim,
                                             encoding)
        if vocabulary is not None:
            self._idx_to_token = list(vocabulary.idx_to_token)
            self._token_to_idx = dict(vocabulary.token_to_idx)
            self._unknown_token = vocabulary.unknown_token
            self._reserved_tokens = vocabulary.reserved_tokens
        self._build_vectors(tok_vecs, vocabulary)


@register
class CompositeEmbedding(TokenEmbedding):
    """Concatenate several embeddings over one vocabulary
    (`embedding.py:677`)."""

    def __init__(self, vocabulary, token_embeddings, **kwargs):
        super().__init__(**kwargs)
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self._unknown_token = vocabulary.unknown_token
        self._reserved_tokens = vocabulary.reserved_tokens
        parts = []
        for emb in token_embeddings:
            rows = emb.get_vecs_by_tokens(self._idx_to_token).asnumpy()
            parts.append(rows.reshape(len(self), emb.vec_len))
        mat = onp.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = NDArray(mat)
