"""Text counting utilities (reference:
`python/mxnet/contrib/text/utils.py:26` count_tokens_from_str)."""
from __future__ import annotations

import collections
import re

__all__ = ["count_tokens_from_str"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Count tokens, splitting on regex delimiters."""
    tokens = [t for t in
              re.split(f"(?:{token_delim})|(?:{seq_delim})", source_str) if t]
    if to_lower:
        tokens = [t.lower() for t in tokens]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter
