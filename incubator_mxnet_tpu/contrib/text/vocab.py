"""Text token indexing (reference: `python/mxnet/contrib/text/vocab.py:28`
`Vocabulary` — unknown token at index 0, reserved tokens, frequency-ordered
counter keys)."""
from __future__ import annotations

import collections

__all__ = ["Vocabulary"]

UNKNOWN_IDX = 0


class Vocabulary:
    """Frequency-ordered token index with an unknown slot at 0."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq <= 0:
            raise ValueError("`min_freq` must be positive")
        if reserved_tokens is not None:
            rset = set(reserved_tokens)
            if unknown_token in rset:
                raise ValueError("`reserved_tokens` cannot contain "
                                 "`unknown_token`")
            if len(rset) != len(reserved_tokens):
                raise ValueError("`reserved_tokens` cannot contain duplicates")
        self._unknown_token = unknown_token
        self._idx_to_token = [unknown_token]
        self._reserved_tokens = None if reserved_tokens is None \
            else list(reserved_tokens)
        if reserved_tokens is not None:
            self._idx_to_token.extend(reserved_tokens)
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}

        if counter is not None:
            if not isinstance(counter, collections.Counter):
                raise TypeError("`counter` must be a collections.Counter")
            skip = set(self._idx_to_token)
            # frequency desc, then insertion order for ties (__cmp__ parity)
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1],))
            budget = most_freq_count if most_freq_count is not None else \
                len(pairs)
            taken = 0
            for tok, freq in pairs:
                if freq < min_freq or taken >= budget:
                    break
                if tok in skip:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)
                taken += 1

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Token(s) → index/indices; unknown maps to 0 (`vocab.py:163`)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, UNKNOWN_IDX) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        """Index/indices → token(s) (`vocab.py:191`)."""
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        toks = []
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise ValueError(f"token index {i} out of range")
            toks.append(self._idx_to_token[i])
        return toks[0] if single else toks
