"""INT8 post-training quantization (reference:
`python/mxnet/contrib/quantization.py` quantize_net/quantize_model,
`src/operator/quantization/calibrate.cc` entropy calibration,
`quantize_graph_pass.cc` graph rewrite).

TPU-native design: instead of an nnvm graph pass inserting
quantize/dequantize nodes around oneDNN int8 kernels, calibrated
Dense/Conv blocks are REPLACED with quantized blocks whose forward

    xq = clip(round(x / s_x))  ->  int8 matmul/conv on the MXU
    (int32 accumulate)         ->  y = acc * (s_x * s_w[oc]) + bias

executes the integer contraction with `lax.dot_general` /
`lax.conv_general_dilated` at `preferred_element_type=int32` — the MXU's
int8 path (2× bf16 throughput) — and XLA fuses the scale/bias epilogue.
Weights use symmetric per-output-channel scales; activations use one
calibrated symmetric scale (minmax or KL-entropy, same algorithms as the
reference).
"""
from __future__ import annotations

import re

import numpy as onp

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["quantize_net", "quantize_model", "QuantizedDense",
           "QuantizedConv2D", "optimal_threshold_entropy",
           "collect_thresholds"]


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def optimal_threshold_entropy(hist, bin_edges, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold over an |activation| histogram
    (reference: `src/operator/quantization/calibrate.cc` GetOptimalThreshold
    — the TensorRT-style entropy calibration)."""
    hist = onp.asarray(hist, dtype=onp.float64)
    num_bins = hist.size
    if num_bins <= num_quantized_bins:
        return float(bin_edges[-1])
    best_kl = onp.inf
    best_i = num_bins
    total = hist.sum()
    if total == 0:
        return float(bin_edges[-1])
    for i in range(num_quantized_bins, num_bins + 1):
        p = hist[:i].copy()
        p[i - 1] += hist[i:].sum()          # clip outliers into last bin
        p_sum = p.sum()
        if p_sum == 0 or p[:i].max() == 0:
            continue
        # quantize the i reference bins down to num_quantized_bins
        q = onp.zeros(i, dtype=onp.float64)
        factor = i / num_quantized_bins
        for j in range(num_quantized_bins):
            lo = int(onp.floor(j * factor))
            hi = int(onp.ceil((j + 1) * factor))
            hi = min(hi, i)
            chunk = hist[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = onp.where(chunk > 0, chunk.sum() / nz, 0.0)
        # smoothed KL(P || Q)
        p_norm = p / p_sum
        q_sum = q.sum()
        if q_sum == 0:
            continue
        q_norm = q / q_sum
        mask = p_norm > 0
        eps = 1e-10
        kl = float((p_norm[mask]
                    * onp.log(p_norm[mask] / (q_norm[mask] + eps))).sum())
        if kl < best_kl:
            best_kl = kl
            best_i = i
    return float(bin_edges[best_i])


class _ActivationStats:
    """Two-pass activation collector: absmax, then histogram for entropy."""

    def __init__(self, num_bins=2048):
        self.num_bins = num_bins
        self.absmax = 0.0
        self.hist = None
        self.bin_edges = None

    def update_minmax(self, x):
        self.absmax = max(self.absmax, float(onp.abs(x).max()))

    def update_hist(self, x):
        if self.absmax == 0.0:
            return
        h, edges = onp.histogram(onp.abs(x), bins=self.num_bins,
                                 range=(0.0, self.absmax))
        if self.hist is None:
            self.hist = h.astype(onp.float64)
            self.bin_edges = edges
        else:
            self.hist += h

    def threshold(self, mode):
        if mode == "naive" or self.hist is None:
            return self.absmax if self.absmax > 0 else 1.0
        return optimal_threshold_entropy(self.hist, self.bin_edges)


def _iter_calib(calib_data, num_batches):
    n = 0
    for batch in calib_data:
        if n >= num_batches:
            break
        x = batch[0] if isinstance(batch, (list, tuple)) else batch
        yield x
        n += 1


def collect_thresholds(net, layers, calib_data, calib_mode="entropy",
                       num_calib_batches=10, num_bins=2048):
    """Run calibration forwards, recording each target layer's INPUT
    activation distribution; returns {layer_id: threshold}."""
    stats = {id(layer): _ActivationStats(num_bins) for _, _, layer in layers}
    originals = {}

    def _hook(layer, phase):
        orig = layer.forward

        def wrapped(x, *args, **kwargs):
            xv = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if phase == "minmax":
                stats[id(layer)].update_minmax(xv)
            else:
                stats[id(layer)].update_hist(xv)
            return orig(x, *args, **kwargs)

        return orig, wrapped

    phases = ["minmax"] + (["hist"] if calib_mode == "entropy" else [])
    batches = list(_iter_calib(calib_data, num_calib_batches))
    for phase in phases:
        for _, _, layer in layers:
            orig, wrapped = _hook(layer, phase)
            originals[id(layer)] = orig
            layer.forward = wrapped
        for x in batches:
            net(x if isinstance(x, NDArray) else NDArray(x))
        for _, _, layer in layers:
            del layer.forward        # restore the class method
    return {lid: s.threshold(calib_mode) for lid, s in stats.items()}


# ---------------------------------------------------------------------------
# quantized blocks
# ---------------------------------------------------------------------------

def _quantize_weight(w, axes):
    """Symmetric per-output-channel int8 weights. `axes` = reduction axes
    (all but the output-channel axis 0)."""
    absmax = onp.maximum(onp.abs(w).max(axis=axes, keepdims=True), 1e-8)
    scale = absmax / 127.0
    wq = onp.clip(onp.round(w / scale), -127, 127).astype(onp.int8)
    return wq, scale.astype(onp.float32)


def _int8_contract(contract):
    """Wrap an integer contraction; falls back to exact f32 emulation on
    backends without int8 MXU/conv support (int8 values are exact in f32
    up to 2^24-sized accumulations)."""
    def run(xq, wq):
        import jax.numpy as jnp

        try:
            return contract(xq, wq)
        except Exception:
            return contract(xq.astype(jnp.float32),
                            wq.astype(jnp.float32)).astype(jnp.int32)

    return run


class QuantizedDense(HybridBlock):
    """INT8 Dense (reference: quantized_fully_connected.cc). Holds int8
    weights + per-channel scales; forward quantizes the activation with the
    calibrated threshold and contracts on the MXU int8 path."""

    def __init__(self, dense, threshold):
        super().__init__()
        w = dense.weight.data().asnumpy()
        wq, w_scale = _quantize_weight(w, axes=1)   # (units, in), scale (units,1)
        self._wq = wq
        self._w_scale = w_scale[:, 0]
        self._bias = (dense.bias.data().asnumpy()
                      if dense.bias is not None else None)
        self._threshold = float(threshold)
        self._units = dense._units
        self._flatten = dense._flatten
        self.act = dense.act
        if self.act is not None:
            self.register_child(self.act, "act")

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        wq = self._wq
        w_scale = self._w_scale
        bias = self._bias
        s_x = self._threshold / 127.0
        flatten = self._flatten

        def f(xv):
            if flatten and xv.ndim > 2:
                xv = xv.reshape(xv.shape[0], -1)
            xq = jnp.clip(jnp.round(xv / s_x), -127, 127).astype(jnp.int8)
            dot = _int8_contract(lambda a, b: jax.lax.dot_general(
                a, b, (((a.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32))
            acc = dot(xq, jnp.asarray(wq))
            y = acc.astype(jnp.float32) * (s_x * jnp.asarray(w_scale))
            if bias is not None:
                y = y + jnp.asarray(bias)
            return y.astype(xv.dtype)

        out = apply_op("quantized_dense", f, (x,))
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"QuantizedDense({self._units}, threshold={self._threshold:.4g})"


class QuantizedConv2D(HybridBlock):
    """INT8 2D convolution (reference: quantized_conv.cc), NCHW layout."""

    def __init__(self, conv, threshold):
        super().__init__()
        w = conv.weight.data().asnumpy()            # (O, I, kh, kw)
        wq, w_scale = _quantize_weight(w, axes=(1, 2, 3))
        self._wq = wq
        self._w_scale = w_scale.reshape(-1)         # (O,)
        self._bias = (conv.bias.data().asnumpy()
                      if conv.bias is not None else None)
        self._threshold = float(threshold)
        self._stride = conv._stride
        self._pad = conv._pad
        self._dilate = conv._dilate
        self._groups = conv._groups
        self.act = conv.act
        if self.act is not None:
            self.register_child(self.act, "act")

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        wq = self._wq
        w_scale = self._w_scale
        bias = self._bias
        s_x = self._threshold / 127.0
        stride, pad, dilate, groups = (self._stride, self._pad,
                                       self._dilate, self._groups)

        def f(xv):
            xq = jnp.clip(jnp.round(xv / s_x), -127, 127).astype(jnp.int8)
            conv = _int8_contract(lambda a, b: jax.lax.conv_general_dilated(
                a, b, window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate,
                feature_group_count=groups,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=jnp.int32))
            acc = conv(xq, jnp.asarray(wq))
            y = acc.astype(jnp.float32) * (
                s_x * jnp.asarray(w_scale)[None, :, None, None])
            if bias is not None:
                y = y + jnp.asarray(bias)[None, :, None, None]
            return y.astype(xv.dtype)

        out = apply_op("quantized_conv", f, (x,))
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"QuantizedConv2D(threshold={self._threshold:.4g})"


# ---------------------------------------------------------------------------
# net rewrite
# ---------------------------------------------------------------------------

def _find_target_layers(block, prefix="", exclude=None):
    """(parent, child_name, layer) for every quantizable layer."""
    out = []
    for name, child in list(block._children.items()):
        path = f"{prefix}.{name}" if prefix else name
        if isinstance(child, (nn.Dense, nn.Conv2D)):
            if not (exclude and any(re.search(p, path) for p in exclude)):
                out.append((block, name, child))
        else:
            out.extend(_find_target_layers(child, path, exclude))
    return out


def _replace_child(parent, name, old, new):
    parent._children[name] = new
    # forward() reaches children through attributes, not _children
    for attr, val in list(parent.__dict__.items()):
        if val is old:
            parent.__dict__[attr] = new


def quantize_net(net, calib_data=None, calib_mode="entropy",
                 quantized_dtype="int8", exclude_layers_match=None,
                 num_calib_batches=10, logger=None):
    """Post-training INT8 quantization of a gluon net, in place.

    - `calib_data`: iterable of batches (or (data, label) pairs) for
      activation calibration. Required for calib_mode 'naive'/'entropy';
      with calib_mode='none' a fixed threshold of 1.0 is used (testing).
    - `calib_mode`: 'naive' (minmax) or 'entropy' (KL-optimal clip), per
      the reference's quantize_model modes.
    - `exclude_layers_match`: list of regexes of layer paths to keep fp32.
    Returns the mutated net (reference returns a new symbol+params; the
    TPU build swaps the layers so hybridize/export keep working)."""
    if quantized_dtype != "int8":
        raise ValueError("only int8 is supported on the TPU build")
    layers = _find_target_layers(net, exclude=exclude_layers_match)
    if not layers:
        return net
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise ValueError(f"calib_mode={calib_mode!r} requires calib_data")
        thresholds = collect_thresholds(net, layers, calib_data, calib_mode,
                                        num_calib_batches)
    else:
        thresholds = {id(layer): 1.0 for _, _, layer in layers}
    for parent, name, layer in layers:
        t = thresholds[id(layer)]
        q = (QuantizedDense(layer, t) if isinstance(layer, nn.Dense)
             else QuantizedConv2D(layer, t))
        _replace_child(parent, name, layer, q)
        if logger:
            logger.info("quantized %s (threshold=%.5g)", name, t)
    return net


def quantize_model(net, **kwargs):
    """Reference-API alias (`contrib.quantization.quantize_model`)."""
    return quantize_net(net, **kwargs)
