"""INT8 post-training quantization (reference:
`python/mxnet/contrib/quantization.py` quantize_net/quantize_model,
`src/operator/quantization/calibrate.cc` entropy calibration,
`quantize_graph_pass.cc` graph rewrite).

TPU-native design: instead of an nnvm graph pass inserting
quantize/dequantize nodes around oneDNN int8 kernels, calibrated
Dense/Conv blocks are REPLACED with quantized blocks whose forward

    xq = clip(round(x / s_x))  ->  int8 matmul/conv on the MXU
    (int32 accumulate)         ->  y = acc * (s_x * s_w[oc]) + bias

executes the integer contraction with `lax.dot_general` /
`lax.conv_general_dilated` at `preferred_element_type=int32` — the MXU's
int8 path (2× bf16 throughput) — and XLA fuses the scale/bias epilogue.
Weights use symmetric per-output-channel scales; activations use one
calibrated symmetric scale (minmax or KL-entropy, same algorithms as the
reference). Quantized weights/scales/thresholds live in registered
`Constant` parameters, so `save_parameters`/`load_parameters` round-trip
the quantized net.
"""
from __future__ import annotations

import re

import numpy as onp

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..gluon.parameter import Constant
from ..ndarray.ndarray import NDArray, apply_op

__all__ = ["quantize_net", "quantize_model", "QuantizedDense",
           "QuantizedConv2D", "optimal_threshold_entropy",
           "collect_thresholds", "fold_conv_bn",
           "quantize_symmetric", "dequantize_symmetric"]


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------

def _smooth_distribution(p, eps=1e-4):
    """Redistribute a little mass from nonzero to zero entries so the KL
    term is defined everywhere (reference: the calibration smoothing in
    `src/operator/quantization/calibrate.cc`). Returns None when the
    distribution can't absorb the smoothing."""
    is_zero = p == 0
    n_nonzero = p.size - is_zero.sum()
    if n_nonzero == 0:
        return None
    eps1 = eps * is_zero.sum() / n_nonzero
    out = p.astype(onp.float64).copy()
    out[is_zero] = eps
    out[~is_zero] -= eps1
    if (out[~is_zero] <= 0).any():
        return None
    return out


def optimal_threshold_entropy(hist, bin_edges, num_quantized_bins=255):
    """KL-divergence-optimal clip threshold over an |activation| histogram
    (reference: `src/operator/quantization/calibrate.cc` GetOptimalThreshold
    — the TensorRT-style entropy calibration). For each candidate clip bin
    `i`, the first `i` bins (outlier mass folded into bin i-1) are merged
    into `num_quantized_bins` equal-width groups; each nonzero position
    gets its group's nonzero-average; both distributions are eps-smoothed
    and the KL(P||Q)-minimizing threshold wins."""
    hist = onp.asarray(hist, dtype=onp.float64)
    num_bins = hist.size
    if num_bins <= num_quantized_bins:
        return float(bin_edges[-1])
    total = hist.sum()
    if total == 0:
        return float(bin_edges[-1])
    # suffix[i] = hist[i:].sum(); csum/cnz give O(1) range sums below
    suffix = onp.concatenate([hist[::-1].cumsum()[::-1], [0.0]])
    csum = onp.concatenate([[0.0], hist.cumsum()])
    cnz = onp.concatenate([[0], (hist > 0).cumsum()])
    best_kl = onp.inf
    best_i = num_bins
    for i in range(num_quantized_bins, num_bins + 1):
        p = hist[:i].copy()
        p[i - 1] += suffix[i]            # clip outliers into last bin
        nm = i // num_quantized_bins     # merged bins per quantized bin
        starts = onp.arange(num_quantized_bins) * nm
        stops = onp.concatenate([starts[1:], [i]])  # last absorbs remainder
        sums = csum[stops] - csum[starts]
        norms = cnz[stops] - cnz[starts]
        nzp = hist[:i] > 0
        if suffix[i] > 0 and hist[i - 1] == 0:
            # folding outliers made position i-1 (in the last group) nonzero
            nzp = nzp.copy()
            nzp[i - 1] = True
            norms[-1] += 1
        vals = onp.where(norms > 0, sums / onp.maximum(norms, 1), 0.0)
        owner = onp.minimum(onp.arange(i) // nm, num_quantized_bins - 1)
        q = vals[owner] * nzp
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        if ps is None or qs is None:
            continue
        ps /= ps.sum()
        qs /= qs.sum()
        kl = float((ps * onp.log(ps / qs)).sum())
        if kl < best_kl:
            best_kl = kl
            best_i = i
    return float(bin_edges[best_i])


class _ActivationStats:
    """Two-pass activation collector: absmax, then histogram for entropy."""

    def __init__(self, num_bins=2048):
        self.num_bins = num_bins
        self.absmax = 0.0
        self.hist = None
        self.bin_edges = None

    def update_minmax(self, x):
        self.absmax = max(self.absmax, float(onp.abs(x).max()))

    def update_hist(self, x):
        if self.absmax == 0.0:
            return
        h, edges = onp.histogram(onp.abs(x), bins=self.num_bins,
                                 range=(0.0, self.absmax))
        if self.hist is None:
            self.hist = h.astype(onp.float64)
            self.bin_edges = edges
        else:
            self.hist += h

    def threshold(self, mode):
        if mode == "naive" or self.hist is None:
            return self.absmax if self.absmax > 0 else 1.0
        return optimal_threshold_entropy(self.hist, self.bin_edges)


def _iter_calib(calib_data, num_batches):
    n = 0
    for batch in calib_data:
        if n >= num_batches:
            break
        x = batch[0] if isinstance(batch, (list, tuple)) else batch
        yield x
        n += 1


def _hybrid_blocks(block, out=None):
    if out is None:
        out = []
    if hasattr(block, "_active"):
        out.append(block)
    for child in block._children.values():
        _hybrid_blocks(child, out)
    return out


def collect_thresholds(net, layers, calib_data, calib_mode="entropy",
                       num_calib_batches=10, num_bins=2048):
    """Run calibration forwards, recording each target layer's INPUT
    activation distribution; returns {layer_id: threshold}.

    Calibration must execute eagerly — a cached/hybridized graph would
    bypass the per-layer hooks (and `asnumpy` on a tracer raises) — so
    hybridization is suspended for the duration and restored after.
    """
    stats = {id(layer): _ActivationStats(num_bins) for _, _, layer in layers}

    def _hook(layer, phase):
        orig = layer.forward

        def wrapped(x, *args, **kwargs):
            xv = x.asnumpy() if isinstance(x, NDArray) else onp.asarray(x)
            if phase == "minmax":
                stats[id(layer)].update_minmax(xv)
            else:
                stats[id(layer)].update_hist(xv)
            return orig(x, *args, **kwargs)

        return wrapped

    phases = ["minmax"] + (["hist"] if calib_mode == "entropy" else [])
    batches = list(_iter_calib(calib_data, num_calib_batches))
    hybrids = _hybrid_blocks(net)
    was_active = [(b, b._active) for b in hybrids]
    try:
        for b in hybrids:
            b._active = False
            b._cached_graph = None
        for phase in phases:
            try:
                for _, _, layer in layers:
                    layer.forward = _hook(layer, phase)
                for x in batches:
                    net(x if isinstance(x, NDArray) else NDArray(x))
            finally:
                for _, _, layer in layers:
                    layer.__dict__.pop("forward", None)
    finally:
        for b, active in was_active:
            b._active = active
    return {lid: s.threshold(calib_mode) for lid, s in stats.items()}


# ---------------------------------------------------------------------------
# quantized blocks
# ---------------------------------------------------------------------------

def _quantize_weight(w, axes):
    """Symmetric per-output-channel int8 weights. `axes` = reduction axes
    (all but the output-channel axis 0)."""
    absmax = onp.maximum(onp.abs(w).max(axis=axes, keepdims=True), 1e-8)
    scale = absmax / 127.0
    wq = onp.clip(onp.round(w / scale), -127, 127).astype(onp.int8)
    return wq, scale.astype(onp.float32)


def quantize_symmetric(x, axes, scale=None):
    """Traceable symmetric int8 quantization (the jax-side twin of
    `_quantize_weight`, same ±127 convention) for in-graph consumers like
    the serving int8 KV cache (`serve.SlotDecoder`,
    ``MXNET_SERVE_KV_DTYPE=int8``).

    `axes` are the reduction axes of the absmax group (e.g. a KV page's
    token×head_dim block); `scale` overrides the derived absmax/127 scale
    (used when re-quantizing into an existing page's scale). Returns
    ``(q_int8, scale)`` with `scale` keeping the reduced axes as size-1
    dims so ``q * scale`` dequantizes by broadcast.
    """
    import jax.numpy as jnp

    if scale is None:
        absmax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
        scale = jnp.maximum(absmax, 1e-8).astype(jnp.float32) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_symmetric(q, scale, dtype=None):
    """Inverse of `quantize_symmetric`: broadcast-multiply back to real
    values (`dtype` defaults to the scale's float dtype)."""
    x = q.astype(scale.dtype) * scale
    return x if dtype is None else x.astype(dtype)


def _int8_contract(contract):
    """Wrap an integer contraction; falls back to exact f32 emulation on
    backends without int8 MXU/conv support (int8 values are exact in f32
    up to 2^24-sized accumulations)."""
    def run(xq, wq):
        import jax.numpy as jnp

        try:
            return contract(xq, wq)
        except Exception:
            return contract(xq.astype(jnp.float32),
                            wq.astype(jnp.float32)).astype(jnp.int32)

    return run


def _constant(value):
    return Constant(NDArray(value))


def _chain_dtype(layer, x):
    """Activation dtype carried across an int8 requantize chain: an int8
    input can't say what the net's float dtype is, so each producer
    records it on its consumer before that consumer traces. Returns the
    dtype this layer's output should restore to."""
    x_dt = (x._data if isinstance(x, NDArray) else x).dtype
    if x_dt == onp.int8:
        chain_dt = layer.__dict__.get("_chain_in_dt", onp.float32)
    else:
        chain_dt = x_dt
    consumer = layer.__dict__.get("_chain_consumer")
    if layer._out_threshold is not None and consumer is not None:
        # a producer may feed SEVERAL decoders of the same codes (a
        # residual block's body[0] AND its downsample both consume the
        # boundary producer's emit): seed every one, or the later-traced
        # branch would clobber the dtype with the float32 default
        consumers = consumer if isinstance(consumer, (tuple, list)) \
            else (consumer,)
        for c in consumers:
            c.__dict__["_chain_in_dt"] = chain_dt
    return chain_dt


class QuantizedDense(HybridBlock):
    """INT8 Dense (reference: quantized_fully_connected.cc). Holds int8
    weights + per-channel scales in Constant parameters; forward quantizes
    the activation with the calibrated threshold and contracts on the MXU
    int8 path."""

    def __init__(self, dense, threshold):
        super().__init__()
        w = dense.weight.data().asnumpy()
        wq, w_scale = _quantize_weight(w, axes=1)   # (units, in), scale (units,1)
        self.qweight = _constant(wq)
        self.qscale = _constant(w_scale[:, 0])
        self.qthreshold = _constant(onp.float32(threshold))
        self.qbias = (_constant(dense.bias.data().asnumpy())
                      if dense.bias is not None else None)
        self._units = dense._units
        self._flatten = dense._flatten
        self._out_threshold = None   # set by requantize chaining
        self.act = dense.act
        if self.act is not None:
            self.register_child(self.act, "act")

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        flatten = self._flatten
        has_bias = self.qbias is not None
        has_out = self._out_threshold is not None
        chain_dt = _chain_dtype(self, x)

        def f(xv, wq, w_scale, thresh, *rest):
            s_x = thresh.astype(jnp.float32) / 127.0
            if flatten and xv.ndim > 2:
                xv = xv.reshape(xv.shape[0], -1)
            if xv.dtype == jnp.int8:
                # requantize-chained producer already emitted at our scale
                xq, out_dt = xv, chain_dt
            else:
                xq = jnp.clip(jnp.round(xv / s_x), -127, 127).astype(jnp.int8)
                out_dt = xv.dtype
            dot = _int8_contract(lambda a, b: jax.lax.dot_general(
                a, b, (((a.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32))
            acc = dot(xq, wq)
            y = acc.astype(jnp.float32) * (s_x * w_scale)
            rest = list(rest)
            if has_bias:
                y = y + rest.pop(0)
            if has_out:
                # emit int8 at the CONSUMER'S calibrated scale; relu /
                # identity glue in between is monotonic so it commutes
                # with the rounding
                out_t = rest.pop(0).astype(jnp.float32)
                return jnp.clip(jnp.round(y * (127.0 / out_t)),
                                -127, 127).astype(jnp.int8)
            return y.astype(out_dt)

        args = (x, self.qweight.data(), self.qscale.data(),
                self.qthreshold.data())
        if has_bias:
            args = args + (self.qbias.data(),)
        if has_out:
            args = args + (self._out_threshold.data(),)
        out = apply_op("quantized_dense", f, args)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        t = float(self.qthreshold.data().asnumpy())
        return f"QuantizedDense({self._units}, threshold={t:.4g})"


class QuantizedConv2D(HybridBlock):
    """INT8 2D convolution (reference: quantized_conv.cc), NCHW layout."""

    def __init__(self, conv, threshold):
        super().__init__()
        w = conv.weight.data().asnumpy()            # (O, I, kh, kw)
        wq, w_scale = _quantize_weight(w, axes=(1, 2, 3))
        self.qweight = _constant(wq)
        self.qscale = _constant(w_scale.reshape(-1))  # (O,)
        self.qthreshold = _constant(onp.float32(threshold))
        self.qbias = (_constant(conv.bias.data().asnumpy())
                      if conv.bias is not None else None)
        self._stride = conv._stride
        self._pad = conv._pad
        self._dilate = conv._dilate
        self._groups = conv._groups
        self._out_threshold = None   # set by requantize chaining
        self.act = conv.act
        if self.act is not None:
            self.register_child(self.act, "act")

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        stride, pad, dilate, groups = (self._stride, self._pad,
                                       self._dilate, self._groups)
        has_bias = self.qbias is not None
        has_out = self._out_threshold is not None
        chain_dt = _chain_dtype(self, x)

        def f(xv, wq, w_scale, thresh, *rest):
            s_x = thresh.astype(jnp.float32) / 127.0
            if xv.dtype == jnp.int8:
                xq, out_dt = xv, chain_dt
            else:
                xq = jnp.clip(jnp.round(xv / s_x), -127, 127).astype(jnp.int8)
                out_dt = xv.dtype
            conv = _int8_contract(lambda a, b: jax.lax.conv_general_dilated(
                a, b, window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate,
                feature_group_count=groups,
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                preferred_element_type=jnp.int32))
            acc = conv(xq, wq)
            y = acc.astype(jnp.float32) * (
                s_x * w_scale[None, :, None, None])
            rest = list(rest)
            if has_bias:
                y = y + rest.pop(0)[None, :, None, None]
            if has_out:
                out_t = rest.pop(0).astype(jnp.float32)
                return jnp.clip(jnp.round(y * (127.0 / out_t)),
                                -127, 127).astype(jnp.int8)
            return y.astype(out_dt)

        args = (x, self.qweight.data(), self.qscale.data(),
                self.qthreshold.data())
        if has_bias:
            args = args + (self.qbias.data(),)
        if has_out:
            args = args + (self._out_threshold.data(),)
        out = apply_op("quantized_conv", f, args)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        t = float(self.qthreshold.data().asnumpy())
        return f"QuantizedConv2D(threshold={t:.4g})"


# ---------------------------------------------------------------------------
# net rewrite
# ---------------------------------------------------------------------------

def fold_conv_bn(net, logger=None):
    """Fold inference-mode BatchNorm into the preceding Conv2D/Dense
    wherever the two are ADJACENT children of the same block (the
    HybridSequential conv→bn idiom of every model_zoo CNN). The BN becomes
    `nn.Identity`, and the conv's weights/bias absorb the affine:

        w' = w * (gamma/sqrt(var+eps))[oc],  b' = beta - mean*gamma/sqrt(..)

    Reference: the oneDNN quantize pass does the same fold before emitting
    int8 kernels (`src/operator/subgraph/dnnl/dnnl_conv_property.h` — conv
    +bn fusion), which is why its int8 chains have no f32 BN in between.
    Safe only for inference: running stats are frozen into the weights.
    Returns the number of folds performed."""
    from ..gluon.parameter import Parameter

    n_folds = 0
    stack = [net]
    while stack:
        block = stack.pop()
        # declaration order equals dataflow order ONLY inside
        # HybridSequential — arbitrary blocks may declare parallel branches
        # as adjacent attributes, so only sequential containers are folded
        if isinstance(block, nn.HybridSequential):
            names = list(block._children)
        else:
            names = []
        for a, b in zip(names, names[1:]):
            ca, cb = block._children[a], block._children[b]
            # exact type: BatchNormReLU is a subclass whose fused relu
            # must survive the fold as an explicit Activation
            bn_relu = type(cb).__name__ == "BatchNormReLU"
            if not (type(cb) is nn.BatchNorm or bn_relu):
                continue
            if not isinstance(ca, (nn.Conv2D, nn.Dense)):
                continue
            # a fused activation runs BETWEEN the conv output and the BN:
            # folding would move the BN affine to before the relu, changing
            # results. The reference oneDNN pass only folds bare conv->BN.
            if getattr(ca, "act", None) is not None:
                if logger:
                    logger.info("skip BN fold into %s: fused activation", a)
                continue
            gamma = (cb.gamma.data().asnumpy() if cb._scale
                     else onp.ones(cb.running_var.shape, onp.float32))
            beta = cb.beta.data().asnumpy()
            mean = cb.running_mean.data().asnumpy()
            var = cb.running_var.data().asnumpy()
            inv = gamma / onp.sqrt(var + cb._epsilon)
            w = ca.weight.data().asnumpy()
            w_shape = (-1,) + (1,) * (w.ndim - 1)
            # keep the conv's declared dtype: w*inv promotes bf16/f16 to f32
            ca.weight.set_data(
                NDArray((w * inv.reshape(w_shape)).astype(w.dtype)))
            bias = beta - mean * inv
            if ca.bias is not None:
                bias = bias + ca.bias.data().asnumpy() * inv
                ca.bias.set_data(NDArray(bias.astype(w.dtype)))
            else:
                p = Parameter(shape=bias.shape, dtype=str(w.dtype))
                p.set_data(NDArray(bias.astype(w.dtype)))
                ca.bias = p
            _replace_child(block, b, cb,
                           nn.Activation("relu") if bn_relu else nn.Identity())
            n_folds += 1
            if logger:
                logger.info("folded BatchNorm %s into %s", b, a)
        stack.extend(c for c in block._children.values()
                     if isinstance(c, HybridBlock))
    for blk in _hybrid_blocks(net):
        blk._cached_graph = None
    return n_folds


def _chain_requantize(net, logger=None):
    """Where quantized layers follow each other through only monotonic
    elementwise glue (relu Activations / Identity) inside one container,
    make the producer emit int8 AT THE CONSUMER'S SCALE so no f32
    activation materializes between MXU int8 ops (reference:
    `src/operator/quantization/requantize-inl.h` chained through the
    quantize_graph_pass). Returns the number of chained pairs."""
    n_chained = 0
    stack = [net]
    while stack:
        block = stack.pop()
        # same restriction as fold_conv_bn: only HybridSequential children
        # are guaranteed to run in declaration order
        kids = ([block._children[n] for n in block._children]
                if isinstance(block, nn.HybridSequential) else [])
        for i, prod in enumerate(kids):
            if not isinstance(prod, (QuantizedConv2D, QuantizedDense)):
                continue
            # the int8 emit happens BEFORE the producer's own fused
            # activation; only a monotonic non-saturating act (relu) or
            # none commutes with the rounding — sigmoid/tanh/gelu applied
            # to int8 CODES would be nonsense
            if prod.act is not None and getattr(
                    prod.act, "_act_type", None) != "relu":
                continue
            j = i + 1
            while j < len(kids) and (
                    isinstance(kids[j], nn.Identity)
                    or (isinstance(kids[j], nn.Activation)
                        and kids[j]._act_type == "relu")):
                j += 1
            if j < len(kids) and isinstance(
                    kids[j], (QuantizedConv2D, QuantizedDense)):
                # share the consumer's qthreshold PARAMETER (not a baked
                # float): load_parameters updates it in place and the
                # producer's emit scale follows. __dict__ assignment on
                # purpose — Block.__setattr__ would REGISTER the shared
                # Parameter under the producer (duplicate checkpoint key,
                # renamed parameter)
                prod.__dict__["_out_threshold"] = kids[j].qthreshold
                # back-ref so the producer can forward its activation
                # dtype to the chain consumer (last layer of an int8
                # chain must emit the NET'S dtype, not hardcoded f32)
                prod.__dict__["_chain_consumer"] = kids[j]
                n_chained += 1
                if logger:
                    logger.info("requantize-chained %s -> %s",
                                type(prod).__name__, type(kids[j]).__name__)
        stack.extend(c for c in block._children.values()
                     if isinstance(c, HybridBlock))
    return n_chained


class QuantizedResidualBlock(HybridBlock):
    """INT8 residual block (reference: the oneDNN subgraph pass fuses
    conv+sum+relu into one int8 primitive, `src/operator/subgraph/dnnl/
    dnnl_conv_property.h` sum fusion — VERDICT r3 #3 'int8 residual-add
    chaining').

    Wraps a quantized BottleneckV1/BasicBlockV1: the body's LAST conv and
    the downsample's last conv both emit int8 at a SHARED add-scale
    (T_add), so the residual add is int8+int8 in one fused elementwise
    kernel — add, relu, and the requantize to the NEXT block's input
    scale never materialize an f32 activation (3 f32 HBM round-trips per
    block on the unchained path). The identity branch arrives as int8 at
    this block's own input scale (the previous block emitted it there).
    """

    def __init__(self, block, t_add):
        super().__init__()
        self.body = block.body
        self.downsample = block.downsample
        self.qadd_threshold = _constant(onp.float32(t_add))
        body_last = _last_quantized(self.body)
        body_last.__dict__["_out_threshold"] = self.qadd_threshold
        body_last.__dict__["_chain_consumer"] = self
        self._ds_chained = False
        if self.downsample is not None:
            ds_last = _last_quantized(self.downsample)
            if ds_last is not None:
                ds_last.__dict__["_out_threshold"] = self.qadd_threshold
                ds_last.__dict__["_chain_consumer"] = self
                self._ds_chained = True
        # input scale of the identity branch = the first body conv's
        # calibrated input threshold (the previous block emits there).
        # __dict__ writes on purpose: Block.__setattr__ would RE-REGISTER
        # (and rename) the shared Parameter under this wrapper — the
        # duplicate-checkpoint-key hazard _chain_requantize documents
        first = self.body._children[list(self.body._children)[0]]
        self.__dict__["_in_threshold"] = getattr(first, "qthreshold", None)
        self.__dict__["_out_threshold"] = None  # set when NEXT block chains
        self.__dict__["_chain_consumer"] = None

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        a = self.body(x)
        res = self.downsample(x) if self.downsample is not None else x
        has_out = self._out_threshold is not None
        chain_dt = _chain_dtype(self, x)
        has_in_t = self._in_threshold is not None

        def f(av, rv, t_add, *rest):
            rest = list(rest)
            s_add = t_add.astype(jnp.float32) / 127.0
            if av.dtype == jnp.int8 and rv.dtype == jnp.int8 \
                    and self._ds_chained:
                # both branches int8 AT THE SAME SCALE: integer add
                y = (av.astype(jnp.int32)
                     + rv.astype(jnp.int32)).astype(jnp.float32) * s_add
                out_dt = chain_dt
            else:
                ya = av.astype(jnp.float32) * s_add \
                    if av.dtype == jnp.int8 else av.astype(jnp.float32)
                if rv.dtype == jnp.int8 and has_in_t:
                    s_in = rest.pop(0).astype(jnp.float32) / 127.0
                    yr = rv.astype(jnp.float32) * s_in
                else:
                    if rv.dtype == jnp.int8:
                        raise TypeError("int8 identity without scale")
                    yr = rv.astype(jnp.float32)
                y = ya + yr
                out_dt = chain_dt if (av.dtype == jnp.int8
                                      or rv.dtype == jnp.int8) else av.dtype
            y = jax.nn.relu(y)
            if has_out:
                out_t = rest.pop(-1).astype(jnp.float32)
                return jnp.clip(jnp.round(y * (127.0 / out_t)),
                                -127, 127).astype(jnp.int8)
            return y.astype(out_dt)

        args = (a, res, self.qadd_threshold.data())
        if has_in_t:
            args = args + (self._in_threshold.data(),)
        if has_out:
            args = args + (self._out_threshold.data(),)
        return apply_op("quantized_residual_add", f, args)

    def __repr__(self):
        t = float(self.qadd_threshold.data().asnumpy())
        return f"QuantizedResidualBlock(t_add={t:.4g})"


def _last_quantized(seq):
    """Last QuantizedConv2D/Dense of a Sequential, skipping trailing glue
    (Identity from BN folds, relu Activations — both pass int8 codes
    through monotonically)."""
    for child in reversed(list(seq._children.values())):
        if isinstance(child, (QuantizedConv2D, QuantizedDense)):
            return child
        if isinstance(child, nn.Identity):
            continue
        if isinstance(child, nn.Activation) and \
                getattr(child, "_act_type", None) == "relu":
            continue
        return None
    return None


_RESIDUAL_V1_NAMES = frozenset({"BottleneckV1", "BasicBlockV1"})


def chain_residual_blocks(net, calib_data=None, num_calib_batches=10,
                          logger=None):
    """Chain int8 through V1 residual blocks: calibrate each block's
    add-domain range (one eager pass over `calib_data` recording
    max|body out| and max|shortcut out|), wrap the blocks, and link
    consecutive blocks so each add emits int8 at the NEXT block's input
    scale. Returns the number of blocks chained."""
    # find candidate blocks: V1 residual blocks whose body convs were
    # quantized (the stages are HybridSequential in the model zoo)
    candidates = []     # (parent, name, block)

    def walk(block):
        for name, child in list(block._children.items()):
            if type(child).__name__ in _RESIDUAL_V1_NAMES:
                if _last_quantized(child.body) is not None:
                    candidates.append((block, name, child))
                continue
            if isinstance(child, HybridBlock):
                walk(child)

    walk(net)
    if not candidates or calib_data is None:
        return 0

    # one eager calibration pass on the already-quantized net: record the
    # add-domain minmax per block (|body out| and |shortcut|)
    ranges = {id(b): 0.0 for _, _, b in candidates}
    hooks = []
    n_batches = 0

    def _make_recorder(b):
        def wrapped(x):
            a = b.body(x)
            r = b.downsample(x) if b.downsample is not None else x
            m = max(float(onp.abs(a.asnumpy()).max()),
                    float(onp.abs(r.asnumpy()).max()))
            ranges[id(b)] = max(ranges[id(b)], m)
            from .. import numpy_extension as npx

            return npx.activation(a + r, act_type="relu")

        return wrapped

    for _, _, b in candidates:
        hooks.append((b, b.forward))
        b.forward = _make_recorder(b)
    # suspend hybridization: the recorder's asnumpy() would trace-crash
    # inside a cached graph (same guard as collect_thresholds)
    hybrids = _hybrid_blocks(net)
    was_active = [(hb, hb._active) for hb in hybrids]
    try:
        for hb in hybrids:
            hb._active = False
        for batch in _iter_calib(calib_data, num_calib_batches):
            net(batch if isinstance(batch, NDArray) else NDArray(batch))
            n_batches += 1
    finally:
        for b, orig in hooks:
            b.forward = orig
        for hb, act in was_active:
            hb._active = act
    if n_batches == 0 or all(v == 0.0 for v in ranges.values()):
        # calib_data was a one-shot iterable already drained by
        # collect_thresholds: without add-domain ranges, chaining would
        # bake garbage scales — skip it (documented: pass a re-iterable)
        if logger:
            logger.warning("chain_residual_blocks: no calibration batches "
                           "(one-shot calib_data?); residual chaining "
                           "skipped")
        return 0

    # wrap the blocks
    for parent, name, b in candidates:
        t_add = max(ranges[id(b)], 1e-6)
        w = QuantizedResidualBlock(b, t_add)
        _replace_child(parent, name, b, w)
        if logger:
            logger.info("residual-chained %s (t_add=%.5g)", name, t_add)

    # link consecutive wrapped blocks WITHIN each stage: block[i] emits
    # int8 at block[i+1]'s input scale
    def link(block):
        kids = ([block._children[n] for n in block._children]
                if isinstance(block, nn.HybridSequential) else [])
        for i in range(len(kids) - 1):
            prod, cons = kids[i], kids[i + 1]
            if not (isinstance(prod, QuantizedResidualBlock)
                    and isinstance(cons, QuantizedResidualBlock)
                    and cons._in_threshold is not None):
                continue
            # EVERY consumer of the emitted int8 codes must decode them:
            # body[0] (the _in_threshold check) AND, when present, the
            # downsample's first layer — with AGREEING scales (shared
            # check: _res_in_threshold)
            t_in = _res_in_threshold(cons)
            if t_in is None:
                if logger:
                    logger.warning(
                        "residual chain skipped at %s: downsample cannot "
                        "decode at the body scale", type(cons).__name__)
                continue
            prod.__dict__["_out_threshold"] = t_in
            prod.__dict__["_chain_consumer"] = tuple(_res_decoders(cons))
        for c in block._children.values():
            if isinstance(c, HybridBlock):
                link(c)

    link(net)
    for blk in _hybrid_blocks(net):
        blk._cached_graph = None
    return len(candidates)


def _find_target_layers(block, prefix="", exclude=None):
    """(parent, child_name, layer) for every quantizable layer."""
    out = []
    for name, child in list(block._children.items()):
        path = f"{prefix}.{name}" if prefix else name
        if isinstance(child, (nn.Dense, nn.Conv2D)):
            if not (exclude and any(re.search(p, path) for p in exclude)):
                out.append((block, name, child))
        else:
            out.extend(_find_target_layers(child, path, exclude))
    return out


def _replace_child(parent, name, old, new):
    parent._children[name] = new
    # forward() reaches children through attributes (`self.fc`) or through
    # container lists (Sequential._layers) — patch both
    for attr, val in list(parent.__dict__.items()):
        if val is old:
            parent.__dict__[attr] = new
        elif isinstance(val, list):
            for i, item in enumerate(val):
                if item is old:
                    val[i] = new


def _first_resblock(b):
    if isinstance(b, QuantizedResidualBlock):
        return b
    if isinstance(b, nn.HybridSequential) and b._children:
        return _first_resblock(b._children[next(iter(b._children))])
    return None


def _last_resblock(b):
    if isinstance(b, QuantizedResidualBlock):
        return b
    if isinstance(b, nn.HybridSequential) and b._children:
        return _last_resblock(b._children[list(b._children)[-1]])
    return None


def _res_decoders(cons):
    """Every layer that decodes a producer's int8 codes when chaining
    INTO a residual block: body[0] and, when present, the downsample's
    first layer. Single source of truth for 'who consumes the emit' —
    used by both chaining passes and the scale-agreement check."""
    decoders = [cons.body._children[list(cons.body._children)[0]]]
    if cons.downsample is not None:
        decoders.append(cons.downsample._children[
            list(cons.downsample._children)[0]])
    return decoders


def _res_in_threshold(cons):
    """The shared decode threshold a producer may emit at, or None when
    the block's body and downsample would decode at diverging scales
    (the same agreement check `chain_residual_blocks.link` applies)."""
    t = cons.__dict__.get("_in_threshold")
    if t is None:
        return None
    decoders = _res_decoders(cons)
    if len(decoders) > 1:
        ds_first = decoders[1]
        if not isinstance(ds_first, (QuantizedConv2D, QuantizedDense)):
            return None
        t_in = float(t.data().asnumpy())
        t_ds = float(ds_first.qthreshold.data().asnumpy())
        if abs(t_in - t_ds) > 1e-5 * max(t_in, t_ds, 1e-6):
            return None
    return t


def chain_boundaries(net, logger=None):
    """Extend int8 requantize chains across the edges the per-container
    passes can't see (reference analogue: the oneDNN subgraph pass
    rewrites the WHOLE graph so its int8 chains cross pooling and stage
    boundaries naturally, `src/operator/subgraph/dnnl/`):

    - producer -> [MaxPool2D / Identity / relu Activation]* -> consumer:
      max pooling on int8 CODES commutes with the monotone per-tensor
      quantization, so the stem conv can emit int8 straight through the
      pool (the stem activations are the largest tensors in the net —
      (64, 64, 112, 112) f32 is a 205 MB round trip per inference).
    - stage_i[-1] residual block -> stage_{i+1}[0] residual block, where
      the stages are ADJACENT nested sequentials.

    Producers: QuantizedConv2D/Dense (fused act relu/None only) or a
    QuantizedResidualBlock; consumers: a residual block whose body and
    downsample agree on the decode scale. Existing chains are never
    overwritten. Returns the number of new links."""
    n_linked = 0
    stack = [net]
    while stack:
        block = stack.pop()
        if isinstance(block, nn.HybridSequential):
            kids = [block._children[n] for n in block._children]
            for i, holder in enumerate(kids):
                if isinstance(holder, (QuantizedConv2D, QuantizedDense)):
                    prod = holder
                    if prod.act is not None and getattr(
                            prod.act, "_act_type", None) != "relu":
                        continue
                else:
                    prod = _last_resblock(holder)
                if prod is None \
                        or prod.__dict__.get("_out_threshold") is not None:
                    continue
                j = i + 1
                while j < len(kids) and (
                        isinstance(kids[j], (nn.Identity, nn.MaxPool2D))
                        or (isinstance(kids[j], nn.Activation)
                            and kids[j]._act_type == "relu")):
                    j += 1
                if j >= len(kids):
                    continue
                cons = _first_resblock(kids[j])
                if cons is None or cons is prod:
                    continue
                t_in = _res_in_threshold(cons)
                if t_in is None:
                    continue
                prod.__dict__["_out_threshold"] = t_in
                # BOTH decoders of the emitted codes need the chain dtype
                # seeded (see _chain_dtype / _res_decoders)
                prod.__dict__["_chain_consumer"] = tuple(_res_decoders(cons))
                n_linked += 1
                if logger:
                    logger.info("boundary-chained %s -> %s",
                                type(prod).__name__, type(cons).__name__)
        stack.extend(c for c in block._children.values()
                     if isinstance(c, HybridBlock))
    if n_linked:
        # _out_threshold is read at TRACE time: stale cached graphs would
        # keep emitting f32 at the new links (chain_residual_blocks has
        # the same invalidation)
        for b in _hybrid_blocks(net):
            b._cached_graph = None
    return n_linked


def quantize_net(net, calib_data=None, calib_mode="entropy",
                 quantized_dtype="int8", exclude_layers_match=None,
                 num_calib_batches=10, fold_bn=True, requantize=True,
                 chain_residual=True, logger=None):
    """Post-training INT8 quantization of a gluon net, in place.

    - `calib_data`: iterable of batches (or (data, label) pairs) for
      activation calibration. Required for calib_mode 'naive'/'entropy';
      with calib_mode='none' a fixed threshold of 1.0 is used (testing).
    - `calib_mode`: 'naive' (minmax) or 'entropy' (KL-optimal clip), per
      the reference's quantize_model modes.
    - `exclude_layers_match`: list of regexes of layer paths to keep fp32.
    - `fold_bn`: fold adjacent Conv→BatchNorm pairs into the conv before
      calibrating, so no f32 BN pass interrupts the int8 chain.
    - `requantize`: chain consecutive quantized layers through int8 at the
      consumer's scale instead of round-tripping f32.
    Returns the mutated net (reference returns a new symbol+params; the
    TPU build swaps the layers so hybridize/export keep working)."""
    if quantized_dtype != "int8":
        raise ValueError("only int8 is supported on the TPU build")
    if fold_bn:
        fold_conv_bn(net, logger=logger)
    layers = _find_target_layers(net, exclude=exclude_layers_match)
    if not layers:
        return net
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise ValueError(f"calib_mode={calib_mode!r} requires calib_data")
        thresholds = collect_thresholds(net, layers, calib_data, calib_mode,
                                        num_calib_batches)
    else:
        thresholds = {id(layer): 1.0 for _, _, layer in layers}
    for parent, name, layer in layers:
        t = thresholds[id(layer)]
        q = (QuantizedDense(layer, t) if isinstance(layer, nn.Dense)
             else QuantizedConv2D(layer, t))
        _replace_child(parent, name, layer, q)
        if logger:
            logger.info("quantized %s (threshold=%.5g)", name, t)
    if requantize:
        _chain_requantize(net, logger=logger)
    if chain_residual and requantize and calib_data is not None:
        # V1 residual blocks: int8 through the add (one fused
        # add+relu+requantize kernel, no f32 activations between blocks)
        chain_residual_blocks(net, calib_data,
                              num_calib_batches=num_calib_batches,
                              logger=logger)
        # stem->stage and stage->stage boundaries: int8 codes flow THROUGH
        # max pools (max commutes with the monotone quantization) and
        # across nested-sequential edges — the biggest remaining f32 round
        # trips sit on the early 200 MB activations
        chain_boundaries(net, logger=logger)
    # stale traced graphs still reference the fp32 layers — force re-trace
    for b in _hybrid_blocks(net):
        b._cached_graph = None
    return net


def quantize_model(net, **kwargs):
    """Reference-API alias (`contrib.quantization.quantize_model`)."""
    return quantize_net(net, **kwargs)
