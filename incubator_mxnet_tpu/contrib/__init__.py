"""contrib — quantization, and other extensions outside the core namespace
(reference: `python/mxnet/contrib/`)."""
from . import quantization

__all__ = ["quantization"]
