"""contrib — quantization, text embeddings, tensorboard hook, and other
extensions outside the core namespace (reference: `python/mxnet/contrib/`).

`contrib.io` / `contrib.ndarray` / `contrib.symbol` in the reference are
thin re-export shims over the main namespaces; here they resolve lazily to
the same modules."""
from . import quantization, tensorboard, text  # noqa: F401

__all__ = ["quantization", "text", "tensorboard", "io", "ndarray", "symbol",
           "onnx"]


def __getattr__(name):
    # shim modules (reference contrib/io.py, contrib/ndarray.py,
    # contrib/symbol.py, contrib/onnx) — same objects as the main namespaces
    if name == "io":
        from .. import io as m

        return m
    if name == "ndarray":
        from .. import ndarray as m

        return m
    if name == "symbol":
        from .. import symbol as m

        return m
    if name == "onnx":
        from .. import onnx as m

        return m
    raise AttributeError(f"module 'contrib' has no attribute {name!r}")
