"""TensorBoard metric logging hook (reference:
`python/mxnet/contrib/tensorboard.py:24` LogMetricsCallback).

Uses a `tensorboardX`/`torch.utils.tensorboard` SummaryWriter when one is
importable; otherwise falls back to an append-only JSONL event file so
training scripts keep working on minimal TPU hosts (the file converts
trivially to TB events offline)."""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class _JsonlWriter:
    def __init__(self, logging_dir):
        os.makedirs(logging_dir, exist_ok=True)
        self._f = open(os.path.join(logging_dir, "metrics.jsonl"), "a")

    def add_scalar(self, tag, value, global_step=None):
        self._f.write(json.dumps({"tag": tag, "value": float(value),
                                  "step": global_step,
                                  "ts": time.time()}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


def _make_writer(logging_dir):
    try:
        from torch.utils.tensorboard import SummaryWriter  # type: ignore

        return SummaryWriter(logging_dir)
    except Exception:
        return _JsonlWriter(logging_dir)


class LogMetricsCallback:
    """Batch-end callback pushing eval metrics to TensorBoard
    (`tensorboard.py:24`)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _make_writer(logging_dir)

    def __call__(self, param):
        """param: `BatchEndParam`-style object with `.eval_metric`."""
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in zip(*_name_value(param.eval_metric)):
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)


def _name_value(metric):
    names, values = [], []
    got = metric.get()
    pairs = zip(*got) if isinstance(got[0], (list, tuple)) else [got]
    for name, value in pairs:
        names.append(name)
        values.append(value)
    return names, values
