"""Legacy model checkpoint helpers (reference: `python/mxnet/model.py:189`
`save_checkpoint` / `:238` `load_checkpoint` — symbol json + `.params`
epoch files).

File layout matches the reference convention:
  <prefix>-symbol.json           the architecture (mx.sym JSON)
  <prefix>-%04d.params           arg/aux parameters for one epoch
Parameter names are prefixed "arg:"/"aux:" exactly as the reference does, so
`load_checkpoint` can split them back.
"""
from __future__ import annotations

from . import symbol as sym
from .ndarray import load as nd_load
from .ndarray import save as nd_save
from .ndarray.ndarray import NDArray

__all__ = ["save_checkpoint", "load_params", "load_checkpoint",
           "BatchEndParam"]

import collections

BatchEndParam = collections.namedtuple(
    "BatchEndParam", ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params=None,
                    remove_amp_cast=True):  # noqa: ARG001
    """Save symbol + params for `epoch` (`model.py:189`)."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    payload = {f"arg:{k}": v if isinstance(v, NDArray) else NDArray(v)
               for k, v in (arg_params or {}).items()}
    payload.update({f"aux:{k}": v if isinstance(v, NDArray) else NDArray(v)
                    for k, v in (aux_params or {}).items()})
    nd_save(f"{prefix}-{epoch:04d}.params", payload)


def load_params(prefix, epoch):
    """(arg_params, aux_params) from an epoch file (`model.py:221`)."""
    loaded = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        kind, _, name = k.partition(":")
        if kind == "arg":
            arg_params[name] = v
        elif kind == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """(symbol, arg_params, aux_params) (`model.py:238`)."""
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
