"""Network visualization (reference: `python/mxnet/visualization.py:46`
`print_summary`, `:210` `plot_network`).

Works over `mx.sym.Symbol` graphs. `plot_network` emits graphviz DOT — via
the `graphviz` python package when installed, else a lightweight stand-in
exposing the same `.source`/`.save` surface (no rendering dependency
required on TPU hosts).
"""
from __future__ import annotations

import numpy as onp

__all__ = ["print_summary", "plot_network"]


def _node_label(node):
    if node._op is None:
        return node.name, "variable"
    return node.name, node._op


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Layer-table summary of a symbol graph (`visualization.py:46`)."""
    out_shapes = {}
    if shape is not None:
        # infer every node's output shape by evaluating internals
        import jax

        from .ndarray.ndarray import NDArray

        args = symbol._all_inputs()
        missing = [a for a in args if a not in shape]
        if missing:
            raise ValueError(f"print_summary: missing shapes for {missing}")

        def fn(vals):
            env = {a: NDArray(v) for a, v in zip(args, vals)}
            # the one shared DAG walk: Symbol._eval fills `record` with every
            # op node's value in a single memoized pass
            record: dict = {}
            symbol._eval(env, record=record)
            return {k: tuple(x._data for x in v) if isinstance(v, tuple)
                    else v._data for k, v in record.items()}

        specs = [jax.ShapeDtypeStruct(tuple(shape[a]), onp.float32)
                 for a in args]
        try:
            shaped = jax.eval_shape(fn, specs)
            out_shapes = {k: (tuple(tuple(x.shape) for x in v)
                              if isinstance(v, tuple) else tuple(v.shape))
                          for k, v in shaped.items()}
        except Exception:
            out_shapes = {}

    positions = [int(line_length * p) for p in positions]
    header = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]
    lines = []

    def fmt_row(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos - 1].ljust(pos)
        return line.rstrip()

    lines.append("_" * line_length)
    lines.append(fmt_row(header))
    lines.append("=" * line_length)
    total_params = 0
    order = symbol._topo()
    arg_shapes = dict(shape or {})
    for node in order:
        if node._op == "__group__":
            continue
        nm, kind = _node_label(node)
        if node._op is None:
            oshape = arg_shapes.get(nm, "")
            nparam = int(onp.prod(arg_shapes[nm])) if nm in arg_shapes else 0
        else:
            oshape = out_shapes.get(nm, "")
            nparam = 0
        total_params += nparam
        prev = ",".join(i.name for i in node._inputs)
        lines.append(fmt_row([f"{nm} ({kind})", oshape, nparam, prev]))
        lines.append("-" * line_length)
    lines.append(f"Total params: {total_params}")
    lines.append("_" * line_length)
    out = "\n".join(lines)
    print(out)
    return out


class _Dot:
    """Minimal graphviz.Digraph stand-in (source + save only)."""

    def __init__(self, title):
        self._title = title
        self._lines = [f'digraph "{title}" {{']

    def node(self, name, label, **attrs):
        a = "".join(f' {k}="{v}"' for k, v in attrs.items())
        self._lines.append(f'  "{name}" [label="{label}"{a}];')

    def edge(self, a, b):
        self._lines.append(f'  "{a}" -> "{b}";')

    @property
    def source(self):
        return "\n".join(self._lines + ["}"])

    def save(self, filename):
        with open(filename, "w") as f:
            f.write(self.source)
        return filename

    def render(self, *a, **k):  # noqa: ARG002
        raise RuntimeError("graphviz binary not available; use .source/.save")


_OP_COLOR = {"np.dot": "lightblue", "npx.fully_connected": "lightblue",
             "npx.convolution": "royalblue1", "npx.relu": "salmon",
             "npx.activation": "salmon", "npx.batch_norm": "orchid1",
             "npx.pooling": "gold", "np.add": "palegreen"}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,  # noqa: ARG001
                 dtype=None, node_attrs=None, hide_weights=True):  # noqa: ARG001
    """DOT graph of a symbol (`visualization.py:210`)."""
    try:
        from graphviz import Digraph  # type: ignore

        dot = Digraph(name=title)
    except Exception:
        dot = _Dot(title)
    order = symbol._topo()
    for node in order:
        if node._op == "__group__":
            continue
        nm, kind = _node_label(node)
        if node._op is None:
            if hide_weights and any(
                    nm == i.name for n in order for i in n._inputs) and \
                    any(h in nm for h in ("weight", "bias", "gamma", "beta",
                                          "moving", "running")):
                continue
            dot.node(nm, nm, shape="oval", fillcolor="#8dd3c7", style="filled")
        else:
            color = _OP_COLOR.get(node._op, "lightgrey")
            dot.node(nm, f"{nm}\n{kind}", shape="box", fillcolor=color,
                     style="filled")
    drawn = {n._name for n in order
             if not (n._op is None and hide_weights and any(
                 h in n._name for h in ("weight", "bias", "gamma", "beta",
                                        "moving", "running")))}
    for node in order:
        if node._op in (None, "__group__"):
            continue
        for inp in node._inputs:
            if inp.name in drawn:
                dot.edge(inp.name, node.name)
    return dot
