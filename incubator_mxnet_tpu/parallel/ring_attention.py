"""Ring attention — sequence-parallel exact attention for long contexts
(Liu et al., "Ring Attention with Blockwise Transformers"; the TPU-native
replacement for the reference's single-device fused attention at sequence
lengths that exceed one chip's HBM — reference role:
`src/operator/subgraph/dnnl/dnnl_transformer_qk_property.h`).

Each device on the `axis_name` ring holds one sequence shard of Q, K, V
(layout (B, H, T_local, D), matching `ops/flash_attention.py`). K/V blocks
rotate around the ring with `lax.ppermute` (neighbor ICI hops) while each
device accumulates its Q block's attention over every K/V block with the
numerically-stable online-softmax recurrence — communication overlaps with
the per-block attention compute, memory stays O(T_local).

Call INSIDE shard_map/pjit (like `parallel/collectives.py`);
`ring_self_attention` is the NDArray-level convenience that builds the
shard_map over the active mesh.
"""
from __future__ import annotations

import math
from functools import partial

__all__ = ["ring_attention", "ring_self_attention"]


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Exact attention over a sequence sharded on `axis_name`.

    q, k, v: (B, H, T_local, D) jax arrays (this device's sequence shard).
    Returns (B, H, T_local, D): attention output for the local Q block
    against the FULL (global) sequence.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import collectives

    b, h, t_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    n = collectives.axis_size(axis_name)
    my = lax.axis_index(axis_name)

    neg_inf = jnp.asarray(-jnp.inf, jnp.float32)
    q32 = q.astype(jnp.float32)

    def block_update(carry, kv_src_idx, k_blk, v_blk):
        o, m, l = carry
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k_blk.astype(jnp.float32)) * sm_scale
        if causal:
            q_pos = my * t_local + jnp.arange(t_local)
            k_pos = kv_src_idx * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, neg_inf)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # rows fully masked so far keep m=-inf; guard the exp shift
        shift = jnp.where(jnp.isneginf(m_new), 0.0, m - m_new)
        p = jnp.exp(s - jnp.where(jnp.isneginf(m_new), 0.0,
                                  m_new)[..., None])
        p = jnp.where(jnp.isneginf(m_new)[..., None], 0.0, p)
        alpha = jnp.exp(shift)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = (o * alpha[..., None]
                 + jnp.einsum("bhqk,bhkd->bhqd", p,
                              v_blk.astype(jnp.float32)))
        return o_new, m_new, l_new

    perm = None  # built lazily from the concrete axis size

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        kv_src = (my - i) % n  # whose block we currently hold
        o, m, l = block_update((o, m, l), kv_src, k_blk, v_blk)
        # rotate K/V to the next device (skippable on the last step, but a
        # static-trip fori_loop keeps the loop body uniform; XLA overlaps
        # the permute with the next block's einsum)
        k_blk = collectives.ring_permute(k_blk, axis_name)
        v_blk = collectives.ring_permute(v_blk, axis_name)
        return o, m, l, k_blk, v_blk

    # initial accumulators must carry the shard_map device-varying type of
    # the loop outputs (they depend on axis_index after one trip)
    o0 = collectives.pvary(jnp.zeros((b, h, t_local, d), jnp.float32),
                           (axis_name,))
    m0 = collectives.pvary(jnp.full((b, h, t_local), -jnp.inf, jnp.float32),
                           (axis_name,))
    l0 = collectives.pvary(jnp.zeros((b, h, t_local), jnp.float32),
                           (axis_name,))
    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(q, k, v, mesh=None, axis="sp", causal=False,
                        sm_scale=None):
    """NDArray-level ring attention: shards the sequence dim of
    (B, H, T, D) inputs over `axis` of the active mesh and runs
    `ring_attention` under shard_map."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..ndarray.ndarray import NDArray
    from .mesh import current_mesh

    mesh = mesh or current_mesh()
    if mesh is None:
        raise ValueError("ring_self_attention needs a mesh (pass mesh= or "
                         "enter a mesh_scope)")
    qv = q._data if isinstance(q, NDArray) else q
    kv = k._data if isinstance(k, NDArray) else k
    vv = v._data if isinstance(v, NDArray) else v

    spec = P(None, None, axis, None)  # shard T of (B, H, T, D)
    fn = shard_map(
        partial(ring_attention, axis_name=axis, causal=causal,
                sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = fn(qv, kv, vv)
    return NDArray(out) if isinstance(q, NDArray) else out
