"""Device mesh management (the TPU-native replacement for the reference's
device topology handling, `src/kvstore/gpu_topology.h` — on TPU the ICI
topology is expressed as a `jax.sharding.Mesh` and XLA routes collectives)."""
from __future__ import annotations

import threading

__all__ = ["Mesh", "make_mesh", "mesh_scope", "current_mesh"]


class _TLS(threading.local):
    def __init__(self):
        self.stack = []


_STATE = _TLS()


def Mesh(devices, axis_names):
    import jax

    return jax.sharding.Mesh(devices, axis_names)


def make_mesh(axis_shapes, devices=None):
    """Build a mesh from {'axis': size} (or ordered (axis, size) pairs);
    e.g. {'dp': 2, 'tp': 4}.

    Uses all available devices by default. Sizes must multiply to the device
    count (one -1 wildcard axis is allowed and must divide evenly)."""
    import numpy as onp

    import jax

    devices = devices if devices is not None else jax.devices()
    if not isinstance(axis_shapes, dict):
        axis_shapes = [(a, s) for a, s in axis_shapes]
        names = [a for a, _ in axis_shapes]
        axis_shapes = dict(axis_shapes)
    else:
        names = list(axis_shapes)
    if len(set(names)) != len(names):
        dupes = sorted({a for a in names if names.count(a) > 1})
        raise ValueError(f"mesh axis names must be unique, got duplicate "
                         f"{dupes} in {names}")
    sizes = list(axis_shapes.values())
    n = len(devices)
    if sizes.count(-1) > 1:
        raise ValueError(f"at most one -1 wildcard axis allowed, got "
                         f"{dict(zip(names, sizes))}")
    if -1 in sizes:
        wild = sizes.index(-1)
        known = int(onp.prod([s for s in sizes if s != -1]))
        if known <= 0 or n % known:
            raise ValueError(
                f"cannot infer wildcard axis {names[wild]!r}: {n} devices "
                f"not divisible by the known axes "
                f"{ {a: s for a, s in zip(names, sizes) if s != -1} } "
                f"(product {known})")
        sizes[wild] = n // known
    total = int(onp.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n}")
    arr = onp.asarray(devices[:total]).reshape(sizes)
    return jax.sharding.Mesh(arr, names)


class mesh_scope:
    """Context manager installing a mesh as the active one."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _STATE.stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False


def current_mesh():
    return _STATE.stack[-1] if _STATE.stack else None
