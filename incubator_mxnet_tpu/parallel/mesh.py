"""Device mesh management (the TPU-native replacement for the reference's
device topology handling, `src/kvstore/gpu_topology.h` — on TPU the ICI
topology is expressed as a `jax.sharding.Mesh` and XLA routes collectives)."""
from __future__ import annotations

import threading

__all__ = ["Mesh", "make_mesh", "mesh_scope", "current_mesh"]


class _TLS(threading.local):
    def __init__(self):
        self.stack = []


_STATE = _TLS()


def Mesh(devices, axis_names):
    import jax

    return jax.sharding.Mesh(devices, axis_names)


def make_mesh(axis_shapes: dict, devices=None):
    """Build a mesh from {'axis': size}; e.g. {'dp': 2, 'tp': 4}.

    Uses all available devices by default. Sizes must multiply to the device
    count (a -1 wildcard axis is allowed)."""
    import numpy as onp

    import jax

    devices = devices if devices is not None else jax.devices()
    names = list(axis_shapes)
    sizes = list(axis_shapes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(onp.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(onp.prod(sizes))
    if total != n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n}")
    arr = onp.asarray(devices[:total]).reshape(sizes)
    return jax.sharding.Mesh(arr, names)


class mesh_scope:
    """Context manager installing a mesh as the active one."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _STATE.stack.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _STATE.stack.pop()
        return False


def current_mesh():
    return _STATE.stack[-1] if _STATE.stack else None
