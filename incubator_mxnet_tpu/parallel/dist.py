"""Multi-host (multi-process) collectives over DCN.

The reference's distributed backend is ps-lite: a scheduler plus server and
worker processes wired by `tools/launch.py` env vars (`DMLC_ROLE`,
`DMLC_PS_ROOT_URI`, ... — `src/kvstore/kvstore_dist.h:266`,
`kvstore_dist_server.h:157`). The TPU-native replacement is the jax
multi-process runtime: `jax.distributed.initialize` is the rendezvous
(≈ scheduler), and reductions are XLA collectives over the global device
mesh (ICI within a slice, DCN/gloo across hosts) — there are no server
processes because allreduce subsumes the push/pull round trip.

`allreduce` here is the facade used by `KVStoreDist` for arrays that live
outside a pjit'ed train step: each process contributes its host-local
value as one shard of a global array along a 'host' axis, and a tiny jit
program sums over that axis with replicated output.
"""
from __future__ import annotations

import logging
import os

__all__ = ["initialize", "is_initialized", "rank", "num_processes",
           "allreduce", "broadcast", "barrier"]

_LOG = logging.getLogger("incubator_mxnet_tpu.parallel.dist")

_STATE = {"initialized": False, "mesh": None, "reducers": {}}


def _transient_rendezvous(exc):
    """Retryable filter for the rendezvous policy: injected faults and
    connection/timeout-shaped transport errors only — a double-init
    RuntimeError is a STATE, not a fault, and must surface immediately."""
    from ..fault.injection import FaultInjected

    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(exc).lower()
    return isinstance(exc, RuntimeError) and any(
        s in msg for s in ("unavailable", "deadline", "timed out",
                           "timeout", "connect", "refused", "unreachable"))


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-process runtime (idempotent).

    Env fallbacks accept both jax-style names (what `tools/launch.py` sets)
    and the reference's DMLC names so launch scripts written for the
    reference keep working: COORDINATOR_ADDRESS | DMLC_PS_ROOT_URI:PORT,
    NUM_PROCESSES | DMLC_NUM_WORKER, PROCESS_ID | DMLC_RANK.
    """
    if _STATE["initialized"]:
        return
    coordinator_address = coordinator_address or _env("COORDINATOR_ADDRESS")
    if coordinator_address is None:
        uri = _env("DMLC_PS_ROOT_URI")
        port = _env("DMLC_PS_ROOT_PORT", default="9000")
        if uri is not None:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        v = _env("NUM_PROCESSES", "DMLC_NUM_WORKER")
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = _env("PROCESS_ID", "DMLC_RANK")
        process_id = int(v) if v is not None else None
    if coordinator_address is None:
        return  # single-process: nothing to join
    import jax

    from ..fault import injection
    from ..fault.retry import RetryExhausted, RetryPolicy

    def _join():
        injection.inject_at("dist_init")      # chaos seam
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    try:
        # rendezvous is the flakiest moment of a multi-host launch (peers
        # race the coordinator's bind): retry TRANSPORT failures with
        # backoff, but never a double-init complaint — that must fall
        # through to the already-up classification below
        RetryPolicy.from_env("dist_init",
                             retryable=_transient_rendezvous).call(_join)
    except RuntimeError as e:
        # Recoverable: the runtime is already up (double-init — jax raises
        # "...should only be called once", or the backend reports multiple
        # processes). Anything else (coordinator unreachable, rendezvous
        # timeout — including after the retry budget) must FAIL LOUDLY
        # when a coordinator was configured — degrading to
        # process_count()==1 would silently train with unreduced
        # gradients. Explicit num_processes==1 is the only single-process
        # escape hatch.
        last = e.last if isinstance(e, RetryExhausted) else e
        msg = str(last).lower()
        already_up = ("already" in msg or "only be called once" in msg
                      or jax.process_count() > 1)
        if not already_up:
            if num_processes == 1:
                _LOG.warning(
                    "dist.initialize: rendezvous failed but "
                    "num_processes=1 — continuing single-process: %s", last)
                return
            _LOG.error(
                "dist.initialize: rendezvous failed FATALLY (coordinator "
                "%s, num_processes=%s): %s", coordinator_address,
                num_processes, last)
            raise RuntimeError(
                f"jax.distributed.initialize failed (coordinator "
                f"{coordinator_address}, num_processes={num_processes}): "
                f"{last}") from e
        _LOG.info("dist.initialize: runtime already up (%s) — reusing it",
                  type(last).__name__)
    _STATE["initialized"] = True


def is_initialized():
    return _STATE["initialized"]


def rank():
    import jax

    return jax.process_index()


def num_processes():
    import jax

    return jax.process_count()


def _host_mesh():
    """Global 1-axis-per-scope mesh: ('host', 'local') over every device."""
    if _STATE["mesh"] is None:
        import jax
        import numpy as onp

        devs = onp.array(jax.devices()).reshape(jax.process_count(), -1)
        _STATE["mesh"] = jax.sharding.Mesh(devs, ("host", "local"))
    return _STATE["mesh"]


def _reducer(op):
    if op not in _STATE["reducers"]:
        import jax

        mesh = _host_mesh()
        repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        fn = {"sum": lambda x: x.sum(axis=0),
              "max": lambda x: x.max(axis=0)}[op]
        from ..telemetry.compiles import ledgered_jit

        _STATE["reducers"][op] = ledgered_jit(
            fn, family=f"dist.reduce_{op}", out_shardings=repl)
    return _STATE["reducers"][op]


def allreduce(x, op="sum"):
    """Reduce a host-local array across all processes; every process gets
    the full result. Single-process: returns x unchanged."""
    import jax
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(x)
    mesh = _host_mesh()
    P = jax.sharding.PartitionSpec
    sh = jax.sharding.NamedSharding(mesh, P(("host", "local")))
    x = jnp.asarray(x)
    local = jax.local_devices()
    if op in ("sum", "mean"):
        # the host's value rides on local device 0; zeros elsewhere, so the
        # row-sum counts each host exactly once (dtype-preserving)
        zero = jnp.zeros_like(x)[None]
        shards = [jax.device_put(x[None] if i == 0 else zero, d)
                  for i, d in enumerate(local)]
        red = "sum"
    else:
        shards = [jax.device_put(x[None], d) for d in local]
        red = op
    ga = jax.make_array_from_single_device_arrays(
        (jax.device_count(),) + x.shape, sh, shards)
    out = _reducer(red)(ga)
    out = jnp.asarray(out.addressable_data(0))
    if op == "mean":
        out = out / jax.process_count()
    return out


def broadcast(x, root=0):
    """Send root's host-local array to every process."""
    import jax
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    contrib = x if jax.process_index() == root else jnp.zeros_like(x)
    return allreduce(contrib, op="sum")


def barrier(tag="barrier"):
    import jax

    if jax.process_count() > 1:
        allreduce(jax.numpy.zeros((1,), "float32")).block_until_ready()


_EXCHANGE_OVERSIZE = "__exchange_objs_oversize__"


def exchange_objs(obj, max_bytes=4096):
    """Collectively exchange one small picklable object per process;
    returns the list of every rank's object (index = rank). Rides the
    same allreduce transport as the data path — each rank fills ITS slot
    of a (P, max_bytes) byte matrix, the sum concatenates them. The
    command channel for remote-process profiler control (reference:
    `KVStoreServerProfilerCommand`, `include/mxnet/kvstore.h:48` —
    commands ride ps-lite messages there, collectives here)."""
    import pickle

    import numpy as onp

    import jax
    import jax.numpy as jnp

    if not is_initialized() or jax.process_count() == 1:
        return [obj]
    payload = pickle.dumps(obj)
    oversize = len(payload) > max_bytes - 4
    if oversize:
        # raising BEFORE the collective would leave peers blocked in the
        # allreduce (distributed hang); ship a small error marker instead
        # and raise on EVERY rank after the exchange completes
        payload = pickle.dumps(_EXCHANGE_OVERSIZE)
    P = jax.process_count()
    me = jax.process_index()
    mat = onp.zeros((P, max_bytes), "uint8")
    mat[me, :4] = onp.frombuffer(len(payload).to_bytes(4, "little"),
                                 "uint8")
    mat[me, 4:4 + len(payload)] = onp.frombuffer(payload, "uint8")
    # disjoint slots: the element-wise sum reassembles each rank's row
    # verbatim (jnp promotes uint8 sums to uint32 — cast back for tobytes)
    summed = onp.asarray(allreduce(jnp.asarray(mat),
                                   op="sum")).astype("uint8")
    out = []
    for r in range(P):
        n = int.from_bytes(summed[r, :4].tobytes(), "little")
        out.append(pickle.loads(summed[r, 4:4 + n].tobytes())
                   if n else None)
    if any(o == _EXCHANGE_OVERSIZE for o in out):
        raise ValueError(
            f"exchange_objs: a rank's object exceeded the {max_bytes}-byte "
            "command slot (all ranks raised after the collective)")
    return out
