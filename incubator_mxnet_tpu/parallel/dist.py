"""Multi-host (multi-process) collectives over DCN.

The reference's distributed backend is ps-lite: a scheduler plus server and
worker processes wired by `tools/launch.py` env vars (`DMLC_ROLE`,
`DMLC_PS_ROOT_URI`, ... — `src/kvstore/kvstore_dist.h:266`,
`kvstore_dist_server.h:157`). The TPU-native replacement is the jax
multi-process runtime: `jax.distributed.initialize` is the rendezvous
(≈ scheduler), and reductions are XLA collectives over the global device
mesh (ICI within a slice, DCN/gloo across hosts) — there are no server
processes because allreduce subsumes the push/pull round trip.

`allreduce` here is the facade used by `KVStoreDist` for arrays that live
outside a pjit'ed train step: each process contributes its host-local
value as one shard of a global array along a 'host' axis, and a tiny jit
program sums over that axis with replicated output.

Transports: the XLA path above is the production one (ICI/DCN). jaxlib
implements cross-process XLA computations only for TPU/GPU backends, so
on a CPU fleet (multi-process tests, `tools/launch.py` dev runs) every
dist op instead rides the **coordination-service host transport**: an
allgather over the rendezvous server's gRPC key-value store
(`key_value_set_bytes` + barriers), reduced host-side. Selection is
automatic (CPU backend, or first XLA "Multiprocess computations aren't
implemented" error); ``MXNET_DIST_TRANSPORT=xla|host`` forces a side.

Membership epochs (elastic topology, see `fault/elastic.py` +
RESILIENCE.md §7): the live world is a *generation-numbered membership*
— ``generation()`` counts epoch transitions and ``active_ranks()`` names
the surviving processes. A topology change (preemption, crash marker,
injected ``topology_change`` seam) re-rendezvouses via
:func:`rendezvous`: survivors post join keys under the NEXT generation's
KV prefix, poll the roster until it settles, and commit over a
subset barrier. Every collective takes a ``generation=`` kwarg; a rank
holding a superseded generation (or one that already left) raises
:class:`StaleGenerationError` — classified NON-retryable — *before*
entering the transport, so a stale rank fails loudly instead of
deadlocking the survivors' collective (lint FL015 keeps in-tree
fault/parallel call sites threading the guard). Subset collectives ride
the host transport only: the XLA global-array path needs every process's
devices, which is exactly what a shrunk membership no longer has.
"""
from __future__ import annotations

import logging
import os
import threading

__all__ = ["initialize", "is_initialized", "rank", "num_processes",
           "allreduce", "broadcast", "barrier", "exchange_objs",
           "generation", "active_ranks", "world_size", "is_active",
           "check_generation", "rendezvous", "pending_departures",
           "pending_rejoins", "StaleGenerationError"]

_LOG = logging.getLogger("incubator_mxnet_tpu.parallel.dist")

_STATE = {"initialized": False, "mesh": None, "reducers": {},
          "transport": None,     # None=undecided, "xla" | "host"
          "host_seq": 0,
          "generation": 0,       # membership epoch counter
          "members": None}       # None = every process; tuple after shrink
_HOST_SEQ_LOCK = threading.Lock()
_HOST_TIMEOUT_MS = 120_000


class StaleGenerationError(RuntimeError):
    """A collective was entered under a membership generation that has
    been superseded (or by a rank no longer in the membership). The rank
    missed an epoch transition: its peers have re-rendezvoused and will
    never show up for this collective, so blocking would deadlock —
    fail loudly instead. NON-retryable by classification
    (`fault.retry.classify_exception` honors ``non_retryable``): a retry
    replays the same stale view."""

    non_retryable = True

    def __init__(self, held, current, why="generation superseded"):
        super().__init__(
            f"dist: stale membership — {why} (held generation {held}, "
            f"current {current}); the fleet re-rendezvoused without this "
            "rank. Re-join via dist.rendezvous() or exit cleanly.")
        self.held = held
        self.current = current


def _transient_rendezvous(exc):
    """Retryable filter for the rendezvous policy: injected faults and
    connection/timeout-shaped transport errors only — a double-init
    RuntimeError is a STATE, not a fault, and must surface immediately."""
    from ..fault.injection import FaultInjected

    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(exc).lower()
    return isinstance(exc, RuntimeError) and any(
        s in msg for s in ("unavailable", "deadline", "timed out",
                           "timeout", "connect", "refused", "unreachable"))


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-process runtime (idempotent).

    Env fallbacks accept both jax-style names (what `tools/launch.py` sets)
    and the reference's DMLC names so launch scripts written for the
    reference keep working: COORDINATOR_ADDRESS | DMLC_PS_ROOT_URI:PORT,
    NUM_PROCESSES | DMLC_NUM_WORKER, PROCESS_ID | DMLC_RANK.
    """
    if _STATE["initialized"]:
        return
    coordinator_address = coordinator_address or _env("COORDINATOR_ADDRESS")
    if coordinator_address is None:
        uri = _env("DMLC_PS_ROOT_URI")
        port = _env("DMLC_PS_ROOT_PORT", default="9000")
        if uri is not None:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        v = _env("NUM_PROCESSES", "DMLC_NUM_WORKER")
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = _env("PROCESS_ID", "DMLC_RANK")
        process_id = int(v) if v is not None else None
    if coordinator_address is None:
        return  # single-process: nothing to join
    import jax

    from ..fault import injection
    from ..fault.retry import RetryExhausted, RetryPolicy

    def _join():
        injection.inject_at("dist_init")      # chaos seam
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    try:
        # rendezvous is the flakiest moment of a multi-host launch (peers
        # race the coordinator's bind): retry TRANSPORT failures with
        # backoff, but never a double-init complaint — that must fall
        # through to the already-up classification below
        RetryPolicy.from_env("dist_init",
                             retryable=_transient_rendezvous).call(_join)
    except RuntimeError as e:
        # Recoverable: the runtime is already up (double-init — jax raises
        # "...should only be called once", or the backend reports multiple
        # processes). Anything else (coordinator unreachable, rendezvous
        # timeout — including after the retry budget) must FAIL LOUDLY
        # when a coordinator was configured — degrading to
        # process_count()==1 would silently train with unreduced
        # gradients. Explicit num_processes==1 is the only single-process
        # escape hatch.
        last = e.last if isinstance(e, RetryExhausted) else e
        msg = str(last).lower()
        already_up = ("already" in msg or "only be called once" in msg
                      or jax.process_count() > 1)
        if not already_up:
            if num_processes == 1:
                _LOG.warning(
                    "dist.initialize: rendezvous failed but "
                    "num_processes=1 — continuing single-process: %s", last)
                return
            _LOG.error(
                "dist.initialize: rendezvous failed FATALLY (coordinator "
                "%s, num_processes=%s): %s", coordinator_address,
                num_processes, last)
            raise RuntimeError(
                f"jax.distributed.initialize failed (coordinator "
                f"{coordinator_address}, num_processes={num_processes}): "
                f"{last}") from e
        _LOG.info("dist.initialize: runtime already up (%s) — reusing it",
                  type(last).__name__)
    _STATE["initialized"] = True


def is_initialized():
    return _STATE["initialized"]


def rank():
    import jax

    return jax.process_index()


def num_processes():
    import jax

    return jax.process_count()


def generation():
    """Current membership-epoch number (0 until the first transition)."""
    return _STATE["generation"]


def active_ranks():
    """Ranks in the current membership, sorted. Before any elastic
    transition this is every process."""
    if _STATE["members"] is not None:
        return _STATE["members"]
    return tuple(range(num_processes()))


def world_size():
    """Size of the current membership (== num_processes() until a
    topology change shrinks it)."""
    return len(active_ranks())


def is_active():
    """Is THIS process part of the current membership? False after it
    left via ``rendezvous(leave=True)``."""
    members = _STATE["members"]
    return members is None or rank() in members


def check_generation(generation_, op="collective"):
    """Membership guard every collective runs before touching the
    transport. ``generation_=None`` tolerates legacy callers (the
    membership check still applies); a mismatched generation or a
    departed rank raises :class:`StaleGenerationError` — loudly, before
    a peer could be left blocked waiting for this rank."""
    cur = _STATE["generation"]
    if generation_ is not None and int(generation_) != cur:
        raise StaleGenerationError(int(generation_), cur,
                                   why=f"{op} under a superseded epoch")
    if not is_active():
        raise StaleGenerationError(
            cur if generation_ is None else int(generation_), cur,
            why=f"{op} from a rank outside the membership")


def _host_mesh():
    """Global 1-axis-per-scope mesh: ('host', 'local') over every device."""
    if _STATE["mesh"] is None:
        import jax
        import numpy as onp

        devs = onp.array(jax.devices()).reshape(jax.process_count(), -1)
        _STATE["mesh"] = jax.sharding.Mesh(devs, ("host", "local"))
    return _STATE["mesh"]


def _reducer(op):
    if op not in _STATE["reducers"]:
        import jax

        mesh = _host_mesh()
        repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        fn = {"sum": lambda x: x.sum(axis=0),
              "max": lambda x: x.max(axis=0)}[op]
        from ..telemetry.compiles import ledgered_jit

        _STATE["reducers"][op] = ledgered_jit(
            fn, family=f"dist.reduce_{op}", out_shardings=repl)
    return _STATE["reducers"][op]


def allreduce(x, op="sum", generation=None):
    """Reduce a host-local array across the current membership; every
    surviving process gets the full result. Single-process: returns x
    unchanged. ``generation=`` is the membership-epoch guard
    (:func:`check_generation`): pass ``dist.generation()`` captured at
    step start so a rank that missed an elastic transition fails loudly
    here instead of deadlocking its peers (lint FL015).

    The multi-process path is the choke point every other dist op rides
    (broadcast/barrier/exchange_objs), so it carries the
    ``collective_delay`` chaos seam (`_FAULT_HOOK`, armed by
    `fault.injection`) and the fleet profiler (`_PROF`, armed by
    `telemetry.fleet.enable()`) — both module-global is-None dead
    branches when off."""
    import jax
    import jax.numpy as jnp

    check_generation(generation, op="allreduce")
    fh = _FAULT_HOOK
    if fh is not None:
        fh()          # fires single-process too: deterministic chaos units
    if jax.process_count() == 1:
        return jnp.asarray(x)
    prof = _PROF
    if prof is None:
        return _allreduce_any(x, op)
    x = jnp.asarray(x)
    with prof.dist_op("allreduce", x.size * x.dtype.itemsize, red=op):
        return _allreduce_any(x, op)


def _use_host_transport():
    forced = os.environ.get("MXNET_DIST_TRANSPORT")
    if forced in ("host", "xla"):
        return forced == "host"
    if _STATE["transport"] is not None:
        return _STATE["transport"] == "host"
    import jax

    # jaxlib's CPU backend has no cross-process computations at all —
    # decide proactively instead of paying a failed compile per call
    host = jax.devices()[0].platform == "cpu"
    _STATE["transport"] = "host" if host else "xla"
    if host:
        _LOG.info("dist: CPU backend — collectives ride the "
                  "coordination-service host transport")
    return host


def _is_no_multiprocess_backend(e):
    return "multiprocess computations aren't implemented" in str(e).lower()


def _allreduce_any(x, op):
    if _STATE["members"] is not None and _use_host_transport() is False:
        # a shrunk membership can't ride the XLA global-array path: it
        # builds arrays over EVERY process's devices, and the departed
        # ranks' devices are exactly what the fleet no longer has
        _LOG.warning("dist: membership is a subset (%s) — forcing the "
                     "coordination-service host transport",
                     _STATE["members"])
        _STATE["transport"] = "host"
    if _use_host_transport():
        return _host_allreduce(x, op)
    try:
        return _allreduce_impl(x, op)
    except Exception as e:
        if not _is_no_multiprocess_backend(e):
            raise
        _LOG.warning(
            "dist.allreduce: XLA cross-process collectives unavailable on "
            "this backend (%s) — falling back to the coordination-service "
            "host transport", e)
        _STATE["transport"] = "host"
        return _host_allreduce(x, op)


def _coord_client():
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "dist: coordination-service client unavailable — initialize() "
            "must join the multi-process runtime before host-transport "
            "collectives")
    return client


_ELASTIC_PFX = "mx/elastic"


def _fleet_generation(client):
    """Highest membership generation any rank has committed to the
    coordination service (None when no transition happened / the
    service lacks directory reads). Non-blocking: ``key_value_dir_get``
    returns immediately with whatever exists."""
    try:
        entries = client.key_value_dir_get(f"{_ELASTIC_PFX}/commit/")
    except Exception as e:
        from ..fault.retry import suppressed

        suppressed("dist._fleet_generation", e)
        return None
    gens = []
    for k, _v in entries:
        tail = str(k).rsplit("/", 1)[-1]
        if tail.startswith("g"):
            try:
                gens.append(int(tail[1:]))
            except ValueError:
                pass
    return max(gens) if gens else None


def _subset_barrier(client, barrier_id, timeout_ms=None):
    """Coordination-service barrier over the CURRENT membership only —
    a shrunk fleet must not wait for ranks that already left."""
    timeout_ms = _HOST_TIMEOUT_MS if timeout_ms is None else timeout_ms
    members = _STATE["members"]
    if members is None:
        client.wait_at_barrier(barrier_id, timeout_ms)
    else:
        client.wait_at_barrier(barrier_id, timeout_ms,
                               process_ids=list(members))


def _host_allgather_bytes(payload, tag):
    """Allgather raw bytes over the rendezvous server's gRPC key-value
    store: each member posts its payload under a per-collective sequence
    key, a barrier orders post→read, and a second barrier keeps deletes
    from racing slower readers. Every member issues collectives in the
    same order, so the local counter agrees fleet-wide; keys carry the
    membership generation so a cross-epoch straggler can never collide.
    Returns one payload per member of ``active_ranks()``, in rank
    order."""
    import jax

    client = _coord_client()
    me = jax.process_index()
    members = active_ranks()
    # a rank that missed an epoch transition would post under a dead
    # prefix and block at a barrier no survivor will ever join — probe
    # the fleet's committed generation and fail loudly instead
    fleet_gen = _fleet_generation(client)
    if fleet_gen is not None and fleet_gen > _STATE["generation"]:
        raise StaleGenerationError(
            _STATE["generation"], fleet_gen,
            why="the fleet committed a newer membership epoch")
    with _HOST_SEQ_LOCK:
        _STATE["host_seq"] += 1
        seq = _STATE["host_seq"]
    pfx = f"mx/hostcoll/g{_STATE['generation']}/{tag}/{seq}"
    key = f"{pfx}/{me:03d}"
    try:
        client.key_value_set_bytes(key, bytes(payload))
    except Exception:
        # a retried collective can collide with its own stale key
        client.key_value_delete(key)
        client.key_value_set_bytes(key, bytes(payload))
    _subset_barrier(client, f"{pfx}/post")
    blobs = [client.blocking_key_value_get_bytes(f"{pfx}/{r:03d}",
                                                 _HOST_TIMEOUT_MS)
             for r in members]
    _subset_barrier(client, f"{pfx}/done")
    client.key_value_delete(key)
    return blobs


def _host_allreduce(x, op):
    import numpy as onp

    import jax.numpy as jnp

    arr = onp.asarray(x)
    blobs = _host_allgather_bytes(arr.tobytes(), "allreduce")
    vals = [onp.frombuffer(b, dtype=arr.dtype).reshape(arr.shape)
            for b in blobs]
    stack = onp.stack(vals)
    if op in ("sum", "mean"):
        # widen integer accumulation (the XLA path's jnp.sum promotes
        # too), then return the input dtype like the jit reducer does
        acc = stack.sum(axis=0, dtype=(arr.dtype if arr.dtype.kind == "f"
                                       else onp.int64))
        if op == "mean":
            out = (acc / len(vals)).astype(
                arr.dtype if arr.dtype.kind == "f" else onp.float32)
        else:
            out = acc.astype(arr.dtype)
    elif op == "max":
        out = stack.max(axis=0)
    else:
        raise ValueError(f"dist.allreduce: unknown op {op!r}")
    return jnp.asarray(out)


def _allreduce_impl(x, op):
    import jax
    import jax.numpy as jnp

    mesh = _host_mesh()
    P = jax.sharding.PartitionSpec
    sh = jax.sharding.NamedSharding(mesh, P(("host", "local")))
    x = jnp.asarray(x)
    local = jax.local_devices()
    if op in ("sum", "mean"):
        # the host's value rides on local device 0; zeros elsewhere, so the
        # row-sum counts each host exactly once (dtype-preserving)
        zero = jnp.zeros_like(x)[None]
        shards = [jax.device_put(x[None] if i == 0 else zero, d)
                  for i, d in enumerate(local)]
        red = "sum"
    else:
        shards = [jax.device_put(x[None], d) for d in local]
        red = op
    ga = jax.make_array_from_single_device_arrays(
        (jax.device_count(),) + x.shape, sh, shards)
    out = _reducer(red)(ga)
    out = jnp.asarray(out.addressable_data(0))
    if op == "mean":
        out = out / jax.process_count()
    return out


def broadcast(x, root=0, generation=None):
    """Send root's host-local array to every member process."""
    import jax
    import jax.numpy as jnp

    check_generation(generation, op="broadcast")
    if jax.process_count() == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    prof = _PROF
    if prof is None:
        return _broadcast_impl(x, root)
    with prof.dist_op("broadcast", x.size * x.dtype.itemsize, root=root):
        return _broadcast_impl(x, root)


def _broadcast_impl(x, root):
    import jax
    import jax.numpy as jnp

    contrib = x if jax.process_index() == root else jnp.zeros_like(x)
    return allreduce(contrib, op="sum")


def barrier(tag="barrier", generation=None):
    import jax

    check_generation(generation, op="barrier")
    if jax.process_count() > 1:
        prof = _PROF
        if prof is None:
            _barrier_impl()
        else:
            # fleet wraps the barrier in a coll_seq-stamped span and
            # (sampled) exchanges arrival timestamps — the straggler
            # signal (see telemetry/fleet.py)
            prof.barrier_probe(tag, _barrier_impl)


def _barrier_impl():
    import jax

    allreduce(jax.numpy.zeros((1,), "float32")).block_until_ready()


_EXCHANGE_OVERSIZE = "__exchange_objs_oversize__"


def exchange_objs(obj, max_bytes=4096, generation=None):
    """Collectively exchange one small picklable object per member;
    returns the list of every rank's object (index = rank; ``None`` at
    ranks outside the membership). Rides the same allreduce transport as
    the data path — each rank fills ITS slot of a (P, max_bytes) byte
    matrix, the sum concatenates them. The command channel for
    remote-process profiler control (reference:
    `KVStoreServerProfilerCommand`, `include/mxnet/kvstore.h:48` —
    commands ride ps-lite messages there, collectives here)."""
    import jax

    check_generation(generation, op="exchange_objs")
    if not is_initialized() or jax.process_count() == 1:
        return [obj]
    prof = _PROF
    if prof is None:
        return _exchange_objs_impl(obj, max_bytes)
    with prof.dist_op("exchange_objs",
                      jax.process_count() * max_bytes):
        return _exchange_objs_impl(obj, max_bytes)


def _exchange_objs_impl(obj, max_bytes):
    import pickle

    import numpy as onp

    import jax
    import jax.numpy as jnp

    payload = pickle.dumps(obj)
    oversize = len(payload) > max_bytes - 4
    if oversize:
        # raising BEFORE the collective would leave peers blocked in the
        # allreduce (distributed hang); ship a small error marker instead
        # and raise on EVERY rank after the exchange completes
        payload = pickle.dumps(_EXCHANGE_OVERSIZE)
    P = jax.process_count()
    me = jax.process_index()
    mat = onp.zeros((P, max_bytes), "uint8")
    mat[me, :4] = onp.frombuffer(len(payload).to_bytes(4, "little"),
                                 "uint8")
    mat[me, 4:4 + len(payload)] = onp.frombuffer(payload, "uint8")
    # disjoint slots: the element-wise sum reassembles each rank's row
    # verbatim (jnp promotes uint8 sums to uint32 — cast back for tobytes)
    summed = onp.asarray(allreduce(jnp.asarray(mat),
                                   op="sum")).astype("uint8")
    out = []
    for r in range(P):
        n = int.from_bytes(summed[r, :4].tobytes(), "little")
        out.append(pickle.loads(summed[r, 4:4 + n].tobytes())
                   if n else None)
    if any(o == _EXCHANGE_OVERSIZE for o in out):
        raise ValueError(
            f"exchange_objs: a rank's object exceeded the {max_bytes}-byte "
            "command slot (all ranks raised after the collective)")
    return out


def rendezvous(min_ranks=1, timeout_s=None, settle_s=None, leave=False):
    """Membership-epoch re-rendezvous: agree on the surviving world after
    a topology change and bump :func:`generation`.

    Survivors post join keys under the NEXT generation's KV prefix
    (``mx/elastic/g<N>/join/<rank>``), poll the roster via directory
    reads until it is STABLE for ``settle_s`` (and ≥ ``min_ranks``),
    then align the commit with a subset barrier over exactly the settled
    roster — rosters that disagree time out there and the whole attempt
    retries under the ``elastic_rendezvous`` policy (``MXNET_RETRY_*``).
    A committed generation is also recorded fleet-wide so a rank that
    missed the transition fails with :class:`StaleGenerationError` at
    its next collective instead of hanging it.

    ``leave=True`` is the departing side: post nothing, mark the local
    membership stale (any later collective raises), return immediately —
    the survivors' roster settles without us. Single-process runs turn
    the epoch over in place (the in-process chaos tests drive the same
    state machine).

    The reverse direction is automatic: a rank that previously left
    (``not is_active()``) calling ``rendezvous(leave=False)`` is a
    RE-ADMISSION — it adopts the fleet's committed generation (so its
    next epoch lands after every transition it missed), clears its stale
    departure marker, and posts a ``mx/elastic/rejoin/<rank>`` marker so
    survivors discover the grow via :func:`pending_rejoins` and meet it
    at the wider roster's commit barrier.

    Returns ``(generation, members)``.
    """
    import time

    import jax

    if timeout_s is None:
        timeout_s = float(os.environ.get("MXNET_ELASTIC_DRAIN_S", "20"))
    if settle_s is None:
        settle_s = min(0.5, max(0.05, timeout_s / 8))
    next_gen = _STATE["generation"] + 1
    rejoin = not leave and not is_active()
    if not is_initialized() or jax.process_count() == 1:
        _STATE["generation"] = next_gen
        _STATE["members"] = () if leave else None
        if rejoin:
            _count_readmission()
        return next_gen, (() if leave else active_ranks())
    client = _coord_client()
    me = jax.process_index()
    if rejoin:
        from ..fault.retry import suppressed as _sup

        fleet_gen = _fleet_generation(client)
        if fleet_gen is not None:
            next_gen = max(next_gen, int(fleet_gen) + 1)
        try:
            # the departure marker is ours to retract — survivors must
            # stop seeing this rank as a pending shrink
            client.key_value_delete(f"{_ELASTIC_PFX}/leave/{me:03d}")
        except Exception as e:
            _sup("dist.rendezvous.clear_leave", e)
        try:
            client.key_value_set_bytes(f"{_ELASTIC_PFX}/rejoin/{me:03d}",
                                       b"1")
        except Exception as e:
            _sup("dist.rendezvous.rejoin_marker", e)
        _LOG.info("dist.rendezvous: rank %d re-admitting at generation %d",
                  me, next_gen)
    pfx = f"{_ELASTIC_PFX}/g{next_gen}"
    if leave:
        from ..fault.retry import suppressed as _suppressed

        try:
            # departure marker: survivors whose trigger did not fire
            # locally (an @rank-targeted seam, a preemption notice only
            # this host saw) discover the shrink via pending_departures()
            client.key_value_set_bytes(f"{_ELASTIC_PFX}/leave/{me:03d}",
                                       b"1")
        except Exception as e:
            _suppressed("dist.rendezvous.leave_marker", e)
        _STATE["generation"] = next_gen
        _STATE["members"] = ()
        _LOG.info("dist.rendezvous: rank %d leaving at generation %d",
                  me, next_gen)
        return next_gen, ()

    from ..fault.retry import RetryPolicy, suppressed
    from ..telemetry import tracing

    def _attempt():
        key = f"{pfx}/join/{me:03d}"
        try:
            client.key_value_set_bytes(key, b"1")
        except Exception:
            # a retried attempt collides with its own earlier join key
            client.key_value_delete(key)
            client.key_value_set_bytes(key, b"1")
        deadline = time.monotonic() + timeout_s
        roster, stable_since = None, None
        while True:
            try:
                entries = client.key_value_dir_get(f"{pfx}/join/")
            except Exception as e:
                suppressed("dist.rendezvous.dir_get", e)
                entries = []
            ranks = set()
            for k, _v in entries:
                try:
                    ranks.add(int(str(k).rsplit("/", 1)[-1]))
                except ValueError:
                    pass
            ranks = tuple(sorted(ranks))
            now = time.monotonic()
            if ranks != roster:
                roster, stable_since = ranks, now
            elif (len(roster) >= max(1, int(min_ranks))
                  and now - stable_since >= settle_s):
                break
            if now >= deadline:
                raise TimeoutError(
                    f"dist.rendezvous: generation {next_gen} roster did "
                    f"not settle within {timeout_s}s (last seen {roster}"
                    f", min_ranks={min_ranks})")
            time.sleep(0.02)
        # commit alignment over exactly the settled roster: a rank that
        # settled on a DIFFERENT roster times out here, and the retry
        # policy re-runs the whole attempt from the join post
        client.wait_at_barrier(f"{pfx}/commit",
                               int(max(1.0, timeout_s) * 1000),
                               process_ids=list(roster))
        return roster

    with tracing.span("elastic.rendezvous", generation=next_gen):
        roster = RetryPolicy.from_env(
            "elastic_rendezvous",
            retryable=_transient_rendezvous).call(_attempt)
    _STATE["generation"] = next_gen
    _STATE["members"] = roster
    try:
        client.key_value_set_bytes(f"{_ELASTIC_PFX}/commit/g{next_gen}",
                                   b"1")
    except Exception as e:
        suppressed("dist.rendezvous.commit", e)   # peers raced the marker
    if rejoin:
        try:
            client.key_value_delete(f"{_ELASTIC_PFX}/rejoin/{me:03d}")
        except Exception as e:
            suppressed("dist.rendezvous.clear_rejoin", e)
        _count_readmission()
    _LOG.info("dist.rendezvous: generation %d committed, members=%s",
              next_gen, roster)
    return next_gen, roster


def pending_departures():
    """Ranks that posted a departure marker but are still in the active
    membership — the survivor-side trigger for an elastic transition
    whose cause (an ``@rank``-targeted fault, a single-host preemption
    notice) fired somewhere else. Returns a sorted tuple; empty when not
    multi-process or nothing is pending."""
    import jax

    if not is_initialized() or jax.process_count() == 1:
        return ()
    from ..fault.retry import suppressed

    try:
        entries = _coord_client().key_value_dir_get(f"{_ELASTIC_PFX}/leave/")
    except Exception as e:
        suppressed("dist.pending_departures", e)
        return ()
    gone = set()
    for k, _v in entries:
        try:
            gone.add(int(str(k).rsplit("/", 1)[-1]))
        except ValueError:
            pass
    return tuple(sorted(gone & set(active_ranks())))


def pending_rejoins():
    """Ranks that posted a re-admission marker but are not yet in the
    active membership — the survivor-side trigger for a GROW-direction
    elastic transition (`fault/elastic.ElasticController` turns it into
    ``transition(grow=...)``, the reverse of :func:`pending_departures`).
    Returns a sorted tuple; empty when not multi-process or nothing is
    pending."""
    import jax

    if not is_initialized() or jax.process_count() == 1:
        return ()
    from ..fault.retry import suppressed

    try:
        entries = _coord_client().key_value_dir_get(
            f"{_ELASTIC_PFX}/rejoin/")
    except Exception as e:
        suppressed("dist.pending_rejoins", e)
        return ()
    back = set()
    for k, _v in entries:
        try:
            back.add(int(str(k).rsplit("/", 1)[-1]))
        except ValueError:
            pass
    return tuple(sorted(back - set(active_ranks())))


def _count_readmission():
    from ..telemetry import registry

    registry.counter(
        "mx_elastic_readmissions_total",
        "ranks re-admitted into a larger membership at a later epoch "
        "(the grow direction of an elastic transition)").inc()


def _reset_membership():
    """Test hook: restore the pristine epoch-0 full membership."""
    _STATE["generation"] = 0
    _STATE["members"] = None


# hot hooks (module-global is-None dead branches, re-armed on import so
# arming order vs import order doesn't matter):
_FAULT_HOOK = None   # fault.injection._arm_hot_hooks: collective_delay seam
_PROF = None         # telemetry.fleet.enable(): collective profiler


def _rearm_hooks():
    import sys

    pkg = __name__.rsplit(".", 2)[0]
    inj = sys.modules.get(pkg + ".fault.injection")
    if inj is not None:
        inj._arm_hot_hooks()
    fleet = sys.modules.get(pkg + ".telemetry.fleet")
    if fleet is not None and fleet.is_enabled():
        fleet._arm()


_rearm_hooks()
