"""Multi-host (multi-process) collectives over DCN.

The reference's distributed backend is ps-lite: a scheduler plus server and
worker processes wired by `tools/launch.py` env vars (`DMLC_ROLE`,
`DMLC_PS_ROOT_URI`, ... — `src/kvstore/kvstore_dist.h:266`,
`kvstore_dist_server.h:157`). The TPU-native replacement is the jax
multi-process runtime: `jax.distributed.initialize` is the rendezvous
(≈ scheduler), and reductions are XLA collectives over the global device
mesh (ICI within a slice, DCN/gloo across hosts) — there are no server
processes because allreduce subsumes the push/pull round trip.

`allreduce` here is the facade used by `KVStoreDist` for arrays that live
outside a pjit'ed train step: each process contributes its host-local
value as one shard of a global array along a 'host' axis, and a tiny jit
program sums over that axis with replicated output.

Transports: the XLA path above is the production one (ICI/DCN). jaxlib
implements cross-process XLA computations only for TPU/GPU backends, so
on a CPU fleet (multi-process tests, `tools/launch.py` dev runs) every
dist op instead rides the **coordination-service host transport**: an
allgather over the rendezvous server's gRPC key-value store
(`key_value_set_bytes` + barriers), reduced host-side. Selection is
automatic (CPU backend, or first XLA "Multiprocess computations aren't
implemented" error); ``MXNET_DIST_TRANSPORT=xla|host`` forces a side.
"""
from __future__ import annotations

import logging
import os
import threading

__all__ = ["initialize", "is_initialized", "rank", "num_processes",
           "allreduce", "broadcast", "barrier"]

_LOG = logging.getLogger("incubator_mxnet_tpu.parallel.dist")

_STATE = {"initialized": False, "mesh": None, "reducers": {},
          "transport": None,     # None=undecided, "xla" | "host"
          "host_seq": 0}
_HOST_SEQ_LOCK = threading.Lock()
_HOST_TIMEOUT_MS = 120_000


def _transient_rendezvous(exc):
    """Retryable filter for the rendezvous policy: injected faults and
    connection/timeout-shaped transport errors only — a double-init
    RuntimeError is a STATE, not a fault, and must surface immediately."""
    from ..fault.injection import FaultInjected

    if isinstance(exc, FaultInjected):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, OSError)):
        return True
    msg = str(exc).lower()
    return isinstance(exc, RuntimeError) and any(
        s in msg for s in ("unavailable", "deadline", "timed out",
                           "timeout", "connect", "refused", "unreachable"))


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the multi-process runtime (idempotent).

    Env fallbacks accept both jax-style names (what `tools/launch.py` sets)
    and the reference's DMLC names so launch scripts written for the
    reference keep working: COORDINATOR_ADDRESS | DMLC_PS_ROOT_URI:PORT,
    NUM_PROCESSES | DMLC_NUM_WORKER, PROCESS_ID | DMLC_RANK.
    """
    if _STATE["initialized"]:
        return
    coordinator_address = coordinator_address or _env("COORDINATOR_ADDRESS")
    if coordinator_address is None:
        uri = _env("DMLC_PS_ROOT_URI")
        port = _env("DMLC_PS_ROOT_PORT", default="9000")
        if uri is not None:
            coordinator_address = f"{uri}:{port}"
    if num_processes is None:
        v = _env("NUM_PROCESSES", "DMLC_NUM_WORKER")
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = _env("PROCESS_ID", "DMLC_RANK")
        process_id = int(v) if v is not None else None
    if coordinator_address is None:
        return  # single-process: nothing to join
    import jax

    from ..fault import injection
    from ..fault.retry import RetryExhausted, RetryPolicy

    def _join():
        injection.inject_at("dist_init")      # chaos seam
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)

    try:
        # rendezvous is the flakiest moment of a multi-host launch (peers
        # race the coordinator's bind): retry TRANSPORT failures with
        # backoff, but never a double-init complaint — that must fall
        # through to the already-up classification below
        RetryPolicy.from_env("dist_init",
                             retryable=_transient_rendezvous).call(_join)
    except RuntimeError as e:
        # Recoverable: the runtime is already up (double-init — jax raises
        # "...should only be called once", or the backend reports multiple
        # processes). Anything else (coordinator unreachable, rendezvous
        # timeout — including after the retry budget) must FAIL LOUDLY
        # when a coordinator was configured — degrading to
        # process_count()==1 would silently train with unreduced
        # gradients. Explicit num_processes==1 is the only single-process
        # escape hatch.
        last = e.last if isinstance(e, RetryExhausted) else e
        msg = str(last).lower()
        already_up = ("already" in msg or "only be called once" in msg
                      or jax.process_count() > 1)
        if not already_up:
            if num_processes == 1:
                _LOG.warning(
                    "dist.initialize: rendezvous failed but "
                    "num_processes=1 — continuing single-process: %s", last)
                return
            _LOG.error(
                "dist.initialize: rendezvous failed FATALLY (coordinator "
                "%s, num_processes=%s): %s", coordinator_address,
                num_processes, last)
            raise RuntimeError(
                f"jax.distributed.initialize failed (coordinator "
                f"{coordinator_address}, num_processes={num_processes}): "
                f"{last}") from e
        _LOG.info("dist.initialize: runtime already up (%s) — reusing it",
                  type(last).__name__)
    _STATE["initialized"] = True


def is_initialized():
    return _STATE["initialized"]


def rank():
    import jax

    return jax.process_index()


def num_processes():
    import jax

    return jax.process_count()


def _host_mesh():
    """Global 1-axis-per-scope mesh: ('host', 'local') over every device."""
    if _STATE["mesh"] is None:
        import jax
        import numpy as onp

        devs = onp.array(jax.devices()).reshape(jax.process_count(), -1)
        _STATE["mesh"] = jax.sharding.Mesh(devs, ("host", "local"))
    return _STATE["mesh"]


def _reducer(op):
    if op not in _STATE["reducers"]:
        import jax

        mesh = _host_mesh()
        repl = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        fn = {"sum": lambda x: x.sum(axis=0),
              "max": lambda x: x.max(axis=0)}[op]
        from ..telemetry.compiles import ledgered_jit

        _STATE["reducers"][op] = ledgered_jit(
            fn, family=f"dist.reduce_{op}", out_shardings=repl)
    return _STATE["reducers"][op]


def allreduce(x, op="sum"):
    """Reduce a host-local array across all processes; every process gets
    the full result. Single-process: returns x unchanged.

    The multi-process path is the choke point every other dist op rides
    (broadcast/barrier/exchange_objs), so it carries the
    ``collective_delay`` chaos seam (`_FAULT_HOOK`, armed by
    `fault.injection`) and the fleet profiler (`_PROF`, armed by
    `telemetry.fleet.enable()`) — both module-global is-None dead
    branches when off."""
    import jax
    import jax.numpy as jnp

    fh = _FAULT_HOOK
    if fh is not None:
        fh()          # fires single-process too: deterministic chaos units
    if jax.process_count() == 1:
        return jnp.asarray(x)
    prof = _PROF
    if prof is None:
        return _allreduce_any(x, op)
    x = jnp.asarray(x)
    with prof.dist_op("allreduce", x.size * x.dtype.itemsize, red=op):
        return _allreduce_any(x, op)


def _use_host_transport():
    forced = os.environ.get("MXNET_DIST_TRANSPORT")
    if forced in ("host", "xla"):
        return forced == "host"
    if _STATE["transport"] is not None:
        return _STATE["transport"] == "host"
    import jax

    # jaxlib's CPU backend has no cross-process computations at all —
    # decide proactively instead of paying a failed compile per call
    host = jax.devices()[0].platform == "cpu"
    _STATE["transport"] = "host" if host else "xla"
    if host:
        _LOG.info("dist: CPU backend — collectives ride the "
                  "coordination-service host transport")
    return host


def _is_no_multiprocess_backend(e):
    return "multiprocess computations aren't implemented" in str(e).lower()


def _allreduce_any(x, op):
    if _use_host_transport():
        return _host_allreduce(x, op)
    try:
        return _allreduce_impl(x, op)
    except Exception as e:
        if not _is_no_multiprocess_backend(e):
            raise
        _LOG.warning(
            "dist.allreduce: XLA cross-process collectives unavailable on "
            "this backend (%s) — falling back to the coordination-service "
            "host transport", e)
        _STATE["transport"] = "host"
        return _host_allreduce(x, op)


def _coord_client():
    from jax._src import distributed

    client = getattr(distributed.global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "dist: coordination-service client unavailable — initialize() "
            "must join the multi-process runtime before host-transport "
            "collectives")
    return client


def _host_allgather_bytes(payload, tag):
    """Allgather raw bytes over the rendezvous server's gRPC key-value
    store: each rank posts its payload under a per-collective sequence
    key, a barrier orders post→read, and a second barrier keeps deletes
    from racing slower readers. Every rank issues collectives in the
    same order, so the local counter agrees fleet-wide. Returns every
    rank's payload, index = rank."""
    import jax

    client = _coord_client()
    nproc = jax.process_count()
    me = jax.process_index()
    with _HOST_SEQ_LOCK:
        _STATE["host_seq"] += 1
        seq = _STATE["host_seq"]
    pfx = f"mx/hostcoll/{tag}/{seq}"
    key = f"{pfx}/{me:03d}"
    try:
        client.key_value_set_bytes(key, bytes(payload))
    except Exception:
        # a retried collective can collide with its own stale key
        client.key_value_delete(key)
        client.key_value_set_bytes(key, bytes(payload))
    client.wait_at_barrier(f"{pfx}/post", _HOST_TIMEOUT_MS)
    blobs = [client.blocking_key_value_get_bytes(f"{pfx}/{r:03d}",
                                                 _HOST_TIMEOUT_MS)
             for r in range(nproc)]
    client.wait_at_barrier(f"{pfx}/done", _HOST_TIMEOUT_MS)
    client.key_value_delete(key)
    return blobs


def _host_allreduce(x, op):
    import numpy as onp

    import jax.numpy as jnp

    arr = onp.asarray(x)
    blobs = _host_allgather_bytes(arr.tobytes(), "allreduce")
    vals = [onp.frombuffer(b, dtype=arr.dtype).reshape(arr.shape)
            for b in blobs]
    stack = onp.stack(vals)
    if op in ("sum", "mean"):
        # widen integer accumulation (the XLA path's jnp.sum promotes
        # too), then return the input dtype like the jit reducer does
        acc = stack.sum(axis=0, dtype=(arr.dtype if arr.dtype.kind == "f"
                                       else onp.int64))
        if op == "mean":
            out = (acc / len(vals)).astype(
                arr.dtype if arr.dtype.kind == "f" else onp.float32)
        else:
            out = acc.astype(arr.dtype)
    elif op == "max":
        out = stack.max(axis=0)
    else:
        raise ValueError(f"dist.allreduce: unknown op {op!r}")
    return jnp.asarray(out)


def _allreduce_impl(x, op):
    import jax
    import jax.numpy as jnp

    mesh = _host_mesh()
    P = jax.sharding.PartitionSpec
    sh = jax.sharding.NamedSharding(mesh, P(("host", "local")))
    x = jnp.asarray(x)
    local = jax.local_devices()
    if op in ("sum", "mean"):
        # the host's value rides on local device 0; zeros elsewhere, so the
        # row-sum counts each host exactly once (dtype-preserving)
        zero = jnp.zeros_like(x)[None]
        shards = [jax.device_put(x[None] if i == 0 else zero, d)
                  for i, d in enumerate(local)]
        red = "sum"
    else:
        shards = [jax.device_put(x[None], d) for d in local]
        red = op
    ga = jax.make_array_from_single_device_arrays(
        (jax.device_count(),) + x.shape, sh, shards)
    out = _reducer(red)(ga)
    out = jnp.asarray(out.addressable_data(0))
    if op == "mean":
        out = out / jax.process_count()
    return out


def broadcast(x, root=0):
    """Send root's host-local array to every process."""
    import jax
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    prof = _PROF
    if prof is None:
        return _broadcast_impl(x, root)
    with prof.dist_op("broadcast", x.size * x.dtype.itemsize, root=root):
        return _broadcast_impl(x, root)


def _broadcast_impl(x, root):
    import jax
    import jax.numpy as jnp

    contrib = x if jax.process_index() == root else jnp.zeros_like(x)
    return allreduce(contrib, op="sum")


def barrier(tag="barrier"):
    import jax

    if jax.process_count() > 1:
        prof = _PROF
        if prof is None:
            _barrier_impl()
        else:
            # fleet wraps the barrier in a coll_seq-stamped span and
            # (sampled) exchanges arrival timestamps — the straggler
            # signal (see telemetry/fleet.py)
            prof.barrier_probe(tag, _barrier_impl)


def _barrier_impl():
    import jax

    allreduce(jax.numpy.zeros((1,), "float32")).block_until_ready()


_EXCHANGE_OVERSIZE = "__exchange_objs_oversize__"


def exchange_objs(obj, max_bytes=4096):
    """Collectively exchange one small picklable object per process;
    returns the list of every rank's object (index = rank). Rides the
    same allreduce transport as the data path — each rank fills ITS slot
    of a (P, max_bytes) byte matrix, the sum concatenates them. The
    command channel for remote-process profiler control (reference:
    `KVStoreServerProfilerCommand`, `include/mxnet/kvstore.h:48` —
    commands ride ps-lite messages there, collectives here)."""
    import jax

    if not is_initialized() or jax.process_count() == 1:
        return [obj]
    prof = _PROF
    if prof is None:
        return _exchange_objs_impl(obj, max_bytes)
    with prof.dist_op("exchange_objs",
                      jax.process_count() * max_bytes):
        return _exchange_objs_impl(obj, max_bytes)


def _exchange_objs_impl(obj, max_bytes):
    import pickle

    import numpy as onp

    import jax
    import jax.numpy as jnp

    payload = pickle.dumps(obj)
    oversize = len(payload) > max_bytes - 4
    if oversize:
        # raising BEFORE the collective would leave peers blocked in the
        # allreduce (distributed hang); ship a small error marker instead
        # and raise on EVERY rank after the exchange completes
        payload = pickle.dumps(_EXCHANGE_OVERSIZE)
    P = jax.process_count()
    me = jax.process_index()
    mat = onp.zeros((P, max_bytes), "uint8")
    mat[me, :4] = onp.frombuffer(len(payload).to_bytes(4, "little"),
                                 "uint8")
    mat[me, 4:4 + len(payload)] = onp.frombuffer(payload, "uint8")
    # disjoint slots: the element-wise sum reassembles each rank's row
    # verbatim (jnp promotes uint8 sums to uint32 — cast back for tobytes)
    summed = onp.asarray(allreduce(jnp.asarray(mat),
                                   op="sum")).astype("uint8")
    out = []
    for r in range(P):
        n = int.from_bytes(summed[r, :4].tobytes(), "little")
        out.append(pickle.loads(summed[r, 4:4 + n].tobytes())
                   if n else None)
    if any(o == _EXCHANGE_OVERSIZE for o in out):
        raise ValueError(
            f"exchange_objs: a rank's object exceeded the {max_bytes}-byte "
            "command slot (all ranks raised after the collective)")
    return out


# hot hooks (module-global is-None dead branches, re-armed on import so
# arming order vs import order doesn't matter):
_FAULT_HOOK = None   # fault.injection._arm_hot_hooks: collective_delay seam
_PROF = None         # telemetry.fleet.enable(): collective profiler


def _rearm_hooks():
    import sys

    pkg = __name__.rsplit(".", 2)[0]
    inj = sys.modules.get(pkg + ".fault.injection")
    if inj is not None:
        inj._arm_hot_hooks()
    fleet = sys.modules.get(pkg + ".telemetry.fleet")
    if fleet is not None and fleet.is_enabled():
        fleet._arm()


_rearm_hooks()
