"""Mixture-of-Experts with expert parallelism over an `ep` mesh axis.

Reference role: the reference has no MoE implementation (GluonNLP-era
MXNet predates it); this is a capability the TPU build adds because the
sharding machinery makes it natural — experts shard one-per-group over
`ep`, and token dispatch/return ride `lax.all_to_all` on ICI (the
standard Switch/GShard layout, see the public scaling-book recipe).

Design (capacity-factor dispatch, top-1 gating):
- gate: tokens -> expert logits; each token routed to its argmax expert,
  dropped beyond `capacity` per expert (counted with a cumsum rank —
  compiler-friendly, no dynamic shapes).
- dispatch: one-hot combine matrix (tokens × experts × capacity) contracts
  tokens into per-expert slots; `all_to_all` moves slots to the expert's
  device group; experts run their FFN on their own tokens; the return
  all_to_all + combine matrix scatter tokens back (weighted by gate prob).

Everything is einsum/all_to_all — static shapes, MXU contractions.
"""
from __future__ import annotations

__all__ = ["moe_dispatch_combine", "moe_ffn_apply", "top1_gating",
           "top2_gating"]


def top1_gating(logits, capacity):
    """Top-1 gating with capacity: returns (combine, dispatch_mask, aux).

    logits: (T, E). combine: (T, E, C) f32 — gate prob at the token's
    (expert, slot), zero elsewhere. dispatch: same support, 1.0 entries.
    aux: load-balancing loss (mean fraction·prob product, Switch eq. 4).
    """
    import jax
    import jax.numpy as jnp

    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                    # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], 1)[:, 0]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # (T, E)
    # slot rank of each token within its expert (arrival order)
    rank = (jnp.cumsum(onehot, axis=0) - onehot) * onehot  # (T, E)
    kept = (rank < capacity) * onehot                      # within capacity
    slot = jnp.sum(rank * kept, axis=-1).astype(jnp.int32)  # (T,)
    slot_oh = jax.nn.one_hot(slot, capacity,
                             dtype=jnp.float32)            # (T, C)
    dispatch = kept[:, :, None] * slot_oh[:, None, :]      # (T, E, C)
    combine = dispatch * gate[:, None, None]
    # Switch load-balance aux: E * mean_e(frac_tokens_e * mean_prob_e)
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return combine, dispatch, aux


def top2_gating(logits, capacity):
    """Top-2 gating with capacity (GShard §3.2 / Switch appendix): each
    token routes to its two highest-probability experts; gate weights are
    the two probs renormalized over the kept pair. Capacity ranks count
    first-choice tokens before second-choice tokens (first choices are
    dropped last). Returns (combine (T,E,C), dispatch, aux) — aux is the
    Switch load-balance loss computed on FIRST choices."""
    import jax
    import jax.numpy as jnp

    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    e1 = jnp.argmax(probs, axis=-1)                        # (T,)
    oh1 = jax.nn.one_hot(e1, e, dtype=jnp.float32)
    probs2 = probs * (1.0 - oh1)
    e2 = jnp.argmax(probs2, axis=-1)
    oh2 = jax.nn.one_hot(e2, e, dtype=jnp.float32)
    g1 = jnp.take_along_axis(probs, e1[:, None], 1)[:, 0]
    g2 = jnp.take_along_axis(probs, e2[:, None], 1)[:, 0]
    denom = jnp.maximum(g1 + g2, 1e-9)                     # renormalize pair
    g1, g2 = g1 / denom, g2 / denom

    # slot ranks: first choices fill before ANY second choice
    rank1 = (jnp.cumsum(oh1, axis=0) - oh1) * oh1          # (T, E)
    used1 = jnp.sum(oh1, axis=0, keepdims=True)            # (1, E)
    rank2 = ((jnp.cumsum(oh2, axis=0) - oh2) + used1) * oh2
    kept1 = (rank1 < capacity) * oh1
    kept2 = (rank2 < capacity) * oh2

    def to_dispatch(kept, rank):
        slot = jnp.sum(rank * kept, axis=-1).astype(jnp.int32)
        slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        return kept[:, :, None] * slot_oh[:, None, :]      # (T, E, C)

    d1 = to_dispatch(kept1, rank1)
    d2 = to_dispatch(kept2, rank2)
    dispatch = d1 + d2
    combine = d1 * g1[:, None, None] + d2 * g2[:, None, None]
    frac = jnp.mean(oh1, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return combine, dispatch, aux


def moe_dispatch_combine(x, gate_logits, expert_fn, capacity_factor=1.25,
                         axis_name=None, top_k=1):
    """Top-1 MoE layer body: dispatch -> expert_fn -> combine (GShard
    token-sharded layout).

    x: (T_local, D) — this device's token shard (the `ep` axis usually
    coincides with the data axis); gate_logits: (T_local, E).
    expert_fn(slots) with slots (E_local, C_total, D) -> same shape —
    applied AFTER the dispatch all_to_all, so under expert parallelism it
    sees only this device's experts but EVERY device's tokens for them.
    Returns (out (T_local, D), aux_loss).
    """
    import jax.numpy as jnp

    from . import collectives

    t, e = gate_logits.shape
    n_groups = 1 if axis_name is None else collectives.axis_size(axis_name)
    if e % n_groups:
        raise ValueError(f"{e} experts not divisible over {n_groups} "
                         "expert-parallel groups")
    capacity = max(1, int(capacity_factor * top_k * t / e))
    if top_k == 1:
        combine, dispatch, aux = top1_gating(gate_logits, capacity)
    elif top_k == 2:
        combine, dispatch, aux = top2_gating(gate_logits, capacity)
    else:
        raise ValueError(f"top_k must be 1 or 2, got {top_k}")
    # keep the layer's activation dtype: f32 one-hots would upcast bf16
    # tokens and double the all_to_all bytes on ICI
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    # local tokens -> per-expert slots
    slots = jnp.einsum("td,tec->ecd", x, dispatch)         # (E, C, D)
    if axis_name is not None:
        # dispatch: each device keeps slots for ITS experts and receives
        # the matching slots from every peer — expert axis splits G-ways,
        # peers' contributions concatenate along the capacity axis
        slots = collectives.all_to_all(slots, axis_name, split_axis=0,
                                       concat_axis=1, tiled=True)
        # -> (E/G, G*C, D)
    out_slots = expert_fn(slots)
    if axis_name is not None:
        # return: inverse permutation
        out_slots = collectives.all_to_all(out_slots, axis_name,
                                           split_axis=1, concat_axis=0,
                                           tiled=True)
        # -> (E, C, D), rows for OUR tokens back home
    out = jnp.einsum("ecd,tec->td", out_slots, combine)
    return out, aux


def moe_ffn_apply(w1, b1, w2, b2):
    """Per-expert FFN: returns expert_fn for moe_dispatch_combine.
    w1: (E_local, D, H), w2: (E_local, H, D)."""
    import jax
    import jax.numpy as jnp

    def expert_fn(slots):                                  # (E, C, D)
        h = jnp.einsum("ecd,edh->ech", slots, w1) + b1[:, None, :]
        h = jax.nn.gelu(h)
        return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

    return expert_fn
