"""Collective ops over the mesh (replacement for `src/kvstore/comm.h` reduce
trees and NCCL/ps-lite: `psum`/`all_gather`/`ppermute` ride ICI links and XLA
overlaps them with compute — the latency-hiding the reference built P3 for).

These are meant to be called INSIDE a shard_map'ed/pjit'ed function; thin
wrappers around jax.lax so user code never imports jax directly. They are
also the fleet profiler's census point: when `telemetry.fleet` is enabled,
every wrapper reports its op/axis/payload-bytes through the module-global
`_CENSUS` hook (a trace-time count — host wall time inside a traced body
would measure tracing, not execution; `fleet.probe_collectives` owns honest
per-op seconds). Lint FL014 keeps raw `lax` collectives in `parallel/` and
`serve/` routed through here so the census can't be bypassed."""
from __future__ import annotations

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "ring_permute", "all_to_all", "axis_size", "pvary"]


def pvary(x, axis_name):
    """Mark a value device-varying over `axis_name` — shard_map's
    replication-typing escape hatch for loop carries whose body outputs
    are varying (ppermute/axis_index inside). `jax.lax.pvary` where the
    pinned jax has it; otherwise adding a zeroed `axis_index` term gives
    the checker a varying operand and XLA folds the arithmetic away.
    Not a comms op, so no census."""
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    if hasattr(jax.lax, "pvary"):
        out = jax.lax.pvary(v, names)
    else:
        out = v
        for ax in names:
            zero = jax.lax.convert_element_type(
                jax.lax.axis_index(ax) * 0, v.dtype)
            out = out + zero
    return NDArray(out) if isinstance(x, NDArray) else out


def axis_size(axis_name):
    """Static size of a mapped axis (a Python int inside shard_map/pjit).
    `lax.psum` of the literal 1 constant-folds to the axis size — the
    portable spelling (`jax.lax.axis_size` is newer than this build's
    pinned jax). Not a comms op, so no census."""
    import jax

    return jax.lax.psum(1, axis_name)


def all_reduce(x, axis_name, op="sum"):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("all_reduce", axis_name, v)
    if op == "sum":
        out = jax.lax.psum(v, axis_name)
    elif op == "mean":
        out = jax.lax.pmean(v, axis_name)
    elif op == "max":
        out = jax.lax.pmax(v, axis_name)
    elif op == "min":
        out = jax.lax.pmin(v, axis_name)
    else:
        raise ValueError(f"unknown op {op!r}")
    return NDArray(out) if isinstance(x, NDArray) else out


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("all_gather", axis_name, v)
    out = jax.lax.all_gather(v, axis_name, axis=axis, tiled=tiled)
    return NDArray(out) if isinstance(x, NDArray) else out


def reduce_scatter(x, axis_name, axis=0):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("reduce_scatter", axis_name, v)
    out = jax.lax.psum_scatter(v, axis_name, scatter_dimension=axis, tiled=True)
    return NDArray(out) if isinstance(x, NDArray) else out


def broadcast(x, axis_name, src=0):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("broadcast", axis_name, v)
    idx = jax.lax.axis_index(axis_name)
    mask = (idx == src).astype(v.dtype)
    out = jax.lax.psum(v * mask, axis_name)
    return NDArray(out) if isinstance(x, NDArray) else out


def ring_permute(x, axis_name, shift=1):
    """Send each shard to the next device on the ring (the building block of
    ring attention / ring allreduce; rides neighbor ICI links)."""
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("ring_permute", axis_name, v)
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    out = jax.lax.ppermute(v, axis_name, perm)
    return NDArray(out) if isinstance(x, NDArray) else out


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """Expert-parallel dispatch/return primitive: every device scatters
    `split_axis` slices to its peers and concatenates what it receives
    along `concat_axis` (the MoE all-to-all; see `parallel/moe.py`)."""
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    c = _CENSUS
    if c is not None:
        c("all_to_all", axis_name, v)
    out = jax.lax.all_to_all(v, axis_name, split_axis=split_axis,
                             concat_axis=concat_axis, tiled=tiled)
    return NDArray(out) if isinstance(x, NDArray) else out


_CENSUS = None   # armed by telemetry.fleet.enable(): (op, axis, value) hook


def _rearm_hooks():
    import sys

    fleet = sys.modules.get(__name__.rsplit(".", 2)[0] + ".telemetry.fleet")
    if fleet is not None and fleet.is_enabled():
        fleet._arm()


_rearm_hooks()
