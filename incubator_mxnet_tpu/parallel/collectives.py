"""Collective ops over the mesh (replacement for `src/kvstore/comm.h` reduce
trees and NCCL/ps-lite: `psum`/`all_gather`/`ppermute` ride ICI links and XLA
overlaps them with compute — the latency-hiding the reference built P3 for).

These are meant to be called INSIDE a shard_map'ed/pjit'ed function; thin
wrappers around jax.lax so user code never imports jax directly."""
from __future__ import annotations

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "ring_permute"]


def all_reduce(x, axis_name, op="sum"):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    if op == "sum":
        out = jax.lax.psum(v, axis_name)
    elif op == "mean":
        out = jax.lax.pmean(v, axis_name)
    elif op == "max":
        out = jax.lax.pmax(v, axis_name)
    elif op == "min":
        out = jax.lax.pmin(v, axis_name)
    else:
        raise ValueError(f"unknown op {op!r}")
    return NDArray(out) if isinstance(x, NDArray) else out


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    out = jax.lax.all_gather(v, axis_name, axis=axis, tiled=tiled)
    return NDArray(out) if isinstance(x, NDArray) else out


def reduce_scatter(x, axis_name, axis=0):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    out = jax.lax.psum_scatter(v, axis_name, scatter_dimension=axis, tiled=True)
    return NDArray(out) if isinstance(x, NDArray) else out


def broadcast(x, axis_name, src=0):
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    idx = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    mask = (idx == src).astype(v.dtype)
    out = jax.lax.psum(v * mask, axis_name)
    del n
    return NDArray(out) if isinstance(x, NDArray) else out


def ring_permute(x, axis_name, shift=1):
    """Send each shard to the next device on the ring (the building block of
    ring attention / ring allreduce; rides neighbor ICI links)."""
    import jax

    from ..ndarray.ndarray import NDArray

    v = x._data if isinstance(x, NDArray) else x
    n = jax.lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    out = jax.lax.ppermute(v, axis_name, perm)
    return NDArray(out) if isinstance(x, NDArray) else out
