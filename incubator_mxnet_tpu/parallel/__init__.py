"""Parallelism: device mesh, collectives, sharded training steps.

Replaces the reference's distributed stack (SURVEY.md §2.4): ps-lite/NCCL/
Horovod → `jax.sharding.Mesh` + XLA collectives over ICI/DCN.
"""
from .mesh import Mesh, current_mesh, make_mesh, mesh_scope  # noqa: F401
from .collectives import (  # noqa: F401
    all_gather, all_reduce, broadcast, reduce_scatter, ring_permute,
)
from .sharded import DataParallel, shard_train_step  # noqa: F401
from .ring_attention import ring_attention, ring_self_attention  # noqa: F401
