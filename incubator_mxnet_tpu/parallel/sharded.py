"""Sharded training steps (the TPU-native equivalent of the reference's
data-parallel Trainer+KVStore pipeline, SURVEY.md §2.4).

Design: instead of per-device parameter copies + explicit allreduce
(`CommDevice::Reduce`, `src/kvstore/comm.h:482`), the WHOLE train step
(forward, backward, optimizer) is one jit program over a `Mesh`. Batch
arrays are sharded over the 'dp' axis, parameters are replicated (pure DP)
or sharded over 'tp' (tensor parallel); XLA inserts the psum/all-gathers on
ICI and overlaps them with compute — subsuming the reference's P3
priority-overlap scheme (`src/kvstore/p3store_dist.h`)."""
from __future__ import annotations

from .. import util
from ..ndarray.ndarray import NDArray

__all__ = ["DataParallel", "shard_train_step"]


def _build_pure_step(net, loss_fn, optimizer, remat_spec=None):
    """(param_vals, opt_states, t, x, y) -> (loss, new_params, new_states).

    Pure function suitable for jit: parameters are substituted into the
    gluon net during tracing (same mechanism as the CachedOp), the loss is
    differentiated with jax.grad, and the optimizer's pure `step` applies
    updates — everything fuses into one XLA program."""
    import jax

    from .. import autograd
    from ..random import trace_key_scope
    from ..utils.trace import TraceContext

    params = [p for p in net.collect_params().values()
              if p.grad_req != "null"]
    frozen = [p for p in net.collect_params().values()
              if p.grad_req == "null"]
    param_arrays = [p.data() for p in params]
    frozen_arrays = [p.data() for p in frozen]
    # Identities of the aux arrays whose functionalized updates the traced
    # step returns; populated at trace time (jit re-traces set it again).
    aux_arrays_cell: list = []
    # [tuple-of-bools] — which per-param optimizer states travel stacked
    # (one leaf instead of n_slots); set by DataParallel BEFORE the first
    # call, read at trace time.
    stacked_mask_cell: list = []

    def forward_loss(param_vals, frozen_vals, key, x, y):
        saved = [(a, a._data) for a in param_arrays + frozen_arrays]
        for a, v in zip(param_arrays, param_vals):
            a._data = v
        for a, v in zip(frozen_arrays, frozen_vals):
            a._data = v
        tc = TraceContext()
        try:
            with tc, trace_key_scope(key), autograd.pause(train_mode=True):
                out = net.forward(NDArray(x))
                loss = loss_fn(out, NDArray(y))
        finally:
            for a, v in saved:
                a._data = v
        aux_pairs = list(tc.updates.values())
        aux_arrays_cell[:] = [a for a, _ in aux_pairs]
        aux_new = tuple(nv for _, nv in aux_pairs)
        return loss.mean()._data, aux_new

    from .. import remat as _remat

    forward_loss = _remat.wrap(forward_loss, remat_spec)

    # Multi-tensor fusion for SMALL parameters (the reference's
    # aggregate_num fused updates, `src/operator/optimizer_op.cc`
    # multi-sgd/multi-adam): BERT-base has ~150 LN gammas/betas/biases of
    # a few KB each — updating them as one concatenated vector collapses
    # ~150 tiny per-param fusions into one kernel. Safe only for
    # ELEMENTWISE rules (LARS/LAMB take per-tensor norms) over plain
    # list-of-like-shaped states.
    _SMALL = 1 << 14
    # MXNET_OPTIMIZER_AGGREGATION_SIZE (env_var.md, default 4): 0/1
    # disables multi-tensor aggregation; our grouping is one concatenated
    # segment rather than count-sized batches, so >1 leaves it on
    import os as _os

    _agg = _os.environ.get("MXNET_OPTIMIZER_AGGREGATION_SIZE")
    _fusion_off = _agg is not None and _agg.isdigit() and int(_agg) <= 1

    def _fusable(i):
        if _fusion_off:
            return False
        a = param_arrays[i]
        # cheap filters FIRST: create_state allocates real device buffers
        # (Adam m/v), which must not happen for every multi-MB weight
        if not getattr(optimizer, "elementwise", False):
            return False
        if a.size > _SMALL or str(a.dtype) != "float32":
            return False
        try:
            s = optimizer.create_state(i, a)
        except Exception:
            return False
        return (isinstance(s, list)
                and all(getattr(x, "shape", None) == a._data.shape
                        for x in s))

    fused_idx = [i for i in range(len(param_arrays)) if _fusable(i)]
    if len(fused_idx) < 2:
        fused_idx = []
    fused_set = frozenset(fused_idx)
    fused_sizes = [int(param_arrays[i].size) for i in fused_idx]
    fused_shapes = [tuple(param_arrays[i].shape) for i in fused_idx]
    fused_bounds = []
    off = 0
    for n in fused_sizes[:-1]:
        off += n
        fused_bounds.append(off)

    def step(param_vals, frozen_vals, opt_states, t, lr, wd, base_key, x, y):
        import jax.numpy as jnp

        # t arrives as a device scalar and the per-step RNG key derives
        # from (base_key, t) ON DEVICE: the host never uploads a counter
        # or splits a key eagerly, so a steady-state step costs ONE
        # execute RPC (each host->device scalar upload is a round trip on
        # a tunneled chip — they measured ~8 ms/step of dead time)
        key = jax.random.fold_in(base_key, t)
        # per-param [slot0, slot1, ...] state lists arrive STACKED as one
        # (n_slots, *shape) array per param where stacked_mask_cell says
        # so (set by DataParallel; see _stack_state): host-side dispatch
        # cost is per-LEAF, so halving the state leaves shaves ~1 ms off
        # every step on a ~260-param net. Unstack inside the program
        # (free slices) for the optimizer's list contract.
        mask = stacked_mask_cell[0] if stacked_mask_cell else ()
        opt_states = [list(s) if i < len(mask) and mask[i] else s
                      for i, s in enumerate(opt_states)]
        (loss, aux_new), grads = jax.value_and_grad(
            forward_loss, has_aux=True)(param_vals, frozen_vals, key, x, y)
        new_params = [None] * len(param_vals)
        new_states = [None] * len(param_vals)
        if fused_idx:
            w_cat = jnp.concatenate([param_vals[i].ravel()
                                     for i in fused_idx])
            g_cat = jnp.concatenate([grads[i].ravel() for i in fused_idx])
            n_slots = len(opt_states[fused_idx[0]])
            s_cat = [jnp.concatenate([opt_states[i][k].ravel()
                                      for i in fused_idx])
                     for k in range(n_slots)]
            nw_cat, ns_cat = optimizer.step(w_cat, g_cat, s_cat, lr, wd, t)
            w_parts = jnp.split(nw_cat, fused_bounds)
            s_parts = [jnp.split(ns_cat[k], fused_bounds)
                       for k in range(n_slots)]
            for j, i in enumerate(fused_idx):
                new_params[i] = w_parts[j].reshape(fused_shapes[j])
                new_states[i] = [s_parts[k][j].reshape(fused_shapes[j])
                                 for k in range(n_slots)]
        for i, (w, g, s) in enumerate(zip(param_vals, grads, opt_states)):
            if i in fused_set:
                continue
            nw, ns = optimizer.step(w, g, s, lr, wd, t)
            new_params[i] = nw
            new_states[i] = ns
        # re-stack the masked state lists so the OUTPUT side returns one
        # leaf per param too
        new_states = [_stack_state(s) if i < len(mask) and mask[i] else s
                      for i, s in enumerate(new_states)]
        return loss, new_params, new_states, aux_new, t + 1

    return (step, params, param_arrays, frozen_arrays, aux_arrays_cell,
            stacked_mask_cell)


def _observed_step_jit(fn):
    """Compile-observatory wrapper for the train-step program family: the
    warmup compile and any later recompile (shape/dtype churn in the batch,
    a static-arg change) land in the ledger with forensics."""
    from ..telemetry import compiles

    return compiles.instrument_jit(fn, "train.DataParallel.step",
                                   donate=(0, 2, 3))


def _stack_state(s):
    """Stack a per-param [slot, slot, ...] optimizer state (same-shaped
    slots, e.g. adam's m/v) into ONE (n_slots, *shape) array; anything
    else passes through untouched. Inverse: list(s) — jnp unstacking is a
    free view inside jit."""
    import jax.numpy as jnp

    if (isinstance(s, list) and len(s) >= 2
            and all(getattr(x, "shape", None) == getattr(s[0], "shape", ())
                    and getattr(x, "dtype", None) == getattr(s[0], "dtype", 0)
                    for x in s)):
        return jnp.stack(s)
    return s


class DataParallel:
    """Compiled data-parallel trainer for a gluon net.

    Usage::

        dp = DataParallel(net, loss_fn, optimizer, mesh=make_mesh({'dp': 8}))
        loss = dp.step(x_batch, y_batch)   # updates net parameters in place
    """

    def __init__(self, net, loss_fn, optimizer, mesh=None, data_axis="dp",
                 param_shardings=None, remat=None):
        import jax

        from .mesh import current_mesh

        if mesh is None:
            # honor an ambient `with mesh_scope(...)` — callers installing
            # a mesh for sharding_constraint expect the trainer to see it
            mesh = current_mesh()
        self.net = net
        self.optimizer = optimizer
        self.mesh = mesh
        self._t = 0
        # kept so rebuild() can re-run this constructor on a NEW mesh
        # after an elastic topology transition (fault/elastic.py)
        self._loss_fn = loss_fn
        self._remat = remat
        (step, params, param_arrays, frozen_arrays,
         aux_arrays_cell, stacked_mask_cell) = _build_pure_step(
            net, loss_fn, optimizer, remat_spec=remat)
        self.params = params
        self.param_arrays = param_arrays
        self.frozen_arrays = frozen_arrays
        self._aux_arrays_cell = aux_arrays_cell
        raw_states = [optimizer.create_state(i, a)
                      for i, a in enumerate(param_arrays)]
        if mesh is None:
            # single-chip: stack same-shaped state slot lists (adam m/v)
            # into one leaf each — per-leaf dispatch is the wall/device
            # gap on a tunneled chip. On a mesh the per-slot arrays keep
            # their param-matched shardings, so they stay unstacked.
            # SMALL params only: re-stacking inside the step is a device
            # copy of the state bytes, so stacking a 23M-param embedding's
            # adam m/v would add ~180 MB of traffic per step — for the
            # ~185 few-KB biases/gammas the copy is noise and the leaf
            # saving is the point (measured: stacking everything made the
            # step 3.5 ms SLOWER; small-only removes ~0.6 ms of dispatch)
            stacked = [_stack_state(s) if a.size <= (1 << 14) else s
                       for s, a in zip(raw_states, param_arrays)]
            self._stacked = tuple(ns is not s
                                  for ns, s in zip(stacked, raw_states))
            self.opt_states = stacked
        else:
            self._stacked = tuple(False for _ in raw_states)
            self.opt_states = raw_states
        stacked_mask_cell[:] = [self._stacked]

        if mesh is not None:
            P = jax.sharding.PartitionSpec
            NS = jax.sharding.NamedSharding
            repl = NS(mesh, P())
            batch_sh = NS(mesh, P(data_axis))
            if param_shardings is None:
                param_sh = [repl] * len(param_arrays)
            else:
                param_sh = [NS(mesh, ps) for ps in param_shardings]
            # optimizer-state leaves matching the param shape (adam m/v,
            # momentum buffers) shard like the param; scalars replicate
            state_sh = [
                jax.tree.map(
                    lambda leaf, _sh=sh, _shape=tuple(a.shape):
                        _sh if tuple(getattr(leaf, "shape", ())) == _shape
                        else repl,
                    s)
                for s, sh, a in zip(self.opt_states, param_sh, param_arrays)
            ]
            # params/states are fed back in every step: outputs must carry
            # the SAME shardings as the declared inputs, or the second call
            # fails with a committed-sharding mismatch
            # frozen params (incl. BN aux stats) start committed to a single
            # device; replicate them onto the mesh ONCE here. Their
            # in_sharding stays None (= follow the arg) because aux updates
            # come back with compiler-chosen shardings and re-enter.
            for a in frozen_arrays:
                a._set_data(jax.device_put(a._data, repl))
            # donate params + optimizer states: they are consumed and
            # rebound every step, so XLA updates them in place instead of
            # materializing copies
            self._jit = _observed_step_jit(jax.jit(
                step,
                in_shardings=(param_sh, None, state_sh,
                              None, None, None, repl, batch_sh, batch_sh),
                out_shardings=(None, param_sh, state_sh, None, None),
                donate_argnums=(0, 2, 3)))
            self._batch_sharding = batch_sh
        else:
            self._jit = _observed_step_jit(
                jax.jit(step, donate_argnums=(0, 2, 3)))
            self._batch_sharding = None
        self._register_hbm_owners()
        # device-resident step counter + cached lr/wd uploads (see step())
        self._t_dev = None
        self._lr_dev = (None, None)
        self._wd_dev = (None, None)
        self._base_key = None
        self._key_epoch = None
        # kept for the sharding pre-flight (shardcheck_report)
        self._step_fn = step
        self._data_axis = data_axis
        self._param_specs = (list(param_shardings)
                             if param_shardings is not None else None)
        mode = (util.getenv("MXNET_SHARDCHECK") or "").strip().lower()
        if mode in ("warn", "raise") and mesh is not None:
            # pre-flight the declared layout before the first step can
            # commit it to chips; batch shapes are unknown here, so this
            # is the spec tier only (call shardcheck_report(x, y) for the
            # full simulated-mesh pass)
            self.shardcheck_report(mode=mode)

    def _register_hbm_owners(self):
        """HBM-census attribution (`telemetry.hbm`): params (incl. frozen)
        and optimizer state. Donation re-binds these arrays every step, so
        the probes read the live handles through a trainer weakref rather
        than capturing the construction-time arrays."""
        import weakref

        import jax.tree_util as jtu

        ref = weakref.ref(self)

        def _params_probe():
            tr = ref()
            if tr is None:
                return None
            return {"arrays": [a._data for a in tr.param_arrays]
                    + [a._data for a in tr.frozen_arrays]}

        def _opt_probe():
            tr = ref()
            if tr is None:
                return None
            return {"arrays": [leaf for leaf in jtu.tree_leaves(
                tr.opt_states) if hasattr(leaf, "nbytes")]}

        from ..telemetry import hbm

        hbm.register_owner("train.params", _params_probe)
        hbm.register_owner("train.optimizer", _opt_probe)

    def shardcheck_report(self, x=None, y=None, hbm_budget_gb=None,
                          mode=None, compile=True):
        """Static sharding pre-flight over this trainer's step program
        (`mx.analysis.shardcheck`). With a sample batch ``(x, y)`` the
        step is abstract-traced and — given a real mesh — compiled under
        the declared shardings for the collective-cost audit; without one
        only the param/optimizer-state layout is checked."""
        import contextlib

        import jax

        from ..analysis.shardcheck import shardcheck
        from .mesh import mesh_scope

        P = jax.sharding.PartitionSpec
        param_vals = [a._data for a in self.param_arrays]
        frozen_vals = [a._data for a in self.frozen_arrays]
        p_specs = (self._param_specs if self._param_specs is not None
                   else [None] * len(param_vals))
        # state leaves shaped like their param shard like the param;
        # everything else (scalars, counters) is unconstrained
        s_specs = [
            jax.tree.map(
                lambda leaf, _sp=sp, _shape=tuple(a.shape):
                    (_sp if tuple(getattr(leaf, "shape", ())) == _shape
                     else None), s)
            for s, sp, a in zip(self.opt_states, p_specs, self.param_arrays)
        ]
        mesh_kw = dict(mesh=self.mesh, hbm_budget_gb=hbm_budget_gb,
                       mode=mode, compile=compile,
                       name="DataParallel.step")
        if x is None or y is None:
            return shardcheck(None, param_vals, frozen_vals,
                              self.opt_states,
                              specs=(p_specs, None, s_specs), **mesh_kw)

        from ..random import next_key

        xv = x._data if isinstance(x, NDArray) else x
        yv = y._data if isinstance(y, NDArray) else y
        batch_spec = P(self._data_axis) if self.mesh is not None else None
        scalar = jax.ShapeDtypeStruct((), "int32")
        fscalar = jax.ShapeDtypeStruct((), "float32")
        step = self._step_fn

        def fn(*args):
            with (mesh_scope(self.mesh) if self.mesh is not None
                  else contextlib.nullcontext()):
                return step(*args)

        fn.__name__ = "DataParallel.step"
        return shardcheck(
            fn, param_vals, frozen_vals, self.opt_states, scalar, fscalar,
            fscalar, next_key(), xv, yv,
            specs=(p_specs, None, s_specs, None, None, None, P(),
                   batch_spec, batch_spec),
            out_specs=(None, p_specs, s_specs, None, None),
            donate_argnums=(0, 2, 3), **mesh_kw)

    def _dev_scalar(self, value, cache_name, dtype):
        """Upload a python scalar only when it CHANGED since the last step —
        steady-state training pays zero host->device transfers for lr/wd."""
        import jax.numpy as jnp

        cached_val, cached_buf = getattr(self, cache_name)
        if cached_buf is None or cached_val != value:
            cached_buf = jnp.asarray(value, dtype)
            setattr(self, cache_name, (value, cached_buf))
        return cached_buf

    def step(self, x, y):
        import jax.numpy as jnp

        from ..random import next_key

        self._t += 1
        # Mirror Trainer semantics: lr/wd are re-evaluated every update (the
        # scheduler sees the bumped num_update) and enter the compiled step
        # as traced scalars, so set_learning_rate/lr_scheduler take effect
        # without retracing.
        self.optimizer.num_update += 1
        lr = float(self.optimizer.learning_rate)
        wd = float(self.optimizer.wd)
        xv = x._data if isinstance(x, NDArray) else x
        yv = y._data if isinstance(y, NDArray) else y
        param_vals = [a._data for a in self.param_arrays]
        frozen_vals = [a._data for a in self.frozen_arrays]
        if self._t_dev is None:
            self._t_dev = jnp.asarray(self._t, jnp.int32)
        from ..random import seed_epoch

        if self._base_key is None or self._key_epoch != seed_epoch():
            # refresh after mx.random.seed() so reseeding mid-training
            # changes the dropout streams (reference semantics)
            self._base_key = next_key()
            self._key_epoch = seed_epoch()
        lr_dev = self._dev_scalar(lr, "_lr_dev", jnp.float32)
        wd_dev = self._dev_scalar(wd, "_wd_dev", jnp.float32)
        # the mesh is active during tracing so npx.sharding_constraint
        # (sequence/tensor-parallel activation hints) can resolve axes
        import contextlib

        from .mesh import mesh_scope

        with (mesh_scope(self.mesh) if self.mesh is not None
              else contextlib.nullcontext()):
            loss, new_params, new_states, aux_new, self._t_dev = self._jit(
                param_vals, frozen_vals, self.opt_states, self._t_dev,
                lr_dev, wd_dev, self._base_key, xv, yv)
        for a, nv in zip(self.param_arrays, new_params):
            a._set_data(nv)
        for a, nv in zip(self._aux_arrays_cell, aux_new):
            a._set_data(nv)
        self.opt_states = new_states
        return NDArray(loss)

    def rebuild(self, mesh, data_axis=None, param_shardings=None):
        """Re-construct the compiled step on a NEW mesh, carrying
        parameters, optimizer state (momenta), and the step counter
        across — the trainer half of an elastic topology transition
        (`fault.elastic.ElasticController`). Values round-trip through
        HOST memory: after a real shrink the departed ranks' devices are
        gone, so a device-to-device reshard has nothing to read from.
        The optimizer state tree is value-restored after the constructor
        re-creates it (a bare ``create_state`` would silently zero adam
        momenta and dent the loss trajectory)."""
        import jax
        import numpy as onp

        from ..telemetry import tracing

        if mesh is None:
            raise ValueError("DataParallel.rebuild requires a target mesh")
        t = self._t
        specs = (list(param_shardings) if param_shardings is not None
                 else self._param_specs)
        with tracing.span("elastic.rebuild",
                          devices=int(mesh.devices.size)):
            old_states = jax.tree.map(
                lambda leaf: (onp.asarray(leaf)
                              if hasattr(leaf, "shape") else leaf),
                self.opt_states)
            # re-commit trainable params onto the new mesh under their
            # declared specs BEFORE the constructor re-collects them —
            # arrays committed to the old mesh would fail the new jit's
            # in_shardings
            P = jax.sharding.PartitionSpec
            NS = jax.sharding.NamedSharding
            for i, a in enumerate(self.param_arrays):
                spec = specs[i] if specs is not None else None
                sh = NS(mesh, spec if spec is not None else P())
                a._set_data(jax.device_put(onp.asarray(a._data), sh))
            for a in self.frozen_arrays:
                a._set_data(jax.device_put(onp.asarray(a._data),
                                           NS(mesh, P())))
            self.__init__(self.net, self._loss_fn, self.optimizer,
                          mesh=mesh,
                          data_axis=data_axis or self._data_axis,
                          param_shardings=specs, remat=self._remat)
            if (jax.tree.structure(old_states)
                    == jax.tree.structure(self.opt_states)):
                self.opt_states = jax.tree.map(
                    lambda old, new: (jax.device_put(old, new.sharding)
                                      if hasattr(new, "sharding")
                                      else old),
                    old_states, self.opt_states)
            else:
                import logging

                logging.getLogger("incubator_mxnet_tpu.parallel").warning(
                    "DataParallel.rebuild: optimizer-state layout changed "
                    "across the mesh transition — state re-initialized")
        self._t = t
        self._t_dev = None          # re-upload on the next step
        return self


def shard_train_step(step_fn, mesh, in_specs, out_specs):
    """shard_map a raw per-device step over the mesh (for SPMD code that
    calls collectives explicitly — ring attention, expert parallel, etc.)."""
    import jax
    from jax.experimental.shard_map import shard_map

    P = jax.sharding.PartitionSpec
    in_specs = tuple(s if isinstance(s, P) else P(*s) if s else P()
                     for s in in_specs)
    out_specs = (out_specs if isinstance(out_specs, P)
                 else P(*out_specs) if out_specs else P())
    from ..telemetry import compiles

    return compiles.ledgered_jit(
        shard_map(step_fn, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs),
        family="train.shard_map_step")
