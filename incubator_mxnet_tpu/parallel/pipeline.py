"""Pipeline parallelism (GPipe schedule) over a `pp` mesh axis.

Reference role: MXNet's model-parallel story is manual device placement
(`example/model-parallel/`, ctx lists per layer) with the engine's
dependency graph overlapping the stages. The TPU-native design is an SPMD
pipeline: stage parameters are SHARDED over the `pp` axis (each device
holds one stage), microbatches circulate stage-to-stage over ICI with
`lax.ppermute`, and the whole schedule is ONE `lax.scan` inside
`shard_map` — XLA overlaps the permute collectives with stage compute,
the same overlap the reference gets from its threaded engine.

Schedule: classic GPipe fill-drain. For S stages and M microbatches the
scan runs S+M-1 ticks; tick t has stage s working on microbatch t-s
(bubble fraction (S-1)/(S+M-1)).

The per-stage function must be shape-preserving ((microbatch, ...) ->
(microbatch, ...)), the natural shape for stacked transformer blocks —
scan-over-layers composes: `stage_fn` itself may be a `lax.scan` over the
layers within the stage.
"""
from __future__ import annotations

__all__ = ["PipelineParallel", "pipeline_apply", "pipeline_stage_params"]


def pipeline_stage_params(params_per_layer, n_stages):
    """Stack per-layer param pytrees into per-stage stacks: layers are
    split contiguously into `n_stages` groups of L/S layers; leaf arrays
    gain a leading (S, L/S) pair of axes, ready to shard axis 0 over pp."""
    import jax
    import jax.numpy as jnp

    n_layers = len(params_per_layer)
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible into "
                         f"{n_stages} stages")
    per = n_layers // n_stages
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *params_per_layer)
    return jax.tree.map(
        lambda x: x.reshape((n_stages, per) + x.shape[1:]), stacked)


def pipeline_apply(stage_fn, stage_params, x, axis_name="pp"):
    """Run the GPipe schedule inside shard_map over `axis_name`.

    - `stage_fn(params, act) -> act`: one stage's forward on ONE
      microbatch (already holding only this device's stage params).
    - `stage_params`: this device's slice (leading stage axis removed by
      shard_map's in_spec).
    - `x`: (n_micro, micro_batch, ...) — the full minibatch split into
      microbatches, replicated across pp (each stage reads only the
      microbatch it needs at fill time; XLA DCEs the rest).
    Returns (n_micro, micro_batch, ...) outputs (valid on the LAST stage;
    callers all-gather or read from stage S-1).
    """
    import jax.numpy as jnp
    from jax import lax

    from . import collectives

    n_stages = collectives.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x.shape[0]
    ticks = n_stages + n_micro - 1

    def tick(carry, t):
        recv, outputs = carry
        # stage 0 injects microbatch t (while it exists); later stages
        # consume what the previous stage sent last tick
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        injected = lax.dynamic_index_in_dim(x, mb_idx, 0, keepdims=False)
        act_in = jnp.where(stage == 0, injected, recv)
        act_out = stage_fn(stage_params, act_in)
        # last stage banks its result for microbatch t-(S-1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        take = jnp.logical_and(stage == n_stages - 1,
                               t >= n_stages - 1)
        current = lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                           keepdims=False)
        banked = jnp.where(take, act_out, current)
        outputs = lax.dynamic_update_index_in_dim(outputs, banked,
                                                  out_idx, 0)
        sent = collectives.ring_permute(act_out, axis_name)
        return (sent, outputs), None

    # the carry becomes device-varying (ppermute/axis_index inside the
    # body); under shard_map's varying-manual-axes typing the INITIAL
    # carry must be marked varying too
    zero = collectives.pvary(jnp.zeros_like(x[0]), axis_name)
    outputs0 = collectives.pvary(jnp.zeros_like(x), axis_name)
    (_, outputs), _ = lax.scan(tick, (zero, outputs0),
                               jnp.arange(ticks))
    return outputs


class PipelineParallel:
    """GPipe TRAINER over a `pp` mesh axis — fwd + bwd + optimizer step
    through the pipeline schedule, compiled as one XLA program.

    The backward pass is `jax.grad` straight through `pipeline_apply`:
    the scan differentiates into the reversed drain schedule and every
    `ppermute` transposes into the inverse ring hop, so stage cotangents
    flow last-stage -> first-stage exactly like a hand-written GPipe
    backward; microbatch gradient ACCUMULATION falls out of the scan's
    vjp summing over ticks. (Reference role: MXNet model-parallel
    training via per-layer ctx placement + the engine's dependency
    overlap, `example/model-parallel/`.)

    Usage::

        stage_params = pipeline_stage_params(layer_params, n_stages)
        pp = PipelineParallel(stage_fn, stage_params, loss_fn,
                              optimizer.SGD(learning_rate=0.1), mesh)
        loss = pp.step(x_micro, y)    # x_micro: (n_micro, micro_b, ...)

    `stage_fn(params, act) -> act` applies ONE stage (its stacked layers)
    to one microbatch. `loss_fn(outs, y)` maps the (n_micro, ...) pipeline
    outputs to a scalar.
    """

    def __init__(self, stage_fn, stage_params, loss_fn, optimizer,
                 mesh, axis_name="pp"):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from . import collectives
        from ..ndarray.ndarray import NDArray

        self.mesh = mesh
        self.axis_name = axis_name
        self.optimizer = optimizer
        n_stages = mesh.shape[axis_name]
        self._t = 0

        # per-leaf optimizer states, stacked over the stage axis like the
        # params (each device updates its own stage's slice)
        leaves = jax.tree.leaves(stage_params)
        states = [optimizer.create_state(i, NDArray(a))
                  for i, a in enumerate(leaves)]
        self._state_treedef = jax.tree.structure(stage_params)
        self.params = jax.device_put(
            stage_params, NamedSharding(mesh, P(axis_name)))
        self.opt_states = jax.device_put(
            states, NamedSharding(mesh, P(axis_name)))

        def device_fn(params, opt_states, x, y, t):
            def loss_of(p):
                # shard_map's P(pp) slice keeps a leading stage axis of
                # size 1 — stage_fn sees the bare per-stage params
                p_local = jax.tree.map(lambda a: a[0], p)
                outs = pipeline_apply(stage_fn, p_local, x, axis_name)
                stage_loss = loss_fn(outs, y)
                last = lax.axis_index(axis_name) == n_stages - 1
                # only the LAST stage banked real outputs; keep the
                # scalar per-device here — this build's shard_map psum
                # transpose over-counts the cotangent by the axis size,
                # so the global reduce happens OUTSIDE value_and_grad
                # (ppermute transposes already route stage cotangents)
                return jnp.where(last, stage_loss, 0.0)

            loss, grads = jax.value_and_grad(loss_of)(params)
            loss = collectives.all_reduce(loss, axis_name)
            p_leaves = jax.tree.leaves(params)
            g_leaves = jax.tree.leaves(grads)
            new_p, new_s = [], []
            for i, (w, g) in enumerate(zip(p_leaves, g_leaves)):
                w2, s2 = optimizer.step(w, g, opt_states[i],
                                        optimizer.learning_rate,
                                        optimizer.wd, t)
                new_p.append(w2)
                new_s.append(s2)
            return (loss,
                    jax.tree.unflatten(self._state_treedef, new_p),
                    new_s)

        psp = P(axis_name)
        from jax.experimental.shard_map import shard_map

        from ..telemetry.compiles import ledgered_jit

        self._jit = ledgered_jit(shard_map(
            device_fn, mesh=mesh,
            in_specs=(psp, psp, P(), P(), P()),
            out_specs=(P(), psp, psp)), family="train.pipeline.step")

    def step(self, x, y):
        """One GPipe train step; returns the scalar loss (NDArray)."""
        import jax.numpy as jnp

        from ..ndarray.ndarray import NDArray

        x = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        y = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        self._t += 1
        loss, self.params, self.opt_states = self._jit(
            self.params, self.opt_states, x, y, jnp.float32(self._t))
        return NDArray(loss)
