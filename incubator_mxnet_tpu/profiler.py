"""Profiler (reference: `python/mxnet/profiler.py` + `src/profiler/` — chrome
tracing JSON, per-op aggregate stats).

TPU-native: wraps the jax/XLA profiler (XPlane → TensorBoard / Perfetto) and
keeps the reference's `set_config / start / stop / dump / dumps` API shape.
Python-level op timing (the aggregate table) is collected by timing the
apply_op funnel when profiling is on."""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "set_state", "start", "stop", "dump", "dumps",
           "pause", "resume", "Scope", "profiler_scope"]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "profile_imperative": True, "aggregate_stats": True}
_STATE = {"running": False, "jax_tracing": False}
_EVENTS: list = []
_AGG = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # count, total, min, max
_LOCK = threading.Lock()


def set_config(**kwargs):
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    if state in ("run", "start"):
        start()
    else:
        stop()


def start(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = True
    logdir = _CONFIG.get("tensorboard_logdir")
    if logdir:
        import jax

        try:
            jax.profiler.start_trace(logdir)
            _STATE["jax_tracing"] = True
        except Exception:
            _STATE["jax_tracing"] = False


def stop(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = False
    if _STATE.get("jax_tracing"):
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _STATE["jax_tracing"] = False


def pause(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = False


def resume(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = True


def is_running():
    return _STATE["running"]


def record_op(name, dur_s):
    """Called from the op funnel when profiling is active."""
    with _LOCK:
        _EVENTS.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                        "ts": time.time() * 1e6, "dur": dur_s * 1e6})
        agg = _AGG[name]
        agg[0] += 1
        agg[1] += dur_s
        agg[2] = min(agg[2], dur_s)
        agg[3] = max(agg[3], dur_s)


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Write chrome://tracing JSON (reference: profiler.py:125)."""
    path = _CONFIG["filename"]
    with _LOCK:
        payload = {"traceEvents": list(_EVENTS), "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def dumps(reset=False, format="table", sort_by="total", ascending=False):  # noqa: ARG001
    """Aggregate per-op stats table (reference: profiler.py:154)."""
    with _LOCK:
        rows = [(name, c, tot * 1000, mn * 1000, mx * 1000)
                for name, (c, tot, mn, mx) in _AGG.items()]
        if reset:
            _AGG.clear()
            _EVENTS.clear()
    key = {"total": 2, "count": 1, "min": 3, "max": 4}.get(sort_by, 2)
    rows.sort(key=lambda r: r[key], reverse=not ascending)
    lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}", "=" * 80]
    for name, c, tot, mn, mx in rows:
        lines.append(f"{name[:39]:<40}{c:>8}{tot:>12.3f}{mn:>10.3f}{mx:>10.3f}")
    return "\n".join(lines)


class Scope:
    """RAII profiling scope (ProfileTask/ProfileEvent parity)."""

    def __init__(self, name="<unk>:"):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _STATE["running"]:
            record_op(self.name, time.perf_counter() - self._t0)
        return False


profiler_scope = Scope

if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    start()
    atexit.register(dump)
