"""Profiler (reference: `python/mxnet/profiler.py` + `src/profiler/` — chrome
tracing JSON, per-op aggregate stats, true per-op DEVICE cost
`src/profiler/profiler.h:263`).

TPU-native: two complementary sources, merged at `dump()`:

- host funnel timing: `record_op` times each apply_op dispatch (imperative
  op latency — on an async device this measures dispatch, not execution);
- DEVICE timeline: `start()` begins a jax/XLA profiler trace (XPlane);
  `stop()` ends it and parses the captured chrome-trace, pulling the
  per-op device events (fusions, custom calls, pjit programs) and their
  durations. `dump()` writes ONE chrome://tracing JSON containing both
  lanes; `dumps()` appends a device-side aggregate table.

`set_config(profile_device=False)` disables the device trace;
`set_config(tensorboard_logdir=...)` additionally keeps the raw XPlane
artifacts where TensorBoard/XProf can load them.
"""
from __future__ import annotations

import atexit
import glob
import gzip
import json
import os
import shutil
import tempfile
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "set_state", "start", "stop", "dump", "dumps",
           "pause", "resume", "Scope", "profiler_scope", "device_events"]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "profile_imperative": True, "aggregate_stats": True,
           "profile_device": True}
_STATE = {"running": False, "jax_tracing": False, "trace_dir": None,
          "own_trace_dir": False}
_EVENTS: list = []
_DEVICE_EVENTS: list = []
_DEVICE_AGG = defaultdict(lambda: [0, 0.0])        # count, total_us
_AGG = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # count, total, min, max
_LOCK = threading.Lock()


def set_config(**kwargs):
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):  # noqa: ARG001
    if state in ("run", "start"):
        start()
    else:
        stop()


def start(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = True
    if not _CONFIG.get("profile_device", True):
        return
    # each start/stop cycle REPLACES the device timeline (a per-epoch
    # start/stop loop would otherwise grow the event list without bound)
    with _LOCK:
        _DEVICE_EVENTS.clear()
        _DEVICE_AGG.clear()
    logdir = _CONFIG.get("tensorboard_logdir")
    if logdir:
        _STATE["trace_dir"] = logdir
        _STATE["own_trace_dir"] = False
    else:
        _STATE["trace_dir"] = tempfile.mkdtemp(prefix="mxtpu_prof_")
        _STATE["own_trace_dir"] = True
    import jax

    try:
        jax.profiler.start_trace(_STATE["trace_dir"])
        # wall-clock anchor: XPlane event timestamps are relative to trace
        # start; dump() rebases them onto the host lane's epoch-µs clock
        _STATE["trace_t0_us"] = time.time() * 1e6
        _STATE["jax_tracing"] = True
    except Exception:
        _STATE["jax_tracing"] = False
        if _STATE.get("own_trace_dir") and _STATE.get("trace_dir"):
            shutil.rmtree(_STATE["trace_dir"], ignore_errors=True)
        _STATE["trace_dir"] = None


def stop(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = False
    if _STATE.get("jax_tracing"):
        import jax

        try:
            jax.profiler.stop_trace()
            _ingest_device_trace(_STATE["trace_dir"])
        except Exception:
            pass
        finally:
            if _STATE.get("own_trace_dir") and _STATE.get("trace_dir"):
                shutil.rmtree(_STATE["trace_dir"], ignore_errors=True)
            _STATE["trace_dir"] = None
        _STATE["jax_tracing"] = False


def _ingest_device_trace(trace_dir):
    """Parse the captured XPlane chrome-trace: keep the device/runtime
    lanes' complete events (+ their metadata rows, remapped clear of the
    host-funnel pid 0) and accumulate per-op device aggregates."""
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        return
    with gzip.open(paths[-1]) as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    lanes = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            lanes[e["pid"]] = e.get("args", {}).get("name", "")
    t0 = _STATE.get("trace_t0_us", 0.0)
    with _LOCK:
        for e in events:
            pid = e.get("pid")
            if pid not in lanes:
                continue
            kept = dict(e)
            kept["pid"] = 1000 + pid       # host funnel events own pid 0
            if "ts" in kept:
                # rebase trace-relative µs onto the host epoch clock so
                # host dispatch and device execution correlate in one view
                kept["ts"] = float(kept["ts"]) + t0
            _DEVICE_EVENTS.append(kept)
            if e.get("ph") == "X" and lanes[pid].startswith("/device:"):
                agg = _DEVICE_AGG[e.get("name", "?")]
                agg[0] += 1
                agg[1] += float(e.get("dur", 0))


def device_events():
    """Parsed device-timeline events from the last stop() (list of chrome
    trace events; empty before any device trace completes)."""
    with _LOCK:
        return list(_DEVICE_EVENTS)


def pause(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = False


def resume(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = True


def is_running():
    return _STATE["running"]


def record_op(name, dur_s):
    """Called from the op funnel when profiling is active."""
    with _LOCK:
        _EVENTS.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                        "ts": time.time() * 1e6, "dur": dur_s * 1e6})
        agg = _AGG[name]
        agg[0] += 1
        agg[1] += dur_s
        agg[2] = min(agg[2], dur_s)
        agg[3] = max(agg[3], dur_s)


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Write ONE chrome://tracing JSON holding the host dispatch lane
    (pid 0) and the device/runtime lanes from the jax trace
    (reference: profiler.py:125 writes the C++ profiler's chrome trace)."""
    path = _CONFIG["filename"]
    with _LOCK:
        merged = [{"name": "process_name", "ph": "M", "pid": 0,
                   "args": {"name": "host: op dispatch"}}]
        merged += list(_EVENTS)
        merged += list(_DEVICE_EVENTS)
        payload = {"traceEvents": merged, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def dumps(reset=False, format="table", sort_by="total", ascending=False):  # noqa: ARG001
    """Aggregate per-op stats (reference: profiler.py:154): host dispatch
    table, then the device-timeline table when a trace was captured."""
    with _LOCK:
        rows = [(name, c, tot * 1000, mn * 1000, mx * 1000)
                for name, (c, tot, mn, mx) in _AGG.items()]
        dev_rows = [(name, c, tot_us / 1000.0)
                    for name, (c, tot_us) in _DEVICE_AGG.items()]
        if reset:
            _AGG.clear()
            _EVENTS.clear()
            _DEVICE_AGG.clear()
            _DEVICE_EVENTS.clear()
    key = {"total": 2, "count": 1, "min": 3, "max": 4}.get(sort_by, 2)
    rows.sort(key=lambda r: r[key], reverse=not ascending)
    lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}", "=" * 80]
    for name, c, tot, mn, mx in rows:
        lines.append(f"{name[:39]:<40}{c:>8}{tot:>12.3f}{mn:>10.3f}{mx:>10.3f}")
    if dev_rows:
        dev_rows.sort(key=lambda r: r[2], reverse=not ascending)
        lines += ["", f"{'Device op':<48}{'Count':>8}{'Total(ms)':>12}",
                  "=" * 80]
        for name, c, tot in dev_rows:
            lines.append(f"{name[:47]:<48}{c:>8}{tot:>12.3f}")
    return "\n".join(lines)


class Scope:
    """RAII profiling scope (ProfileTask/ProfileEvent parity)."""

    def __init__(self, name="<unk>:"):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _STATE["running"]:
            record_op(self.name, time.perf_counter() - self._t0)
        return False


profiler_scope = Scope

if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    start()
    atexit.register(dump)
