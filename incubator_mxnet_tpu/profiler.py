"""Profiler (reference: `python/mxnet/profiler.py` + `src/profiler/` — chrome
tracing JSON, per-op aggregate stats, true per-op DEVICE cost
`src/profiler/profiler.h:263`).

TPU-native: two complementary sources, merged at `dump()`:

- host funnel timing: `record_op` times each apply_op dispatch (imperative
  op latency — on an async device this measures dispatch, not execution);
- DEVICE timeline: `start()` begins a jax/XLA profiler trace (XPlane);
  `stop()` ends it and parses the captured chrome-trace, pulling the
  per-op device events (fusions, custom calls, pjit programs) and their
  durations. `dump()` writes ONE chrome://tracing JSON containing both
  lanes; `dumps()` appends a device-side aggregate table.

`set_config(profile_device=False)` disables the device trace;
`set_config(tensorboard_logdir=...)` additionally keeps the raw XPlane
artifacts where TensorBoard/XProf can load them.
"""
from __future__ import annotations

import atexit
import glob
import gzip
import json
import os
import shutil
import tempfile
import threading
import time
from collections import defaultdict

__all__ = ["set_config", "set_state", "start", "stop", "dump", "dumps",
           "pause", "resume", "Scope", "profiler_scope", "device_events",
           "event_stat_bytes", "event_stat_flops",
           "memory_stats", "live_buffer_table", "memory_snapshot",
           "analyze_memory"]

_CONFIG = {"filename": "profile.json", "profile_all": False,
           "profile_imperative": True, "aggregate_stats": True,
           "profile_device": True, "profile_memory": False}
_STATE = {"running": False, "jax_tracing": False, "trace_dir": None,
          "own_trace_dir": False}
_EVENTS: list = []
_DEVICE_EVENTS: list = []
_DEVICE_AGG = defaultdict(lambda: [0, 0.0])        # count, total_us
_AGG = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])  # count, total, min, max
_LOCK = threading.Lock()


_REMOTE_PENDING: list = []   # ('set_config', {...}) / ('set_state', 'run')


def set_config(**kwargs):
    """`profile_process='server'` queues the config as a REMOTE command:
    it ships to every process of the dist job at the next kvstore sync
    point and applies there (reference: `KVStoreServerProfilerCommand`
    kSetConfig riding ps-lite, `include/mxnet/kvstore.h:48` — the TPU
    build has no separate server processes, so 'server' means 'all
    processes of the job')."""
    if kwargs.pop("profile_process", "worker") == "server":
        _REMOTE_PENDING.append(("set_config", dict(kwargs)))
        if not _dist_active():      # degenerate job: we ARE the server
            _CONFIG.update(kwargs)
        return
    _CONFIG.update(kwargs)


def set_state(state="stop", profile_process="worker"):
    if profile_process == "server":
        _REMOTE_PENDING.append(("set_state", state))
        if _dist_active():
            return
    if state in ("run", "start"):
        start()
    else:
        stop()


def _dist_active():
    try:
        from .parallel import dist

        return dist.is_initialized() and dist.num_processes() > 1
    except Exception:
        return False


def sync_remote_commands():
    """Collective exchange+apply of queued 'server' profiler commands —
    called from KVStoreDist sync points (every process must participate;
    commands from ANY rank apply on ALL ranks)."""
    global _REMOTE_PENDING
    if not _dist_active():
        _REMOTE_PENDING = []
        return
    from .parallel import dist

    mine, _REMOTE_PENDING = _REMOTE_PENDING, []
    for cmds in dist.exchange_objs(mine):
        for kind, arg in cmds or []:
            if kind == "set_config":
                _CONFIG.update(arg)
            elif kind == "set_state":
                set_state(arg)


def start(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = True
    if not _CONFIG.get("profile_device", True):
        return
    # each start/stop cycle REPLACES the device timeline (a per-epoch
    # start/stop loop would otherwise grow the event list without bound)
    with _LOCK:
        _DEVICE_EVENTS.clear()
        _DEVICE_AGG.clear()
    del _PAUSED_INTERVALS[:]
    logdir = _CONFIG.get("tensorboard_logdir")
    if logdir:
        _STATE["trace_dir"] = logdir
        _STATE["own_trace_dir"] = False
    else:
        _STATE["trace_dir"] = tempfile.mkdtemp(prefix="mxtpu_prof_")
        _STATE["own_trace_dir"] = True
    import jax

    try:
        # wall-clock anchor: XPlane event timestamps are relative to the
        # MOMENT start_trace is called (session setup time included), so
        # the anchor must be captured BEFORE the call — capturing it
        # after used to shear the device lanes by the multi-second
        # profiler-session init on some backends
        _STATE["trace_t0_us"] = time.time() * 1e6
        jax.profiler.start_trace(_STATE["trace_dir"])
        _STATE["jax_tracing"] = True
    except Exception:
        _STATE["jax_tracing"] = False
        if _STATE.get("own_trace_dir") and _STATE.get("trace_dir"):
            shutil.rmtree(_STATE["trace_dir"], ignore_errors=True)
        _STATE["trace_dir"] = None


def stop(profile_process="worker"):  # noqa: ARG001
    _STATE["running"] = False
    if _STATE.get("jax_tracing"):
        import jax

        try:
            jax.profiler.stop_trace()
            _ingest_device_trace(_STATE["trace_dir"])
        except Exception as e:
            from .fault.retry import suppressed

            suppressed("profiler.stop_trace", e)   # device trace lost
        finally:
            if _STATE.get("own_trace_dir") and _STATE.get("trace_dir"):
                shutil.rmtree(_STATE["trace_dir"], ignore_errors=True)
            _STATE["trace_dir"] = None
        _STATE["jax_tracing"] = False


def _ingest_device_trace(trace_dir):
    """Parse the captured XPlane chrome-trace: keep the device/runtime
    lanes' complete events (+ their metadata rows, remapped clear of the
    host-funnel pid 0) and accumulate per-op device aggregates."""
    paths = sorted(glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        return
    with gzip.open(paths[-1]) as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    lanes = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            lanes[e["pid"]] = e.get("args", {}).get("name", "")
    t0 = _STATE.get("trace_t0_us", 0.0)
    with _LOCK:
        for e in events:
            pid = e.get("pid")
            if pid not in lanes:
                continue
            kept = dict(e)
            kept["pid"] = 1000 + pid       # host funnel events own pid 0
            if "ts" in kept:
                # rebase trace-relative µs onto the host epoch clock so
                # host dispatch and device execution correlate in one view
                kept["ts"] = float(kept["ts"]) + t0
                # honor pause()/resume(): the device trace records through
                # a pause, so filter its events out at ingest (metadata
                # rows carry no timestamp and always survive)
                if e.get("ph") != "M" and _in_paused_interval(kept["ts"]):
                    continue
            if e.get("ph") == "X":
                # normalize the per-version XPlane stat spellings into
                # canonical arg keys so every downstream consumer
                # (roofline, kernel census) reads one name
                b, fl = event_stat_bytes(kept), event_stat_flops(kept)
                if b is not None or fl is not None:
                    args = dict(kept.get("args") or {})
                    if b is not None:
                        args["bytes_accessed"] = b
                    if fl is not None:
                        args["flops"] = fl
                    kept["args"] = args
            _DEVICE_EVENTS.append(kept)
            if e.get("ph") == "X" and lanes[pid].startswith("/device:"):
                agg = _DEVICE_AGG[e.get("name", "?")]
                agg[0] += 1
                agg[1] += float(e.get("dur", 0))


def event_stat_bytes(e):
    """Bytes accessed by one trace event, from its XPlane stat args, or
    None when the trace carries no byte accounting for it. THE extraction
    path: `telemetry.roofline` and `telemetry.kernels` both route through
    here, so a new jax/XLA stat spelling (``bytes accessed`` vs
    ``bytes_accessed`` vs bare ``bytes``) is fixed in one place."""
    args = e.get("args") or {}
    for k, v in args.items():
        lk = k.lower()
        if "bytes" in lk and ("access" in lk or lk == "bytes"):
            try:
                return int(float(v))
            except (TypeError, ValueError):
                continue
    return None


def event_stat_flops(e):
    """FLOPs of one trace event from its XPlane stat args (``flops`` /
    ``model_flops`` / ``device_flops`` spellings), or None."""
    args = e.get("args") or {}
    for k, v in args.items():
        lk = k.lower().replace(" ", "_")
        if lk in ("flops", "model_flops", "device_flops",
                  "estimated_flops"):
            try:
                return int(float(v))
            except (TypeError, ValueError):
                continue
    return None


def device_events():
    """Parsed device-timeline events from the last stop() (list of chrome
    trace events; empty before any device trace completes). Events whose
    XPlane stats carry byte/FLOP accounting additionally expose the
    canonical ``bytes_accessed``/``flops`` arg keys (normalized at
    ingest), so consumers need not know the per-version stat spellings."""
    with _LOCK:
        return list(_DEVICE_EVENTS)


def device_op_totals():
    """{op name: (count, total_us)} aggregated from the /device: lanes
    only — true on-chip execution time, no host/launch events (what the
    aggregate table in dumps() prints)."""
    with _LOCK:
        return {k: (v[0], v[1]) for k, v in _DEVICE_AGG.items()}


_PAUSED_INTERVALS: list = []   # [start_us, end_us|None] epoch-µs, host clock


def pause(profile_process="worker"):  # noqa: ARG001
    """Stop host-side op recording AND mark the paused interval so device
    events are suppressed too.

    Scope: the host flag takes effect immediately (`record_op` checks it
    per op). The jax/XLA DEVICE trace cannot be paused mid-flight — it
    keeps recording until `stop()` — so instead the paused window
    [pause(), resume()] is remembered and `_ingest_device_trace` drops
    device events whose (rebased) timestamp falls inside it. Metadata
    rows (process/thread names) are always kept."""
    _STATE["running"] = False
    _PAUSED_INTERVALS.append([time.time() * 1e6, None])


def resume(profile_process="worker"):  # noqa: ARG001
    """Resume host-side op recording and close the paused interval (see
    `pause` for the device-trace suppression semantics)."""
    _STATE["running"] = True
    if _PAUSED_INTERVALS and _PAUSED_INTERVALS[-1][1] is None:
        _PAUSED_INTERVALS[-1][1] = time.time() * 1e6


def _in_paused_interval(ts_us):
    for start, end in _PAUSED_INTERVALS:
        if ts_us >= start and (end is None or ts_us <= end):
            return True
    return False


def is_running():
    return _STATE["running"]


def record_op(name, dur_s):
    """Called from the op funnel when profiling is active."""
    mem = None
    if _CONFIG.get("profile_memory"):
        mem = _live_bytes()
    with _LOCK:
        _EVENTS.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                        "ts": time.time() * 1e6, "dur": dur_s * 1e6})
        agg = _AGG[name]
        agg[0] += 1
        agg[1] += dur_s
        agg[2] = min(agg[2], dur_s)
        agg[3] = max(agg[3], dur_s)
        if mem is not None:
            m = _MEM_AGG[name]
            m[0] = max(m[0], mem)
            if mem > _MEM_STATE["peak"]:
                _MEM_STATE["peak"] = mem
                _MEM_STATE["peak_op"] = name


# ---------------------------------------------------------------------------
# memory profiler (reference: `src/profiler/storage_profiler.h:130`
# GpuDeviceStorageProfiler per-alloc attribution + kMemory profile mode,
# `src/profiler/profiler.h:265`)
# ---------------------------------------------------------------------------

_MEM_AGG = defaultdict(lambda: [0])                 # peak live bytes at op
_MEM_STATE = {"peak": 0, "peak_op": None}


def _live_bytes():
    import jax

    total = 0
    for a in jax.live_arrays():
        try:
            total += a.nbytes
        except Exception:  # noqa: FL006 — deleted/donated buffer racing the sweep
            pass
    return total


def memory_stats(device=None):
    """Per-device memory statistics. On TPU/GPU this surfaces the PJRT
    allocator's `bytes_in_use` / `peak_bytes_in_use`; on backends without
    allocator stats (CPU) it falls back to summed live-buffer bytes. The
    reference's `GpuDeviceStorageProfiler` csv role."""
    import jax

    devices = [device] if device is not None else jax.devices()
    out = {}
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            live = sum(a.nbytes for a in jax.live_arrays()
                       if d in getattr(a, "devices", lambda: set())())
            stats = {"bytes_in_use": live, "peak_bytes_in_use": live,
                     "source": "live_arrays"}
        out[str(d)] = dict(stats)
    return out


def live_buffer_table(top=20):
    """The largest live device buffers (shape, dtype, bytes) — per-alloc
    attribution in the spirit of the reference's storage profiler dump."""
    import jax

    rows = []
    for a in jax.live_arrays():
        try:
            rows.append((tuple(a.shape), str(a.dtype), int(a.nbytes)))
        except Exception:  # noqa: FL006 — deleted/donated buffer racing the sweep
            continue
    rows.sort(key=lambda r: -r[2])
    return rows[:top]


def memory_snapshot(path="memory.prof"):
    """Write a pprof-format device memory profile
    (`jax.profiler.device_memory_profile`) — loadable with `pprof` /
    TensorBoard memory viewer. Returns the path."""
    import jax

    with open(path, "wb") as f:
        f.write(jax.profiler.device_memory_profile())
    return path


def analyze_memory(fn, *args, static_argnums=None):
    """Compile `fn(*args)` and return XLA's memory analysis — argument /
    output / TEMP (activation) / alias bytes and the generated code size.
    The temp size is the compiler's actual activation-buffer plan, so it
    directly exposes what remat saves (used by `tests/test_profiler.py`
    to pin remat peak < no-remat peak). Works on every backend —
    compile-time analysis, nothing is executed."""
    import jax

    # AOT memory estimator: lower+compile for analysis only, nothing runs
    jitted = jax.jit(fn, static_argnums=static_argnums or ())  # noqa: FL012
    compiled = jitted.lower(*args).compile()
    an = compiled.memory_analysis()
    if an is None:                 # pragma: no cover - backend-dependent
        return None
    return {
        "argument_size_in_bytes": an.argument_size_in_bytes,
        "output_size_in_bytes": an.output_size_in_bytes,
        "temp_size_in_bytes": an.temp_size_in_bytes,
        "alias_size_in_bytes": an.alias_size_in_bytes,
        "generated_code_size_in_bytes": an.generated_code_size_in_bytes,
    }


def dump(finished=True, profile_process="worker"):  # noqa: ARG001
    """Write ONE chrome://tracing JSON holding the host dispatch lane
    (pid 0), the device/runtime lanes from the jax trace (reference:
    profiler.py:125 writes the C++ profiler's chrome trace), and — when
    span tracing is armed — the request/step span lanes from
    `telemetry.tracing` (all three share the epoch-µs clock base)."""
    path = _CONFIG["filename"]
    with _LOCK:
        merged = [{"name": "process_name", "ph": "M", "pid": 0,
                   "args": {"name": "host: op dispatch"}}]
        merged += list(_EVENTS)
        merged += list(_DEVICE_EVENTS)
    from .telemetry import tracing

    if tracing.is_enabled():
        merged += tracing.chrome_events()
    payload = {"traceEvents": merged, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def dumps(reset=False, format="table", sort_by="total", ascending=False,
          memory=False):
    """Aggregate per-op stats (reference: profiler.py:154): host dispatch
    table, then the device-timeline table when a trace was captured;
    `memory=True` appends the memory section (per-device allocator stats,
    observed live-bytes peak + the op at peak when
    `set_config(profile_memory=True)` sampled during the run, and the
    largest live buffers — the reference's kMemory mode +
    storage-profiler table). `format="json"` returns the same aggregates
    as a JSON string (host/device rows + optional memory section) instead
    of the text tables; `"table"` is the default text path."""
    if format not in ("table", "json"):
        raise ValueError(f"format must be 'table' or 'json', got {format!r}")
    with _LOCK:
        rows = [(name, c, tot * 1000, mn * 1000, mx * 1000)
                for name, (c, tot, mn, mx) in _AGG.items()]
        dev_rows = [(name, c, tot_us / 1000.0)
                    for name, (c, tot_us) in _DEVICE_AGG.items()]
        mem_rows = [(name, peak[0]) for name, peak in _MEM_AGG.items()]
        mem_peak = dict(_MEM_STATE)
        if reset:
            _AGG.clear()
            _EVENTS.clear()
            _DEVICE_AGG.clear()
            _DEVICE_EVENTS.clear()
            _MEM_AGG.clear()
            _MEM_STATE.update(peak=0, peak_op=None)
    key = {"total": 2, "count": 1, "min": 3, "max": 4}.get(sort_by, 2)
    rows.sort(key=lambda r: r[key], reverse=not ascending)
    if format == "json":
        payload = {
            "host": [{"name": n, "count": c, "total_ms": tot, "min_ms": mn,
                      "max_ms": mx} for n, c, tot, mn, mx in rows],
            "device": sorted(
                ({"name": n, "count": c, "total_ms": tot}
                 for n, c, tot in dev_rows),
                key=lambda r: r["total_ms"], reverse=not ascending),
        }
        if memory:
            payload["memory"] = {
                "devices": memory_stats(),
                "observed_peak": mem_peak,
                "op_peak_live_bytes": {n: p for n, p in mem_rows},
                "largest_live_buffers": [
                    {"shape": list(shape), "dtype": dtype, "nbytes": nb}
                    for shape, dtype, nb in live_buffer_table(10)],
            }
        return json.dumps(payload)
    lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
             f"{'Max(ms)':>10}", "=" * 80]
    for name, c, tot, mn, mx in rows:
        lines.append(f"{name[:39]:<40}{c:>8}{tot:>12.3f}{mn:>10.3f}{mx:>10.3f}")
    if dev_rows:
        dev_rows.sort(key=lambda r: r[2], reverse=not ascending)
        lines += ["", f"{'Device op':<48}{'Count':>8}{'Total(ms)':>12}",
                  "=" * 80]
        for name, c, tot in dev_rows:
            lines.append(f"{name[:47]:<48}{c:>8}{tot:>12.3f}")
    if memory:
        lines += ["", "Memory", "=" * 80]
        for dev, st in memory_stats().items():
            in_use = st.get("bytes_in_use", 0)
            peak = st.get("peak_bytes_in_use", in_use)
            lines.append(f"{dev:<40}{in_use / 2**20:>14.2f} MiB in use"
                         f"{peak / 2**20:>14.2f} MiB peak")
        if mem_peak["peak"]:
            lines.append(
                f"observed live-bytes peak: {mem_peak['peak'] / 2**20:.2f} "
                f"MiB at op {mem_peak['peak_op']}")
            mem_rows.sort(key=lambda r: -r[1])
            lines += ["", f"{'Op (peak live bytes at dispatch)':<48}"
                          f"{'MiB':>12}", "-" * 60]
            for name, peak_b in mem_rows[:15]:
                lines.append(f"{name[:47]:<48}{peak_b / 2**20:>12.2f}")
        lines += ["", f"{'Largest live buffers':<40}{'dtype':>10}"
                      f"{'MiB':>12}", "-" * 62]
        for shape, dtype, nbytes in live_buffer_table(10):
            lines.append(f"{str(shape)[:39]:<40}{dtype:>10}"
                         f"{nbytes / 2**20:>12.2f}")
    return "\n".join(lines)


class Scope:
    """RAII profiling scope (ProfileTask/ProfileEvent parity)."""

    def __init__(self, name="<unk>:"):
        self.name = name
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if _STATE["running"]:
            record_op(self.name, time.perf_counter() - self._t0)
        return False


profiler_scope = Scope

if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    # MXNET_PROFILER_MODE (env_var.md, default 0): 0 = symbolic/device
    # only (skip per-op imperative timing), 1 = all
    if os.environ.get("MXNET_PROFILER_MODE", "0") != "1":
        set_config(profile_imperative=False)
    start()
    atexit.register(dump)
