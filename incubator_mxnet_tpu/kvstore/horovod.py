"""Horovod-compatible kvstore facade (reference:
`python/mxnet/kvstore/horovod.py:27`).

The reference delegates broadcast/pushpull to `horovod.mxnet`'s MPI
allreduce ring. On TPU the same role — synchronous allreduce across all
workers with no parameter server — is exactly what XLA collectives over
ICI/DCN do, so this facade keeps the Horovod class's API surface
(rank/local_rank/num_workers, broadcast, pushpull; `pull` unsupported,
like the original) while the transport is the mesh/`jax.distributed`
reduce of the device store.
"""
from __future__ import annotations

import os

from .base import register
from .kvstore import KVStoreDevice

__all__ = ["Horovod"]


@register
class Horovod(KVStoreDevice):
    """`kv = mx.kv.create('horovod')` — allreduce-only store."""

    def __init__(self):
        super().__init__()
        try:
            from ..parallel import dist

            dist.initialize()
            self._dist = dist
        except Exception:
            self._dist = None

    @property
    def rank(self):
        return self._dist.rank() if self._dist else 0

    @property
    def local_rank(self):
        """Rank within the host (reference horovod facade semantics —
        used for per-host device/file assignment). Honors the launcher's
        local-rank env (our tools/launch.py, OpenMPI, torchrun) when
        present; a single jax process owns all of a host's chips, so
        absent those env vars the process IS host-local rank 0... unless
        several ranks share the host, where global rank is the only
        (documented, possibly wrong) fallback left."""
        for name in ("MXNET_LOCAL_RANK", "HOROVOD_LOCAL_RANK",
                     "OMPI_COMM_WORLD_LOCAL_RANK", "LOCAL_RANK"):
            v = os.environ.get(name)
            if v:
                try:
                    return int(v)
                except ValueError:
                    continue   # malformed export (e.g. 'LOCAL_RANK=')
        return self._dist.rank() if self._dist else 0

    @property
    def num_workers(self):
        return self._dist.num_processes() if self._dist else 1

    def _reduce(self, value):
        from ..ndarray.ndarray import NDArray

        if self._dist and self._dist.num_processes() > 1 \
                and isinstance(value, NDArray):
            return NDArray(self._dist.allreduce(value._data, op="sum"))
        return super()._reduce(value)

    def init(self, key, value):
        from ..ndarray.ndarray import NDArray

        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            arr = v if isinstance(v, NDArray) else NDArray(v)
            if self._dist and self._dist.num_processes() > 1:
                # rank 0's tensor wins — the Horovod broadcast contract
                # (reference horovod.py broadcast_parameters); without it
                # per-rank random init silently diverges
                arr = NDArray(self._dist.broadcast(arr._data, root=0))
            self._store[k] = arr.copy()

    def broadcast(self, key, value, out=None, priority=0):  # noqa: ARG002
        """init (rank 0's tensor wins) + write into `out` directly — the
        base class routes through pull(), which this store forbids."""
        self.init(key, value)
        if out is None:
            return
        keys = key if isinstance(key, (list, tuple)) else [key]
        outs = out if isinstance(out, (list, tuple)) else [out]
        if not isinstance(key, (list, tuple)):
            outs = [out]
        for k, o in zip(keys, outs):
            v = self._store[k]
            for t in (o if isinstance(o, (list, tuple)) else [o]):
                if t is not None:
                    t._set_data(v._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        # parity: the reference's Horovod store forbids pull (allreduce
        # has no server-held value to read back); use pushpull/broadcast
        raise NotImplementedError(
            "Horovod kvstore does not support pull; use pushpull")

    @staticmethod
    def is_capable(capability):
        return False          # no server-side optimizer (reference parity)
