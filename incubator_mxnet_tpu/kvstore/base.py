"""KVStore base + registry (reference: `python/mxnet/kvstore/base.py:74` —
`KVStoreBase` with broadcast/pushpull and a type-string registry, so Trainer
code is backend-agnostic)."""
from __future__ import annotations

__all__ = ["KVStoreBase", "register", "create"]


class KVStoreBase:
    """Key-value store interface: broadcast / push / pull / pushpull."""

    OPTIMIZER = "optimizer"

    _registry: dict = {}

    # -- interface ----------------------------------------------------------
    def broadcast(self, key, value, out):
        raise NotImplementedError

    def pushpull(self, key, value, out=None, priority=0):
        raise NotImplementedError

    def push(self, key, value, priority=0):
        raise NotImplementedError

    def pull(self, key, out=None, priority=0):
        raise NotImplementedError

    def set_optimizer(self, optimizer):
        raise NotImplementedError

    @staticmethod
    def is_capable(capability):  # noqa: ARG004
        return False

    @property
    def type(self):
        return type(self).__name__

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise NotImplementedError

    def load_optimizer_states(self, fname):
        raise NotImplementedError

    # -- registry -----------------------------------------------------------
    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        KVStoreBase._registry[name] = klass
        return klass


register = KVStoreBase.register


def create(name="local"):
    """Create a KVStore (reference: kvstore.cc:41 type-string dispatch).

    Accepted types: 'local', 'device' (single-process, collectives over the
    active mesh), 'dist', 'dist_sync', 'dist_device_sync', 'dist_async'
    (multi-host over DCN via jax.distributed; async degrades to sync —
    collectives are synchronous on TPU, documented in SURVEY.md §2.4),
    'nccl' (alias of 'device'; ICI collectives replace NCCL),
    'horovod'/'byteps' (compatibility facades, `kvstore/horovod.py` /
    `byteps.py`, over the same collectives)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    key = name.lower()
    if key.startswith("dist_async"):
        # straggler semantics change: reference dist_async applies each
        # worker's push immediately (kvstore_dist_server.h ASyncMode);
        # here every update is a synchronous collective
        import warnings

        warnings.warn(
            f"KVStore type {name!r} degrades to synchronous on TPU: "
            "XLA collectives have no async parameter-server mode, so "
            "updates are globally ordered (no stale gradients). Port "
            "scripts relying on async staleness semantics accordingly.",
            UserWarning, stacklevel=2)
    aliases = {
        "nccl": "device",
        "dist_sync": "dist",
        "dist_device_sync": "dist",
        "dist_sync_device": "dist",
        "dist_async": "dist",
        "dist_async_device": "dist",
        "p3": "dist",
        "local_allreduce_cpu": "local",
        "local_allreduce_device": "device",
    }
    key = aliases.get(key, key)
    mapping = {"local": "kvstorelocal", "device": "kvstoredevice",
               "dist": "kvstoredist"}
    klass = KVStoreBase._registry.get(mapping.get(key, key))
    if klass is None:
        raise ValueError(f"unknown KVStore type {name!r}")
    return klass()
