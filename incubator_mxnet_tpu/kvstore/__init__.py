from .base import KVStoreBase, create, register  # noqa: F401
from .byteps import BytePS  # noqa: F401
from .horovod import Horovod  # noqa: F401
from .kvstore import KVStore, KVStoreDevice, KVStoreDist, KVStoreLocal  # noqa: F401
