"""Gradient compression (reference: `src/kvstore/gradient_compression.cc`,
`python/mxnet/kvstore/kvstore.py set_gradient_compression`).

Two codecs:
- "2bit": elements ≥ +threshold quantize to +threshold, ≤ −threshold to
  −threshold, else 0 — with per-key error-feedback residual accumulation
  exactly like the reference's quantize_2bit kernel, so dropped mass is
  carried into later steps (this is what keeps SGD convergent).
- "fp16": cast payload to float16 and back (reference's 1-bit/fp16 family).

TPU-native note: on the wire this is what would ride DCN in a multi-host
run (the reference compresses ps-lite ZPush payloads); in-process stores
apply the same quantize→decompress roundtrip so convergence semantics are
identical everywhere and testable single-host.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ndarray.ndarray import NDArray

__all__ = ["GradientCompression", "create"]


class GradientCompression:
    def __init__(self, type="2bit", threshold=0.5):  # noqa: A002
        if type not in ("2bit", "fp16"):
            raise ValueError(f"unsupported compression type {type!r}; "
                             "expected '2bit' or 'fp16'")
        if type == "2bit" and threshold <= 0:
            raise ValueError("2bit compression needs a positive threshold")
        self.type = type
        self.threshold = float(threshold)
        self._residual: dict = {}  # key -> jax array

    def compress(self, key, value):
        """value (NDArray) → quantized NDArray; updates the residual."""
        v = value._data if isinstance(value, NDArray) else jnp.asarray(value)
        if self.type == "fp16":
            return NDArray(v.astype(jnp.float16).astype(v.dtype))
        t = self.threshold
        r = self._residual.get(key)
        acc = v if r is None else v + r
        q = jnp.where(acc >= t, t, jnp.where(acc <= -t, -t, 0.0))
        q = q.astype(v.dtype)
        self._residual[key] = acc - q
        return NDArray(q)

    def reset(self):
        self._residual.clear()


def create(params) -> GradientCompression:
    """Build from the reference's dict form:
    {'type': '2bit', 'threshold': 0.5}."""
    if isinstance(params, GradientCompression):
        return params
    if not isinstance(params, dict) or "type" not in params:
        raise ValueError("compression_params must be a dict with a 'type'")
    return GradientCompression(type=params["type"],
                               threshold=params.get("threshold", 0.5))
