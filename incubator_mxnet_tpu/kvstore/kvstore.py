"""KVStore implementations over XLA collectives.

Reference mechanisms replaced (SURVEY.md §2.4):
- `KVStoreLocal`/`CommCPU`/`CommDevice` (`src/kvstore/kvstore_local.h:65`,
  `comm.h:104,482`): single-process aggregation → on TPU, gradients computed
  under a sharded train step are already partial sums; `pushpull` applies
  `jax.lax.psum` via shard_map when a mesh is active, else identity.
- `KVStoreDist`/ps-lite (`kvstore_dist.h`): parameter-server push/pull →
  multi-host `jax.distributed` + the same psum over the DCN-connected mesh.
- `KVStoreNCCL` (`kvstore_nccl.h`): NCCL allreduce → ICI psum (alias
  'device').

Async PS mode has no idiomatic TPU equivalent (collectives are synchronous);
'dist_async' is accepted and degrades to synchronous — documented behavior.
"""
from __future__ import annotations

import pickle

from ..ndarray.ndarray import NDArray
from ..telemetry import tracing
from .base import KVStoreBase, register

__all__ = ["KVStore", "KVStoreLocal", "KVStoreDevice", "KVStoreDist"]


class _SingleProcessStore(KVStoreBase):
    def __init__(self):
        self._store: dict = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    @staticmethod
    def _chaos_probe(seam):
        """Fault-injection probe at the sync-point entry, RETRIED under the
        'kvstore' policy: the probe sits before any store mutation, so a
        retry is always safe (idempotent), and an injected fault that
        outlives the budget surfaces as RetryExhausted — the shape a real
        flaky collective would take. Dead branch when chaos is off."""
        from ..fault import injection

        if not injection.injection_enabled(seam):
            return
        from ..fault.retry import RetryPolicy

        RetryPolicy.from_env("kvstore").call(injection.inject_at, seam)

    def set_gradient_compression(self, compression_params):
        """Enable gradient compression on the push leg (reference:
        kvstore.py set_gradient_compression → gradient_compression.cc)."""
        from . import compression

        self._compression = compression.create(compression_params)

    def _maybe_compress(self, key, value):
        if self._compression is None or not isinstance(value, NDArray):
            return value
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(value, RowSparseNDArray):
            return value  # reference: sparse grads are never compressed
        return self._compression.compress(key, value)

    # -- legacy init/push/pull ---------------------------------------------
    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            self._store[k] = v.copy() if isinstance(v, NDArray) else NDArray(v)

    @staticmethod
    def _merge_sparse(vs):
        """Aggregate per-device row_sparse gradient copies: concatenate
        (indices, values) and gather-unique-sum — the CommDevice reduce for
        sparse values (reference: `src/kvstore/kvstore_local.h:232`
        PushImpl row_sparse merge). Stays sparse: only touched rows are
        materialized."""
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        sp = [v for v in vs if isinstance(v, RowSparseNDArray)]
        if len(sp) != len(vs):
            raise ValueError("cannot mix row_sparse and dense values for "
                             "one key in a single push")
        idx = jnp.concatenate([v._sp_indices for v in sp])      # noqa: SLF001
        val = jnp.concatenate([v._sp_values for v in sp])       # noqa: SLF001
        merged = RowSparseNDArray(val, idx, sp[0].shape)
        u, v = merged._canonical()                              # noqa: SLF001
        return RowSparseNDArray(v, u, sp[0].shape)

    def push(self, key, value, priority=0):  # noqa: ARG002
        with tracing.span("kvstore.push"):
            self._push_impl(key, value)

    def _push_impl(self, key, value):
        from ..ndarray.sparse import RowSparseNDArray

        self._chaos_probe("kvstore_push")
        if isinstance(key, (list, tuple)):
            keys, values = key, value
        else:
            # scalar key: a list value is the per-device COPIES of that one
            # key (reference push(key, [list]) aggregation semantics)
            keys, values = [key], [value]
        for k, v in zip(keys, values):
            vs = v if isinstance(v, (list, tuple)) else [v]
            if any(isinstance(x, RowSparseNDArray) for x in vs):
                agg = self._merge_sparse(vs)
            else:
                agg = vs[0]
                for extra in vs[1:]:
                    agg = agg + extra
                agg = self._maybe_compress(k, agg)
            agg = self._reduce(agg)
            if self._updater is not None and k in self._store:
                self._updater(k, agg, self._store[k])
            elif isinstance(agg, RowSparseNDArray):
                # aggregated sparse gradient: the store entry keeps the
                # row_sparse form (reference stores merged buffers in the
                # value's stype) so a following pull/row_sparse_pull sees
                # only touched rows
                self._store[k] = agg.copy()
            elif k in self._store:
                self._store[k]._set_data(agg._data)
            else:
                self._store[k] = agg.copy()

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):  # noqa: ARG002
        """Pull ONLY `row_ids` rows of the stored value as row_sparse
        (reference: `kvstore_local.h:279` PullRowSparseImpl — the
        BERT-scale embedding path: never materialize (vocab, dim))."""
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        if row_ids is None:
            raise ValueError("row_sparse_pull requires row_ids")
        if isinstance(key, (list, tuple)):
            keys = key
            ids = row_ids if isinstance(row_ids, (list, tuple)) \
                else [row_ids] * len(keys)
            outs = out if isinstance(out, (list, tuple)) \
                else [out] * len(keys)
        else:
            # scalar key: list out/row_ids are the per-device TARGETS for
            # that one key, each with its own row set (reference:
            # PullRowSparseImpl per-device row unions)
            keys = [key]
            ids = [row_ids]
            outs = [out]
        results = []
        for k, rid, o in zip(keys, ids, outs):
            v = self._store[k]
            rids = rid if isinstance(rid, (list, tuple)) else [rid]
            tgts = o if isinstance(o, (list, tuple)) else [o]
            if len(rids) != len(tgts) and o is not None:
                raise ValueError(
                    f"row_sparse_pull key {k!r}: {len(tgts)} outs but "
                    f"{len(rids)} row_ids")
            per_key = []
            for rj, t in zip(rids, tgts if o is not None
                             else [None] * len(rids)):
                rid_j = rj._data if isinstance(rj, NDArray) \
                    else jnp.asarray(rj)
                rows = jnp.unique(rid_j.reshape(-1)).astype(jnp.int32)
                if isinstance(v, RowSparseNDArray):
                    res = v.retain(NDArray(rows))
                else:
                    res = RowSparseNDArray(v._data[rows], rows, v.shape)
                if t is not None:
                    t._set_sparse(res._sp_values,     # noqa: SLF001
                                  res._sp_indices)    # noqa: SLF001
                per_key.append(res)
            results.append(per_key if isinstance(rid, (list, tuple))
                           else per_key[0])
        return results if isinstance(key, (list, tuple)) else results[0]

    def pull(self, key, out=None, priority=0, ignore_sparse=True):  # noqa: ARG002
        with tracing.span("kvstore.pull"):
            self._chaos_probe("kvstore_pull")
            if isinstance(key, (list, tuple)):
                keys, outs = key, out if out is not None \
                    else [None] * len(key)
            else:
                # scalar key: a list out is the per-device TARGETS for
                # that key
                keys, outs = [key], [out]
            results = []
            for k, o in zip(keys, outs):
                v = self._store[k]
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    if t is not None:
                        t._set_data(v._data)
                results.append(v)
            return results if isinstance(key, (list, tuple)) else results[0]

    def pushpull(self, key, value, out=None, priority=0):
        """Allreduce: the fused push+pull path (reference: kvstore.h:58).

        For a single key, `value` may be a LIST of per-device gradient
        copies (the reference's `CommDevice::Reduce` input shape,
        `src/kvstore/comm.h:482`): they are summed, then the result is
        written to every entry of `out`."""
        with tracing.span("kvstore.pushpull"):
            self._pushpull_impl(key, value, out)

    def _pushpull_impl(self, key, value, out):
        from ..ndarray.sparse import RowSparseNDArray

        self._chaos_probe("kvstore_push")
        if not isinstance(key, (list, tuple)):
            key, value, out = [key], [value], [out]
        elif out is None:
            out = [None] * len(key)
        for k, v, o in zip(key, value, out):  # noqa: B007
            vs = v if isinstance(v, (list, tuple)) else [v]
            targets = o if isinstance(o, (list, tuple)) else [o]
            if any(isinstance(x, RowSparseNDArray) for x in vs):
                red = self._reduce(self._merge_sparse(vs))
                for t in targets or [None]:
                    if t is None:
                        continue
                    if isinstance(t, RowSparseNDArray) and \
                            isinstance(red, RowSparseNDArray):
                        t._set_sparse(red._sp_values,      # noqa: SLF001
                                      red._sp_indices)     # noqa: SLF001
                    else:
                        t._set_data(red._data)
                if all(t is None for t in targets) and \
                        isinstance(vs[0], RowSparseNDArray) and \
                        isinstance(red, RowSparseNDArray):
                    vs[0]._set_sparse(red._sp_values,      # noqa: SLF001
                                      red._sp_indices)     # noqa: SLF001
                continue
            agg = vs[0]
            for extra in vs[1:]:
                agg = agg + extra
            agg = self._maybe_compress(k, agg)
            red = self._reduce(agg)
            for t in targets:
                if t is not None:
                    t._set_data(red._data)
            if all(t is None for t in targets) and isinstance(vs[0], NDArray):
                vs[0]._set_data(red._data)

    def broadcast(self, key, value, out=None, priority=0):  # noqa: ARG002
        self.init(key, value)
        if out is not None:
            self.pull(key, out)

    def _reduce(self, value):
        return value

    def barrier(self):
        with tracing.span("kvstore.barrier"):
            self._chaos_probe("kvstore_barrier")

    # -- optimizer on kvstore ----------------------------------------------
    def set_optimizer(self, optimizer):
        from ..optimizer import get_updater

        self._optimizer = optimizer
        self._updater = get_updater(optimizer)

    def set_updater(self, updater):
        self._updater = updater

    @staticmethod
    def is_capable(capability):
        return capability in ("optimizer",)

    def save_optimizer_states(self, fname, dump_optimizer=False):
        state = self._updater.get_states(dump_optimizer) if self._updater \
            else pickle.dumps({})
        with open(fname, "wb") as f:
            f.write(state)

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            data = f.read()
        if self._updater is not None:
            self._updater.set_states(data)


@register
class KVStoreLocal(_SingleProcessStore):
    """type='local' — single-device aggregation (identity reduce)."""


@register
class KVStoreDevice(_SingleProcessStore):
    """type='device'/'nccl' — reduce over the active device mesh's data axis
    with psum (ICI); identity when no mesh is active."""

    def _reduce(self, value):
        # A single logical jax array is already globally consistent across
        # the mesh (sharded train steps psum gradients in-program; a
        # replicated array has identical values on every device), so
        # single-array reduce is the identity BY DESIGN. Aggregation of
        # per-device gradient COPIES — the reference's CommDevice role —
        # happens in push/pushpull over list-valued inputs.
        return value


@register
class KVStoreDist(_SingleProcessStore):
    """type='dist*' — multi-host data parallel over DCN.

    Joins the jax multi-process runtime on construction (rendezvous driven
    by `tools/launch.py`-style env: COORDINATOR_ADDRESS, PROCESS_ID,
    NUM_PROCESSES — or the reference's DMLC_* names). `pushpull`/`push`
    REALLY reduce across processes with an XLA collective over the global
    device mesh (the ps-lite ZPush/ZPull replacement,
    `src/kvstore/kvstore_dist.h:266`); `init`/`broadcast` ship rank 0's
    value to everyone (the server broadcast role,
    `kvstore_dist_server.h:157`). 'dist_async' degrades to synchronous —
    collectives have no async-PS analogue (documented divergence)."""

    def __init__(self):
        super().__init__()
        from ..parallel import dist

        dist.initialize()
        self._dist = dist

    @property
    def rank(self):
        return self._dist.rank()

    @property
    def num_workers(self):
        return self._dist.num_processes()

    def _reduce(self, value):
        if self._dist.num_processes() == 1 or not isinstance(value, NDArray):
            return value
        # the cross-host collective is the real pushpull wire hop (ps-lite
        # retried these at the message layer via Resender); allreduce is
        # idempotent, so a transient DCN failure is safely retried here
        from ..fault.retry import RetryPolicy

        return NDArray(RetryPolicy.from_env("kvstore").call(
            self._dist.allreduce, value._data, op="sum"))

    def init(self, key, value):
        keys = key if isinstance(key, (list, tuple)) else [key]
        values = value if isinstance(value, (list, tuple)) else [value]
        for k, v in zip(keys, values):
            arr = v if isinstance(v, NDArray) else NDArray(v)
            if self._dist.num_processes() > 1:
                arr = NDArray(self._dist.broadcast(arr._data, root=0))
            self._store[k] = arr.copy()

    def barrier(self):
        from ..ndarray.ndarray import waitall

        with tracing.span("kvstore.barrier", dist=True):
            waitall()
            self._chaos_probe("kvstore_barrier")
            # sync point doubles as the command channel: queued
            # profile_process='server' commands ship and apply here
            # (reference: KVStoreServerProfilerCommand on ps-lite
            # messages), and telemetry rank-stat summaries ride the same
            # collective
            from .. import profiler
            from ..fault.retry import RetryPolicy
            from ..telemetry import monitor as _telem_monitor

            profiler.sync_remote_commands()
            _telem_monitor.sync_rank_stats()
            RetryPolicy.from_env("kvstore").call(self._dist.barrier)


KVStore = KVStoreLocal
