"""BytePS-compatible kvstore facade (reference:
`python/mxnet/kvstore/byteps.py:29`).

The reference delegates to `byteps.mxnet` (RDMA/PS hybrid push-pull). On
TPU the communication role collapses into the same synchronous
collectives as every other store; this facade preserves the BytePS
class's surface — notably that `broadcast` must be called before
`pushpull` on a key, and `pull` is unsupported — over the mesh /
`jax.distributed` transport.
"""
from __future__ import annotations

from .base import register
from .horovod import Horovod

__all__ = ["BytePS"]


@register
class BytePS(Horovod):
    """`kv = mx.kv.create('byteps')` — push-pull store, no raw pull."""

    def pushpull(self, key, value, out=None, priority=0):
        keys = key if isinstance(key, (list, tuple)) else [key]
        for k in keys:
            if k not in self._store:
                raise ValueError(
                    f"BytePS requires broadcast(key={k!r}) before pushpull "
                    "(reference byteps.py contract)")
        return super().pushpull(key, value, out=out, priority=priority)
