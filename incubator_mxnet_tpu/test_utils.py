"""Test harness library (reference: `python/mxnet/test_utils.py`, 2608 LoC —
assert_almost_equal :656, check_numeric_gradient :1044, check_consistency
:1491, environment :2359). The cpu-vs-tpu `check_consistency` pattern is the
reference's key correctness trick (SURVEY.md §4) and is preserved here."""
from __future__ import annotations

import contextlib
import os

import numpy as onp

from .device import cpu, current_device, tpu
from .ndarray.ndarray import NDArray

__all__ = [
    "assert_almost_equal", "almost_equal", "same", "rand_ndarray", "rand_shape_2d",
    "rand_shape_3d", "rand_shape_nd", "check_numeric_gradient", "check_consistency",
    "check_symbolic_forward", "check_symbolic_backward",
    "environment", "default_device", "default_context", "effective_dtype",
    "assert_allclose",
]


def _to_numpy(a):
    if isinstance(a, NDArray):
        return a.asnumpy()
    return onp.asarray(a)


def default_device():
    return current_device()


default_context = default_device


def effective_dtype(a):
    return _to_numpy(a).dtype


def same(a, b):
    return onp.array_equal(_to_numpy(a), _to_numpy(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return onp.allclose(_to_numpy(a), _to_numpy(b), rtol=rtol, atol=atol,
                        equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    """(reference: test_utils.py:656)"""
    a_np, b_np = _to_numpy(a), _to_numpy(b)
    if not onp.allclose(a_np, b_np, rtol=rtol, atol=atol, equal_nan=equal_nan):
        abs_err = onp.abs(a_np - b_np)
        with onp.errstate(divide="ignore", invalid="ignore"):
            rel = abs_err / (onp.abs(b_np) + atol)
        idx = onp.unravel_index(onp.nanargmax(rel), rel.shape)
        raise AssertionError(
            f"Arrays {names[0]} and {names[1]} not almost equal "
            f"(rtol={rtol}, atol={atol}); max rel err {onp.nanmax(rel):.3e} at "
            f"{idx}: {a_np[idx]!r} vs {b_np[idx]!r}")


assert_allclose = assert_almost_equal


def rand_shape_2d(dim0=10, dim1=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (onp.random.randint(1, dim0 + 1), onp.random.randint(1, dim1 + 1),
            onp.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(onp.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 device=None):  # noqa: ARG001
    if stype != "default":
        raise ValueError("sparse storage is not supported on the TPU build")
    return NDArray(onp.random.uniform(-1, 1, size=shape).astype(dtype),
                   device=device)


def check_numeric_gradient(fn, inputs, eps=1e-3, rtol=1e-2, atol=1e-4):
    """Central finite differences vs autograd (reference: test_utils.py:1044).

    `fn(*inputs)` must return a scalar-reducible NDArray; inputs are NDArrays
    with float dtype.

    Both the analytic backward AND the numeric evaluations run in training
    mode (`autograd.record`) so mode-dependent ops (BatchNorm, Dropout-free
    nets) compare the same function; numeric evaluations are batched per
    perturbed element with float32 ops, so `eps` should stay ≥1e-3 to clear
    rounding noise."""
    from . import autograd

    inputs = [x if isinstance(x, NDArray) else NDArray(x) for x in inputs]
    for x in inputs:
        x.attach_grad()
    with autograd.record():
        out = fn(*inputs)
        loss = out.sum()
    loss.backward()
    analytic = [x.grad.asnumpy().copy() for x in inputs]

    def eval_scalar(args):
        # training-mode forward without backward: the same function the
        # analytic gradient differentiated
        with autograd.record():
            return float(fn(*args).sum().item())

    for i, x in enumerate(inputs):
        base = x.asnumpy().astype("float64")
        num = onp.zeros_like(base)
        flat = base.ravel()
        num_flat = num.ravel()
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = eval_scalar([NDArray(base.astype(x.dtype)) if k == i
                              else inputs[k] for k in range(len(inputs))])
            flat[j] = orig - eps
            fm = eval_scalar([NDArray(base.astype(x.dtype)) if k == i
                              else inputs[k] for k in range(len(inputs))])
            flat[j] = orig
            num_flat[j] = (fp - fm) / (2 * eps)
        assert_almost_equal(analytic[i], num, rtol=rtol, atol=atol,
                            names=(f"analytic[{i}]", f"numeric[{i}]"))


def _parse_location(sym, location):
    """list-or-dict location → {arg_name: NDArray} (reference:
    test_utils.py:932 _parse_location)."""
    arg_names = sym.list_arguments()
    if isinstance(location, dict):
        missing = set(arg_names) - set(location)
        if missing:
            raise ValueError(f"location is missing arguments {sorted(missing)}")
        items = [(k, location[k]) for k in arg_names]
    else:
        if len(location) != len(arg_names):
            raise ValueError(
                f"location has {len(location)} entries for "
                f"{len(arg_names)} arguments {arg_names}")
        items = list(zip(arg_names, location))
    return {k: v if isinstance(v, NDArray) else NDArray(onp.asarray(v))
            for k, v in items}


def _parse_aux(sym, aux_states):
    if aux_states is None:
        return None
    aux_names = sym.list_auxiliary_states()
    if isinstance(aux_states, dict):
        items = [(k, aux_states[k]) for k in aux_names]
    else:
        items = list(zip(aux_names, aux_states))
    return {k: v if isinstance(v, NDArray) else NDArray(onp.asarray(v))
            for k, v in items}


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=1e-6,
                           aux_states=None, ctx=None, equal_nan=False,
                           dtype=None):  # noqa: ARG001
    """Bind `sym` at `location`, run forward, compare every output with
    `expected` (reference: test_utils.py:1194 — same list-or-dict
    contracts). Returns the executor outputs."""
    loc = _parse_location(sym, location)
    ex = sym.bind(device=ctx, args=loc, aux_states=_parse_aux(sym, aux_states))
    outputs = ex.forward(is_train=False)
    if isinstance(expected, dict):
        expected = [expected[k] for k in sym.list_outputs()]
    for name, expect, out in zip(sym.list_outputs(), expected, outputs):
        assert_almost_equal(out, expect, rtol=rtol, atol=atol,
                            names=(f"FORWARD_{name}", f"EXPECTED_{name}"),
                            equal_nan=equal_nan)
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=1e-6, aux_states=None, grad_req="write",
                            ctx=None, equal_nan=False, dtype=None):  # noqa: ARG001
    """Bind `sym` at `location`, backprop `out_grads`, compare each input
    gradient with `expected` (reference: test_utils.py:1277). `grad_req`
    may be a string or a per-argument dict; 'null' entries are skipped.
    Returns the gradient arrays."""
    loc = _parse_location(sym, location)
    arg_names = sym.list_arguments()
    grads = {k: NDArray(onp.zeros(v.shape, "float32"))
             for k, v in loc.items()}
    ex = sym.bind(device=ctx, args=loc, args_grad=grads,
                  grad_req=grad_req, aux_states=_parse_aux(sym, aux_states))
    ex.forward(is_train=True)
    if out_grads is not None and not isinstance(out_grads, (list, tuple)):
        out_grads = [out_grads]
    if out_grads is not None:
        out_grads = [g if isinstance(g, NDArray) else NDArray(onp.asarray(g))
                     for g in out_grads]
    ex.backward(out_grads)
    if isinstance(expected, dict):
        expected_items = expected.items()
    else:
        expected_items = zip(arg_names, expected)
    for name, expect in expected_items:
        if expect is None:
            continue
        req = grad_req.get(name, "write") if isinstance(grad_req, dict) \
            else grad_req
        if req == "null":
            continue
        assert_almost_equal(ex.grad_dict[name], expect, rtol=rtol,
                            atol=atol,
                            names=(f"BACKWARD_{name}", f"EXPECTED_{name}"),
                            equal_nan=equal_nan)
    return [ex.grad_dict.get(n) for n in arg_names]


def check_consistency(fn, inputs, devices=None, rtol=1e-4, atol=1e-5):
    """Run `fn` on each device and require identical outputs (the reference's
    cross-device trick, test_utils.py:1491, adapted cpu-vs-tpu)."""
    devices = devices or [cpu(0), current_device()]
    results = []
    for dev in devices:
        dev_inputs = [x.to_device(dev) if isinstance(x, NDArray) else x
                      for x in inputs]
        out = fn(*dev_inputs)
        if isinstance(out, (list, tuple)):
            results.append([_to_numpy(o) for o in out])
        else:
            results.append([_to_numpy(out)])
    ref = results[0]
    for got, dev in zip(results[1:], devices[1:]):
        for r, g in zip(ref, got):
            assert_almost_equal(g, r, rtol=rtol, atol=atol,
                                names=(str(dev), str(devices[0])))


@contextlib.contextmanager
def environment(*args):
    """Scoped env vars (reference: test_utils.py:2359). Accepts (key, value)
    or a dict; value None removes the variable."""
    if len(args) == 1 and isinstance(args[0], dict):
        env = args[0]
    else:
        env = {args[0]: args[1]}
    saved = {k: os.environ.get(k) for k in env}
    try:
        for k, v in env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
