"""Python custom operators (reference: `python/mxnet/operator.py:434-760` —
CustomOp/CustomOpProp executed via callbacks from the C++ custom-op worker
pool, `src/operator/custom/custom.cc`).

TPU-native: custom ops run eagerly on host (they are Python by definition);
autograd integration goes through the tape's custom-node mechanism
(`autograd.Function`), so `backward()` participates in `loss.backward()`
like any framework op. For jit-compilable custom kernels write pallas or a
C extension (`library.load`); this API is the maximum-flexibility path.
"""
from __future__ import annotations

import numpy as onp

from . import autograd
from .ndarray.ndarray import NDArray

__all__ = ["CustomOp", "CustomOpProp", "register", "Custom", "get_all_registered_operators"]

_REGISTRY: dict = {}


class CustomOp:
    """Base class for custom operator implementations
    (reference: operator.py:434)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write `src` into `dst` honoring the write/add/null request
        (reference: operator.py:452)."""
        if req in ("null", 0):
            return
        src = src if isinstance(src, NDArray) else NDArray(src)
        if req in ("add", "add_to", 3):
            dst._set_data(dst._data + src._data)
        else:
            dst._set_data(src._data)


class CustomOpProp:
    """Operator properties: argument lists, shape/type inference, and the
    CustomOp factory (reference: operator.py:710)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), \
            [in_type[0]] * len(self.list_auxiliary_states())

    def create_operator(self, ctx, in_shapes, in_dtypes):  # noqa: ARG002
        return CustomOp()

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """Class decorator registering a CustomOpProp under `reg_name`
    (reference: operator.py:778). The op is then invocable as
    `operator.Custom(*inputs, op_type=reg_name)` or via the `nd.Custom` /
    `npx.Custom` aliases."""
    def wrap(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register expects a CustomOpProp subclass")
        _REGISTRY[reg_name] = prop_cls
        return prop_cls

    return wrap


def get_all_registered_operators():
    return sorted(_REGISTRY)


class _CustomFunction(autograd.Function):
    """Bridges CustomOp.forward/backward onto the autograd tape."""

    def __init__(self, prop, op, n_out):
        super().__init__()
        self.prop = prop
        self.op = op
        self.n_out = n_out
        self.in_data = None
        self.out_data = None

    def forward(self, *inputs):
        out_shapes = self._out_shapes
        out_dtypes = self._out_dtypes
        import jax.numpy as jnp

        outs = [NDArray(jnp.zeros(s, onp.dtype(d)))
                for s, d in zip(out_shapes, out_dtypes)]
        self.in_data = list(inputs)
        self.out_data = outs
        self.op.forward(is_train=autograd.is_training(),
                        req=["write"] * len(outs),
                        in_data=list(inputs), out_data=outs, aux=[])
        return tuple(outs) if len(outs) > 1 else outs[0]

    def backward(self, *output_grads):
        in_grads = [NDArray(onp.zeros(tuple(x.shape),
                                      onp.dtype(str(x.dtype))))
                    for x in self.in_data]
        self.op.backward(req=["write"] * len(in_grads),
                         out_grad=list(output_grads),
                         in_data=self.in_data, out_data=self.out_data,
                         in_grad=in_grads, aux=[])
        return tuple(in_grads) if len(in_grads) > 1 else in_grads[0]


def Custom(*inputs, op_type, **kwargs):  # noqa: N802
    """Invoke a registered custom op (reference: the generated `nd.Custom`,
    `src/operator/custom/custom.cc` CustomOperator dispatch)."""
    if op_type not in _REGISTRY:
        raise ValueError(f"custom op {op_type!r} is not registered; "
                         f"known: {get_all_registered_operators()}")
    prop = _REGISTRY[op_type](**kwargs)
    arrays = [a if isinstance(a, NDArray) else NDArray(a) for a in inputs]
    n_args = len(prop.list_arguments())
    if len(arrays) != n_args:
        raise ValueError(f"{op_type} expects {n_args} inputs "
                         f"({prop.list_arguments()}), got {len(arrays)}")
    in_shapes = [tuple(a.shape) for a in arrays]
    _, out_shapes, _ = prop.infer_shape(list(in_shapes))
    in_types = [str(a.dtype) for a in arrays]
    _, out_types, _ = prop.infer_type(list(in_types))
    op = prop.create_operator(None, in_shapes, in_types)
    fn = _CustomFunction(prop, op, len(out_shapes))
    fn._out_shapes = [tuple(s) for s in out_shapes]
    fn._out_dtypes = out_types
    return fn(*arrays)
