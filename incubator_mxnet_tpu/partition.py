"""Pluggable graph-partition / subgraph-rewrite backends.

Reference: `src/operator/subgraph/subgraph_property.h` — SubgraphSelector
(`:88`, walks the nnvm graph selecting connected op sets), SubgraphProperty
(`:265`, replaces the match with an accelerated fused node), and the named
backend registry (`:543`, `MXNET_REGISTER_SUBGRAPH_PROPERTY`), driven by
`HybridBlock.optimize_for(backend)` (`python/mxnet/gluon/block.py:1190`).

TPU-native design. The reference matches patterns over the nnvm graph —
a graph whose nodes ARE framework ops. A raw jaxpr is too low-level for
that (one `softmax` becomes a reduce/sub/exp/sum/div DAG), so when a
backend is active each funnel op is OUTLINED: `apply_op` wraps the op's
pure function in `jax.jit`, making it a single `pjit` equation whose
`name` param is the op name. The traced forward then yields a jaxpr whose
equations correspond 1:1 to framework ops — the nnvm-graph analogue —
and subgraph matching is a scan over op names with dataflow chaining.
Matched chains are spliced out and replaced by the backend's fused
implementation (re-traced in place); XLA inlines the nested pjit calls,
so an un-matched outlined op costs nothing after compilation.

Two hook levels, mirroring the reference:
- `Backend.rewrite_block(block, **opts)` — structural rewrite before
  tracing (the quantize pass level: swaps child blocks in place).
- `Backend.patterns` — dataflow-level rewrites applied to the traced
  graph at hybridize/compile time (the dnnl fuse-property level).

Built-in backends:
- "flash_attention": rewrites unfused batch_dot→softmax→batch_dot
  attention written with framework ops into the pallas flash-attention
  kernel (`ops/flash_attention.py`).
- "int8": block-level post-training quantization
  (`contrib.quantization.quantize_net`) — calibration data passed through
  `optimize_for(..., backend_opts=...)`.
"""
from __future__ import annotations

import threading

__all__ = ["Pattern", "Backend", "register_backend", "get_backend",
           "list_backends", "backend_scope", "active_backend",
           "outline_op", "rewrite_jaxpr", "apply_backend",
           "segment_pattern", "graph_op_names"]

_BACKENDS: dict = {}


class Pattern:
    """A dataflow chain of op names to fuse.

    - `ops`: list of stages; each stage is an op name or a tuple of
      acceptable names. A stage may be suffixed "?" (optional) when given
      as a string, e.g. "true_divide?" — skipped if the next eqn doesn't
      match it. Names match either outlined funnel ops (pjit name) or raw
      jaxpr primitives (e.g. "div", "exp").
    - `replace(eqns, invals)`: called with the MATCHED JaxprEqns and the
      chain's input values (traced); returns the replacement output(s).
      Must be trace-compatible (pure jax).
    - `guard(eqns)`: optional predicate to reject matches (inspect params
      / avals).
    """

    def __init__(self, name, ops, replace, guard=None):
        self.name = name
        self.ops = ops
        self.replace = replace
        self.guard = guard

    def stage(self, i):
        spec = self.ops[i]
        optional = False
        if isinstance(spec, str):
            if spec.endswith("?"):
                spec, optional = spec[:-1], True
            names = (spec,)
        else:
            names = tuple(spec)
        return names, optional


class Backend:
    """A named partition backend (reference: SubgraphProperty subclass +
    MXNET_REGISTER_SUBGRAPH_PROPERTY)."""

    name: str = ""
    #: funnel ops to outline into single named eqns while tracing under
    #: this backend; "*" outlines every funnel op
    mark_ops: frozenset | str = frozenset()
    patterns: list = []

    def rewrite_block(self, block, **opts):   # noqa: ARG002
        """Structural hook run by optimize_for BEFORE tracing."""
        return block


def register_backend(backend):
    """Register a Backend instance (or class — instantiated); returns it,
    usable as a class decorator."""
    b = backend() if isinstance(backend, type) else backend
    if not b.name:
        raise ValueError("backend needs a name")
    _BACKENDS[b.name] = b
    return backend


def get_backend(name):
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(
            f"unknown partition backend {name!r}; registered: "
            f"{sorted(_BACKENDS)}") from None


def list_backends():
    return sorted(_BACKENDS)


# ---------------------------------------------------------------------------
# backend scope + op outlining (the graph-building half)
# ---------------------------------------------------------------------------

class _Scope(threading.local):
    def __init__(self):
        self.backend = None


_SCOPE = _Scope()


class backend_scope:
    def __init__(self, backend):
        self._b = backend

    def __enter__(self):
        self._prev = _SCOPE.backend
        _SCOPE.backend = self._b
        return self._b

    def __exit__(self, *exc):
        _SCOPE.backend = self._prev
        return False


def active_backend():
    return _SCOPE.backend


_OUTLINED_PREFIX = "mxop_"


def outline_op(name, pure_fn, static_info=None):
    """When a backend scope is active and `name` is marked, wrap the op's
    pure function so it traces as ONE named pjit equation. `static_info`
    (closed-over op parameters like softmax's axis) is encoded into the
    eqn name — "mxop_softmax|axis=-1" — so pattern guards can inspect it
    via `eqn_op_info`."""
    b = _SCOPE.backend
    if b is None:
        return pure_fn
    marked = b.mark_ops == "*" or name in b.mark_ops
    if not marked:
        return pure_fn
    import jax

    # the pjit eqn's `name` param comes from the wrapped fn's __name__
    def _outlined(*args, **kwargs):
        return pure_fn(*args, **kwargs)

    suffix = ""
    if static_info:
        suffix = "|" + ",".join(f"{k}={static_info[k]}"
                                for k in sorted(static_info))
    _outlined.__name__ = _OUTLINED_PREFIX + name + suffix
    # trace-time outlining shim, inlined into the enclosing cached-graph
    # program — never a standalone runtime program family
    return jax.jit(_outlined)  # noqa: FL012


def _eqn_op_name(eqn):
    """Framework-op name of an eqn: outlined jit-call name (mxop_*) or the
    raw primitive name. (jax names the call primitive 'jit' as of 0.9,
    'pjit' before.)"""
    if eqn.primitive.name in ("jit", "pjit"):
        name = eqn.params.get("name", "")
        if name.startswith(_OUTLINED_PREFIX):
            return name[len(_OUTLINED_PREFIX):].split("|", 1)[0]
        return f"pjit:{name}"
    return eqn.primitive.name


def eqn_op_info(eqn):
    """Parse an outlined eqn's static_info suffix back into a dict of
    strings ("mxop_softmax|axis=-1" -> {"axis": "-1"}); {} otherwise."""
    if eqn.primitive.name not in ("jit", "pjit"):
        return {}
    name = eqn.params.get("name", "")
    if not name.startswith(_OUTLINED_PREFIX) or "|" not in name:
        return {}
    out = {}
    for part in name.split("|", 1)[1].split(","):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# jaxpr chain matching + splicing (the SubgraphSelector/Property half)
# ---------------------------------------------------------------------------

def _match_chain(eqns, start, pattern, use_counts, outvars):
    """Try to match `pattern` starting at eqns[start]. Chain rule: each
    next stage consumes an output of the previous stage's eqn, and every
    intermediate output is used EXACTLY once and is not a graph output
    (same single-consumer discipline as SubgraphSelector::SelectOutput).
    Returns (matched_eqns, skipped_optional_count) or None."""
    from jax.extend.core import Var

    matched = []
    i = start
    stage = 0
    n = len(pattern.ops)
    prev_outs: set = set()
    while stage < n:
        names, optional = pattern.stage(stage)
        if i >= len(eqns):
            if optional:
                stage += 1
                continue
            return None
        eqn = eqns[i]
        name = _eqn_op_name(eqn)
        consumes_prev = (not matched) or any(
            isinstance(v, Var) and v in prev_outs for v in eqn.invars)
        if name in names and consumes_prev:
            if matched:
                # intermediates: single consumer, not a graph output
                for v in prev_outs:
                    if use_counts.get(v, 0) != 1 or v in outvars:
                        return None
            matched.append(eqn)
            prev_outs = set(eqn.outvars)
            stage += 1
            i += 1
        elif optional:
            stage += 1
        elif not matched:
            return None
        else:
            # a foreign eqn interleaved: only tolerable if it doesn't
            # consume the chain (dead-simple scheduling independence);
            # bail out to keep the match conservative
            if any(isinstance(v, Var) and v in prev_outs for v in eqn.invars):
                return None
            i += 1
            if i - start > len(pattern.ops) + 8:
                return None
    return matched if len(matched) >= 2 or n == 1 else None


def rewrite_jaxpr(closed, patterns):
    """Scan a ClosedJaxpr for pattern chains; splice each match out and
    replace it with the pattern's fused implementation (traced in place).
    Returns (new_closed_jaxpr, n_rewrites)."""
    import jax
    import jax.extend.core as jec
    from jax.extend.core import Var

    jaxpr = closed.jaxpr
    eqns = list(jaxpr.eqns)
    use_counts: dict = {}
    for eqn in eqns:
        for v in eqn.invars:
            if isinstance(v, Var):
                use_counts[v] = use_counts.get(v, 0) + 1
    outvars = set(v for v in jaxpr.outvars if isinstance(v, Var))

    n_rewrites = 0
    for pattern in patterns:
        i = 0
        while i < len(eqns):
            m = _match_chain(eqns, i, pattern, use_counts, outvars)
            if not m:
                i += 1
                continue
            if pattern.guard is not None and not pattern.guard(m):
                i += 1
                continue
            produced = set()
            for eqn in m:
                produced.update(eqn.outvars)
            # chain inputs: invars not produced inside the match
            in_vars, seen = [], set()
            for eqn in m:
                for v in eqn.invars:
                    if isinstance(v, Var) and v not in produced \
                            and v not in seen:
                        in_vars.append(v)
                        seen.add(v)
            final_outs = list(m[-1].outvars)

            # trace the replacement against the input avals
            def _repl(*invals, _m=m):
                out = pattern.replace(_m, invals)
                return out if isinstance(out, tuple) else (out,)

            sub = jax.make_jaxpr(_repl)(*[v.aval for v in in_vars])
            if [v.aval.shape for v in sub.jaxpr.outvars] != \
               [v.aval.shape for v in final_outs]:
                raise ValueError(
                    f"partition backend pattern {pattern.name!r}: "
                    "replacement output shapes "
                    f"{[v.aval.shape for v in sub.jaxpr.outvars]} != matched "
                    f"{[v.aval.shape for v in final_outs]}")
            # splice: remap sub-jaxpr invars -> chain inputs, sub outvars ->
            # chain outputs; constvars lift into the outer closed consts
            mapping = dict(zip(sub.jaxpr.invars, in_vars))
            const_vars = list(sub.jaxpr.constvars)
            new_constvars = []
            new_consts = []
            for cv, cval in zip(const_vars, sub.consts):
                new_constvars.append(cv)
                new_consts.append(cval)
            out_map = dict(zip(sub.jaxpr.outvars, final_outs))

            def _sub_var(v, mapping=mapping, out_map=out_map):
                if not isinstance(v, Var):
                    return v
                return out_map.get(v, mapping.get(v, v))

            spliced = []
            for eqn in sub.jaxpr.eqns:
                spliced.append(eqn.replace(
                    invars=[_sub_var(v) for v in eqn.invars],
                    outvars=[_sub_var(v) for v in eqn.outvars]))
            # a replacement outvar that is itself an invar/constant (pure
            # pass-through) can't be expressed by splicing alone
            for sv, ov in out_map.items():
                if sv in mapping or not isinstance(sv, Var):
                    raise ValueError(
                        f"pattern {pattern.name!r}: replacement may not "
                        "pass an input straight through to an output")

            # insert the replacement where the LAST matched eqn sat: any
            # interleaved (non-consuming) eqn between the matched ones may
            # PRODUCE a chain input (e.g. a v projection traced after the
            # softmax), so splicing at the chain head would use it before
            # definition
            last_pos = eqns.index(m[-1])
            insert_at = sum(1 for e in eqns[:last_pos] if e not in m)
            kept = [e for e in eqns if e not in m]
            eqns = kept[:insert_at] + spliced + kept[insert_at:]
            # rebuild use counts (splice changed the graph)
            use_counts = {}
            for eqn in eqns:
                for v in eqn.invars:
                    if isinstance(v, Var):
                        use_counts[v] = use_counts.get(v, 0) + 1
            jaxpr = jaxpr.replace(
                eqns=eqns, constvars=list(jaxpr.constvars) + new_constvars)
            closed = jec.ClosedJaxpr(jaxpr,
                                     list(closed.consts) + new_consts)
            n_rewrites += 1
            i += 1
    return closed, n_rewrites


def segment_pattern(ops, name):
    """Pattern that fuses a matched op-name chain into ONE compiled
    segment named `name` — semantics-preserving (the replacement
    re-binds the matched eqns under a single named jit). This is the
    directive form extension passes/partitioners emit
    (`library.py` v2 `{"fuse"/"subgraphs": [{"ops": [...]}]}`)."""
    def replace(eqns, invals):
        import jax
        from jax.extend.core import Var

        produced = set()
        for e in eqns:
            produced.update(e.outvars)
        in_vars, seen = [], set()
        for e in eqns:
            for v in e.invars:
                if isinstance(v, Var) and v not in produced \
                        and v not in seen:
                    in_vars.append(v)
                    seen.add(v)

        def run(*xs):
            env = dict(zip(in_vars, xs))

            def read(v):
                return env[v] if isinstance(v, Var) else v.val

            for e in eqns:
                outs = e.primitive.bind(*[read(v) for v in e.invars],
                                        **e.params)
                if not e.primitive.multiple_results:
                    outs = [outs]
                for ov, o in zip(e.outvars, outs):
                    env[ov] = o
            res = tuple(env[v] for v in eqns[-1].outvars)
            return res if len(res) > 1 else res[0]

        run.__name__ = name
        # pattern-replacement body, traced inline with tracer invals —
        # not a runtime program family
        return jax.jit(run)(*invals)  # noqa: FL012

    return Pattern(name, list(ops), replace)


def graph_op_names(closed):
    """Linear op-name view of a traced graph — the serialization handed
    to extension passes/partitioners."""
    return [_eqn_op_name(e) for e in closed.jaxpr.eqns]


def apply_backend(fn, backend):
    """Wrap a pure traced fn so that, at trace time, it is (1) traced with
    the backend's ops outlined, (2) pattern-rewritten, (3) inlined back
    into the surrounding trace. Shape-polymorphic via jax's own caching —
    the rewrite happens per trace. A backend may define
    `dynamic_patterns(closed)` to derive patterns from the traced graph
    (extension partitioners do — their directives depend on the graph)."""
    import jax
    import jax.tree_util as jtu

    def wrapped(*args):
        with backend_scope(backend):
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
        patterns = list(backend.patterns)
        dyn = getattr(backend, "dynamic_patterns", None)
        if dyn is not None:
            patterns += list(dyn(closed))
        if patterns:
            closed, n = rewrite_jaxpr(closed, patterns)
            backend.last_rewrites = n   # observability for tests/logging
        flat, _ = jtu.tree_flatten(args)
        out_flat = jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *flat)
        treedef = jtu.tree_structure(
            out_shape, is_leaf=lambda x: hasattr(x, "shape"))
        return jtu.tree_unflatten(treedef, out_flat)

    return wrapped


# ---------------------------------------------------------------------------
# built-in backends
# ---------------------------------------------------------------------------

def _flash_guard(eqns):
    """Shapes must identify the standard attention layout unambiguously:
    scores=(B,T,Tk) from q=(B,T,d) @ k^T with k=(B,Tk,d)."""
    qk = eqns[0]
    # the QK stage must literally be q @ k^T: the transpose flags ride in
    # the outlined eqn's static_info (shape inference alone cannot tell
    # q@k^T from q@k when k is square — r3 ADVICE). Outlined batch_dot
    # always carries the flags; their absence means an un-flagged matmul
    # we refuse to rewrite.
    qk_info = eqn_op_info(qk)
    if qk_info.get("transpose_b") != "True" or \
            qk_info.get("transpose_a") == "True":
        return False
    q_aval, k_aval = qk.invars[0].aval, qk.invars[1].aval
    s_aval = qk.outvars[0].aval
    if len(q_aval.shape) != 3 or len(k_aval.shape) != 3:
        return False
    b, t, d = q_aval.shape
    if k_aval.shape[0] != b or k_aval.shape[2] != d:
        return False
    tk = k_aval.shape[1]
    if tuple(s_aval.shape) != (b, t, tk):
        return False
    # the PV stage must be transpose-free: att(B,T,Tk) @ v(B,Tk,d)
    pv_info = eqn_op_info(eqns[-1])
    if pv_info.get("transpose_a") == "True" or \
            pv_info.get("transpose_b") == "True":
        return False
    # the fused kernel softmaxes the LAST axis; reject chains whose
    # softmax ran on any other axis (the outliner encodes it in the name)
    soft = eqns[-2]
    axis = eqn_op_info(soft).get("axis")
    if axis not in ("-1", str(len(s_aval.shape) - 1)):
        return False
    # optional scale stage must be a literal scalar (the pallas kernel
    # takes sm_scale as a static float)
    for eqn in eqns[1:-2]:
        from jax.extend.core import Literal

        if _eqn_op_name(eqn) in ("div", "mul"):
            if not isinstance(eqn.invars[1], Literal):
                return False
    # final stage consumes softmax output against v=(B,Tk,d)
    v_aval = eqns[-1].invars[1].aval
    return tuple(v_aval.shape) == (b, tk, d)


def _flash_replace(eqns, invals):
    from jax.extend.core import Literal

    from .ops.flash_attention import flash_attention

    q, k, v = invals[0], invals[1], invals[-1]
    scale = 1.0   # no scale stage matched => the unfused math had none
    for eqn in eqns[1:-2]:
        name = _eqn_op_name(eqn)
        if name in ("div", "mul") and isinstance(eqn.invars[1], Literal):
            val = float(eqn.invars[1].val)
            scale = (1.0 / val) if name == "div" else val
    o = flash_attention(q[:, None], k[:, None], v[:, None],
                        sm_scale=scale)
    return o[:, 0]


class FlashAttentionBackend(Backend):
    """Rewrites unfused `batch_dot → (scale) → softmax → batch_dot`
    attention written with framework ops into the fused flash-attention
    kernel — the role the reference's dnnl transformer-QK subgraph
    property plays (`src/operator/subgraph/dnnl/
    dnnl_transformer_qk_property.h`), here targeting the pallas/XLA fused
    kernel. Softmax is assumed on the last axis (the attention
    convention); masked_softmax chains are NOT matched (a dense mask
    cannot be recovered into the kernel's per-sequence lengths)."""

    name = "flash_attention"
    mark_ops = frozenset({"batch_dot", "softmax"})
    # the scale stage is optional: a bare batch_dot→softmax→batch_dot
    # chain fuses with sm_scale=1
    patterns = [Pattern(
        "qk_softmax_v",
        ["batch_dot", "div?", "mul?", "softmax", "batch_dot"],
        _flash_replace, guard=_flash_guard)]


class Int8Backend(Backend):
    """Block-level post-training INT8 quantization as a partition backend
    (reference: the quantize pass registered as SG property 'ONEDNN_QUANTIZE',
    `src/operator/subgraph/dnnl/dnnl_subgraph_property.cc`). Options are
    forwarded to `contrib.quantization.quantize_net` — pass
    `backend_opts={'calib_data': ..., 'calib_mode': 'entropy'}`."""

    name = "int8"

    def rewrite_block(self, block, **opts):
        from .contrib.quantization import quantize_net

        return quantize_net(block, **opts)


register_backend(FlashAttentionBackend)
register_backend(Int8Backend)
