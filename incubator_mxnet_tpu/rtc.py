"""Runtime kernel compilation (reference: `python/mxnet/rtc.py` —
`CudaModule` compiles CUDA C with NVRTC at runtime and exposes kernels as
callable ops; impl `src/common/rtc.cc:31`).

TPU-native: the runtime-codegen role is played by **pallas**. `PallasModule`
wraps user-written pallas kernel functions into framework ops that execute
through the `apply_op` funnel (tape-recorded, AMP-aware, async). `CudaModule`
exists for API parity and raises with a pointer to the pallas path — there
is no CUDA on a TPU host.
"""
from __future__ import annotations

from .ndarray.ndarray import apply_op_flat

__all__ = ["CudaModule", "PallasModule"]


class CudaModule:
    """Unsupported on TPU (`rtc.py:33` in the reference)."""

    def __init__(self, *args, **kwargs):  # noqa: ARG002
        raise RuntimeError(
            "CudaModule (NVRTC runtime compilation) has no TPU equivalent; "
            "write a pallas kernel and wrap it with mx.rtc.PallasModule — "
            "see incubator_mxnet_tpu/ops/flash_attention.py for the "
            "pattern.")


class PallasKernel:
    """One compiled-on-first-call pallas kernel bound to a grid/blockspec
    factory. Create via `PallasModule.get_kernel`."""

    def __init__(self, name, builder):
        self._name = name
        self._builder = builder

    def __call__(self, *args, **static_kwargs):
        def fn(*tensor_vals):
            return self._builder(*tensor_vals, **static_kwargs)

        return apply_op_flat(f"pallas:{self._name}", fn, args, {})

    def launch(self, args, device=None, grid_dims=None, block_dims=None):  # noqa: ARG002
        """Reference-signature launch (`rtc.py:116 CudaKernel.launch`);
        grid/block dims are owned by the pallas BlockSpec, so they are
        accepted and ignored."""
        out = self(*args)
        return out if isinstance(out, tuple) else (out,)


class PallasModule:
    """Collection of pallas kernels exposed as framework ops
    (the `CudaModule` analogue).

    `kernels` maps name → builder. A builder takes the unwrapped jax-array
    operands (plus static keyword args) and returns the kernel result —
    typically via `jax.experimental.pallas.pallas_call`. Example::

        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def add_one(x):
            def kern(x_ref, o_ref):
                o_ref[...] = x_ref[...] + 1.0
            return pl.pallas_call(
                kern, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)

        mod = mx.rtc.PallasModule({"add_one": add_one})
        y = mod.get_kernel("add_one")(x_ndarray)

    Autodiff: `pallas_call` has no automatic VJP — a builder that must be
    differentiable should wrap its kernel in `jax.custom_vjp` with a
    backward kernel (the pattern `ops/flash_attention.py` uses); the funnel
    then records it on the tape like any other op.
    """

    def __init__(self, kernels: dict):
        if not isinstance(kernels, dict) or not kernels:
            raise ValueError("PallasModule expects a non-empty dict of "
                             "name -> pallas builder callables")
        self._kernels = {name: PallasKernel(name, fn)
                         for name, fn in kernels.items()}

    def get_kernel(self, name, signature=None):  # noqa: ARG002
        """Look up a kernel (`rtc.py:74 CudaModule.get_kernel`; the
        signature string is unnecessary — shapes/dtypes come from the
        operands at call time)."""
        try:
            return self._kernels[name]
        except KeyError:
            raise ValueError(
                f"kernel {name!r} not in module; have "
                f"{sorted(self._kernels)}") from None

    def __contains__(self, name):
        return name in self._kernels
