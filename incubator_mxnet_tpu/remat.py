"""Activation rematerialization (memory-opt) policies.

Reference: `MXNET_BACKWARD_DO_MIRROR` (mirror almost all activations —
recompute them in backward) and `MXNET_MEMORY_OPT` (the graph memory
optimizer) — `docs/static_site/src/pages/api/faq/env_var.md:230-238`,
implemented by the nnvm mirror pass (`src/nnvm/gradient.cc`).

TPU-native: the same trade is `jax.checkpoint` over the compiled forward —
the backward recomputes from checkpointed inputs instead of holding every
activation to the end of the step. The `policy` argument picks WHAT may be
saved (jax.checkpoint_policies):

- ``remat=True`` / ``"nothing_saveable"``: save nothing, recompute
  everything — the DO_MIRROR semantic.
- ``"dots_saveable"``: save matmul/conv outputs (MXU work), recompute
  elementwise/VPU ops — the balanced MEMORY_OPT semantic.
- any other `jax.checkpoint_policies` name, or a policy callable.

Environment parity: setting ``MXNET_BACKWARD_DO_MIRROR=1`` or
``MXNET_MEMORY_OPT=1`` applies the corresponding default to every
`hybridize()` / `DataParallel` that doesn't pass ``remat`` explicitly.

Measurement: `saved_bytes(fn, *args)` sums the autodiff residuals a
function would keep live between forward and backward — the quantity
remat controls. (Final HBM peaks are XLA's call; the tunneled AOT client
does not expose faithful buffer assignment, so the residual ledger is the
framework-level contract we can pin.)
"""
from __future__ import annotations

import os

__all__ = ["resolve_policy", "wrap", "saved_bytes"]

_TRUE = ("1", "true", "yes", "on")


def resolve_policy(spec):
    """Normalize a remat spec to (active, policy-or-None).

    spec: None (consult env), False (off), True (nothing_saveable),
    a policy name string, or a callable policy."""
    if spec is None:
        if os.environ.get("MXNET_BACKWARD_DO_MIRROR", "").lower() in _TRUE:
            spec = True
        elif os.environ.get("MXNET_MEMORY_OPT", "").lower() in _TRUE:
            spec = "dots_saveable"
        else:
            return False, None
    if spec is False:
        return False, None
    import jax

    if spec is True:
        return True, jax.checkpoint_policies.nothing_saveable
    if callable(spec):
        return True, spec
    policy = getattr(jax.checkpoint_policies, str(spec), None)
    if policy is None:
        raise ValueError(
            f"unknown remat policy {spec!r}; see jax.checkpoint_policies")
    return True, policy


def wrap(fn, spec):
    """jax.checkpoint-wrap `fn` per the resolved spec (identity if off)."""
    active, policy = resolve_policy(spec)
    if not active:
        return fn
    import jax

    return jax.checkpoint(fn, policy=policy)


def saved_bytes(fn, *args):
    """Total bytes of autodiff residuals `fn` saves for backward — the
    live forward→backward memory the remat policy governs."""
    try:
        from jax.ad_checkpoint import saved_residuals
    except ImportError:   # public alias removed in jax 0.9
        from jax._src.ad_checkpoint import saved_residuals

    total = 0
    for aval, _src in saved_residuals(fn, *args):
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for s in shape:
            n *= s
        total += n * dtype.itemsize
    return total
