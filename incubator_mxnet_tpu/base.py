"""Base utilities: dtype handling, errors, registries.

TPU-native re-design of the reference's bootstrap layer
(`python/mxnet/base.py` in Apache MXNet 2.0). Where the reference loads
`libmxnet.so` over ctypes and code-generates op modules from the C registry
(`python/mxnet/base.py:633`), we register ops in pure Python over jax and
keep an introspectable registry for signature/docs parity.
"""
from __future__ import annotations

import numpy as onp

__all__ = [
    "MXNetError",
    "NotSupportedForSparseNDArray",
    "np_dtype",
    "dtype_name",
    "string_types",
    "numeric_types",
    "integer_types",
    "_OP_REGISTRY",
    "register_op_meta",
    "list_ops",
]


class MXNetError(RuntimeError):
    """Framework-level error (parity with mxnet.base.MXNetError)."""


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__(f"Function {function.__name__} is not supported for sparse NDArray")


string_types = (str,)
numeric_types = (float, int, onp.generic)
integer_types = (int, onp.integer)

_DTYPE_ALIASES = {
    "float32": "float32",
    "float64": "float64",
    "float16": "float16",
    "bfloat16": "bfloat16",
    "uint8": "uint8",
    "uint16": "uint16",
    "uint32": "uint32",
    "uint64": "uint64",
    "int8": "int8",
    "int16": "int16",
    "int32": "int32",
    "int64": "int64",
    "bool": "bool",
}


def np_dtype(dtype):
    """Normalize a dtype-like object to a numpy/jax dtype."""
    import jax.numpy as jnp

    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _DTYPE_ALIASES.get(dtype, dtype)
        if name == "bfloat16":
            return jnp.bfloat16
        return onp.dtype(name)
    if dtype in (float,):
        return onp.dtype("float32")
    if dtype in (int,):
        return onp.dtype("int32")
    if dtype in (bool,):
        return onp.dtype("bool")
    return onp.dtype(dtype) if not hasattr(dtype, "dtype") else dtype


def dtype_name(dtype) -> str:
    import jax.numpy as jnp

    if dtype is None:
        return "None"
    if dtype == jnp.bfloat16:
        return "bfloat16"
    return onp.dtype(dtype).name


# ---------------------------------------------------------------------------
# Op registry: keeps (name, namespace, fn, doc) so `list_ops` and docs tools
# can introspect, mirroring the reference's NNVM registry role
# (`src/operator/` NNVM_REGISTER_OP) without code generation.
# ---------------------------------------------------------------------------
_OP_REGISTRY: dict = {}


def register_op_meta(name: str, namespace: str, fn) -> None:
    _OP_REGISTRY[f"{namespace}.{name}"] = fn


def list_ops(namespace: str | None = None):
    if namespace is None:
        return sorted(_OP_REGISTRY)
    prefix = namespace + "."
    return sorted(k[len(prefix):] for k in _OP_REGISTRY if k.startswith(prefix))
