"""Generic object registry (reference: `python/mxnet/registry.py` —
`get_register_func`/`get_create_func`/`get_alias_func`, used by
initializers, optimizers and lr schedulers for string-config creation)."""
from __future__ import annotations

import json

__all__ = ["get_register_func", "get_alias_func", "get_create_func"]

_REGISTRIES: dict = {}


def _registry(base_class):
    return _REGISTRIES.setdefault(base_class, {})


def get_register_func(base_class, nickname):
    """Build a @register decorator for `base_class` (`registry.py:38`)."""
    registry = _registry(base_class)

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise TypeError(f"{klass} must subclass {base_class}")
        key = (name or klass.__name__).lower()
        registry[key] = klass
        return klass

    register.__name__ = f"register_{nickname}"
    return register


def get_alias_func(base_class, nickname):
    """Build an @alias("name", ...) decorator (`registry.py:90`)."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass

        return reg

    alias.__name__ = f"alias_{nickname}"
    return alias


def get_create_func(base_class, nickname):
    """Build a create(name_or_instance, **kwargs) factory
    (`registry.py:120`). Accepts an instance (returned as-is), a name, or
    a json string ``["name", {kwargs}]``."""
    registry = _registry(base_class)

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            return args[0]
        if not args:
            raise ValueError(f"create_{nickname} needs a name")
        name, args = args[0], args[1:]
        if isinstance(name, str) and name.startswith("["):
            name, cfg = json.loads(name)
            kwargs = {**cfg, **kwargs}
        klass = registry.get(str(name).lower())
        if klass is None:
            raise ValueError(
                f"{name!r} is not registered; known {nickname}s: "
                f"{sorted(registry)}")
        return klass(*args, **kwargs)

    create.__name__ = f"create_{nickname}"
    return create
