"""Static sharding / partition-spec analyzer (`mx.analysis.shardcheck`).

GSPMD (Xu et al., 2021) validates and propagates shardings at compile
time; a wrong or missing PartitionSpec in THIS stack historically failed
only at pod runtime — as a silent full replication, a per-device OOM, or
an all-gather on the decode hot path. `shardcheck` is the pre-flight
analogue: it abstract-evaluates a program against a mesh (real, abstract,
or a plain ``{"axis": size}`` dict) and emits typed findings SC001-SC006
(`findings.SHARD_RULES`) before any chip is touched.

Three analysis tiers, each running when its inputs are available:

1. **spec tier** (always): pure host math over ``(aval, spec, mesh)``
   leaves — SC001 unconstrained large params, SC002 divisibility, SC003
   unknown axes, and the per-device byte estimate behind SC006.
2. **eval_shape tier** (needs ``fn``): output avals via `jax.eval_shape`
   + a jaxpr walk counting explicit collectives; donated-argument
   aliasing is resolved here (SC004) and output bytes enter the SC006
   estimate.
3. **simulated-mesh tier** (needs a real `jax.sharding.Mesh`, e.g. a CPU
   host forced to N devices via
   ``XLA_FLAGS=--xla_force_host_platform_device_count=N``): the program
   is lowered and compiled under the declared shardings and the HLO text
   is scanned for ``all-gather``/``all-reduce``/``reduce-scatter``/
   ``collective-permute``/``all-to-all`` with estimated bytes moved per
   step (SC005 flags full-operand re-materialization).

Env knobs (registered in `util._ENV_KNOBS`):
- ``MXNET_SHARDCHECK=warn|raise`` — trainers run shardcheck at
  construction and log/raise on findings (off by default).
- ``MXNET_SHARDCHECK_HBM_GB`` — per-device HBM budget for SC006.
"""
from __future__ import annotations

import logging
import math
import re

from .. import util
from ..base import MXNetError
from .findings import SHARD_RULES, ShardReport  # noqa: F401

__all__ = ["shardcheck", "SHARD_RULES", "ShardReport"]

_LOG = logging.getLogger("mxnet.analysis")

# Default SC001 threshold: replicating anything under 1 MiB is noise.
_REPLICATED_MIN_BYTES = 1 << 20

# HLO collective mnemonics scanned in the compiled text (tier 3) with the
# result-shape regex: `%x = f32[128,64]{1,0} all-gather(f32[64,64] ...)`.
_HLO_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                    "collective-permute", "all-to-all")
_HLO_RESULT_RE = re.compile(
    r"=\s+(?:\(?\s*)([a-z0-9]+)\[([0-9,]*)\][^\s]*\s+"
    r"(" + "|".join(_HLO_COLLECTIVES) + r")(?:-start|-done)?\(")
_HLO_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
                 "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
                 "s32": 4, "u32": 4, "f32": 4,
                 "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16}

# jaxpr primitives that are explicit cross-shard transfers (shard_map /
# pmap-style code); GSPMD-inserted ones only appear in tier 3.
_JAXPR_COLLECTIVES = {"psum": "all-reduce", "psum2": "all-reduce",
                      "all_gather": "all-gather",
                      "reduce_scatter": "reduce-scatter",
                      "psum_scatter": "reduce-scatter",
                      "ppermute": "collective-permute",
                      "pgather": "all-gather", "all_to_all": "all-to-all"}


class _MeshView:
    """Uniform view over the accepted mesh forms: a real `Mesh` (enables
    the compile tier), an `AbstractMesh`, or a plain ``{"axis": size}``
    dict (spec-level analysis only)."""

    def __init__(self, mesh):
        import jax

        self.real = None
        if mesh is None:
            self.sizes = {}
        elif isinstance(mesh, dict):
            self.sizes = {str(k): int(v) for k, v in mesh.items()}
        else:
            self.sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
            if isinstance(mesh, jax.sharding.Mesh):
                self.real = mesh

    @property
    def n_devices(self):
        return math.prod(self.sizes.values()) if self.sizes else 1


def _is_spec_leaf(x):
    import jax

    return (x is None
            or isinstance(x, (jax.sharding.PartitionSpec,
                              jax.sharding.NamedSharding)))


def _as_spec(s):
    """NamedSharding -> its PartitionSpec; P()/None pass through."""
    import jax

    if isinstance(s, jax.sharding.NamedSharding):
        return s.spec
    return s


def _as_aval(leaf):
    """Any array-ish leaf -> ShapeDtypeStruct (NDArray unwrapped)."""
    import jax
    import numpy as onp

    if hasattr(leaf, "_data"):          # mx NDArray
        leaf = leaf._data
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return leaf
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
    arr = onp.asarray(leaf)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def _nbytes(aval):
    try:
        item = aval.dtype.itemsize
    except Exception:
        item = 4
    return math.prod(aval.shape) * item if aval.shape else item


def _norm_entries(spec, rank):
    """Spec -> per-dim tuple-of-axis-names, padded with () to `rank`.
    None (unconstrained) and P() (explicitly replicated) both normalize
    to all-() — they differ only for SC001, handled by the caller."""
    entries = []
    for e in tuple(spec or ()):
        if e is None:
            entries.append(())
        elif isinstance(e, tuple):
            entries.append(tuple(e))
        else:
            entries.append((e,))
    while len(entries) < rank:
        entries.append(())
    return tuple(entries)


def _spec_leaves_for(arg, spec, where):
    """Broadcast one spec over an arg subtree, or zip a matching spec
    tree; returns one spec per array leaf of `arg`."""
    import jax

    n = len(jax.tree_util.tree_leaves(arg))
    if _is_spec_leaf(spec):
        return [spec] * n
    spec_leaves, spec_tree = jax.tree_util.tree_flatten(
        spec, is_leaf=_is_spec_leaf)
    arg_tree = jax.tree_util.tree_structure(arg)
    if spec_tree != arg_tree:
        raise ValueError(
            f"shardcheck: spec tree for {where} does not match the "
            f"argument structure ({spec_tree} vs {arg_tree})")
    return spec_leaves


def _flatten_with_specs(args, specs, name, prefix="arg"):
    """Yield (label, aval, spec, arg_index) per array leaf, broadcasting a
    single spec over an arg subtree or zipping a matching spec tree."""
    import jax

    if specs is None:
        specs = (None,) * len(args)
    if len(specs) != len(args):
        raise ValueError(
            f"shardcheck({name}): got {len(args)} abstract args but "
            f"{len(specs)} spec entries — pass one spec (or spec tree, or "
            f"None) per argument")
    out = []
    for i, (arg, spec) in enumerate(zip(args, specs)):
        leaves = jax.tree_util.tree_flatten_with_path(arg)[0]
        spec_leaves = _spec_leaves_for(arg, spec, f"{prefix} {i}")
        for (path, leaf), sp in zip(leaves, spec_leaves):
            label = f"{prefix}{i}{jax.tree_util.keystr(path)}"
            out.append((label, _as_aval(leaf), sp, i))
    return out


def _check_leaf(report, label, aval, spec, mv, replicated_min_bytes):
    """Spec-tier checks for one leaf; returns (per_device_bytes,
    shard_factor)."""
    nbytes = _nbytes(aval)
    rank = len(aval.shape)
    raw = _as_spec(spec)
    if raw is not None and len(tuple(raw)) > rank:
        report.add_rule(
            "SC002",
            f"{label}: spec {raw} has {len(tuple(raw))} entries but the "
            f"array has rank {rank}", severity="error", site=label,
            nbytes=nbytes)
        return nbytes, 1
    entries = _norm_entries(raw, rank)
    shard_factor = 1
    for dim, axes in enumerate(entries):
        factor = 1
        for ax in axes:
            if ax not in mv.sizes:
                report.add_rule(
                    "SC003",
                    f"{label}: spec names mesh axis {ax!r} but the mesh "
                    f"only has axes {tuple(mv.sizes) or '()'}",
                    severity="error", site=label, nbytes=nbytes)
                factor = None
                break
            factor *= mv.sizes[ax]
        if not factor or factor == 1:
            continue
        if aval.shape[dim] % factor:
            report.add_rule(
                "SC002",
                f"{label}: dim {dim} has size {aval.shape[dim]}, not "
                f"divisible by mesh axis {'x'.join(axes)} (size {factor}) "
                f"— jit rejects this sharding", severity="error",
                site=label, nbytes=nbytes)
        else:
            shard_factor *= factor
    if (raw is None and shard_factor == 1 and mv.n_devices > 1
            and nbytes >= replicated_min_bytes):
        report.add_rule(
            "SC001",
            f"{label}: no sharding constraint — {nbytes / 2**20:.1f} MiB "
            f"silently replicated on each of {mv.n_devices} devices",
            severity="warn", site=label, nbytes=nbytes)
    return -(-nbytes // shard_factor), shard_factor


def _scan_jaxpr(jaxpr, collectives):
    """Count explicit collective primitives (shard_map-style code) in a
    (closed) jaxpr, recursing into nested jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        kind = _JAXPR_COLLECTIVES.get(eqn.primitive.name)
        if kind is not None:
            moved = sum(_nbytes(v.aval) for v in eqn.outvars
                        if hasattr(v, "aval"))
            rec = collectives.setdefault(kind, {"count": 0, "bytes": 0})
            rec["count"] += 1
            rec["bytes"] += moved
        for v in eqn.params.values():
            if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                _scan_jaxpr(v, collectives)
            elif isinstance(v, (list, tuple)):
                for w in v:
                    if hasattr(w, "eqns") or hasattr(w, "jaxpr"):
                        _scan_jaxpr(w, collectives)


def _scan_hlo(hlo_text, collectives):
    """Collective census over compiled HLO: count + bytes of each result."""
    for m in _HLO_RESULT_RE.finditer(hlo_text):
        dtype, dims, op = m.group(1), m.group(2), m.group(3)
        item = _HLO_ITEMSIZE.get(dtype, 4)
        n = 1
        for d in dims.split(","):
            if d.strip():
                n *= int(d)
        rec = collectives.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        rec["bytes"] += n * item


def _match_donations(report, leaves, out_leaves, donate_argnums):
    """Greedy shape/dtype aliasing of donated input leaves onto output
    leaves (XLA's own matching rule); emits SC004 on spec mismatch and
    returns (aliased_output_ids, donated_bytes)."""
    donate = set(donate_argnums or ())
    taken = set()
    donated_bytes = 0
    for label, aval, spec, argi in leaves:
        if argi not in donate:
            continue
        match = None
        for j, (olabel, oaval, ospec) in enumerate(out_leaves):
            if j in taken:
                continue
            if oaval.shape == aval.shape and oaval.dtype == aval.dtype:
                match = (j, olabel, oaval, ospec)
                break
        if match is None:
            continue
        j, olabel, oaval, ospec = match
        taken.add(j)
        donated_bytes += _nbytes(aval)
        in_e = _norm_entries(_as_spec(spec), len(aval.shape))
        out_e = _norm_entries(_as_spec(ospec), len(oaval.shape))
        if in_e != out_e:
            report.add_rule(
                "SC004",
                f"{label} is donated but sharded {_as_spec(spec)} while "
                f"its aliasing output {olabel} is {_as_spec(ospec)} — XLA "
                f"cannot alias the buffers; every step pays a silent "
                f"{_nbytes(aval) / 2**20:.1f} MiB copy",
                severity="warn", site=label, nbytes=_nbytes(aval))
    return taken, donated_bytes


def shardcheck(fn_or_step, *abstract_args, mesh=None, specs=None,
               out_specs=None, donate_argnums=(), hbm_budget_gb=None,
               hot_path=False, replicated_min_bytes=_REPLICATED_MIN_BYTES,
               name=None, mode=None, compile=True):
    """Pre-flight a program's sharding layout against a mesh.

    Parameters
    ----------
    fn_or_step : callable or None
        The jit-able step function. ``None`` restricts analysis to the
        spec tier (construction-time use, before batch shapes exist).
    *abstract_args
        One entry per fn argument: arrays, NDArrays, ShapeDtypeStructs,
        or pytrees thereof. Only shapes/dtypes are read.
    mesh : jax.sharding.Mesh | AbstractMesh | dict | None
        Real mesh enables the simulated-mesh compile tier; a
        ``{"axis": size}`` dict gives device-free spec analysis; None
        means single-device (specs naming axes raise SC003).
    specs / out_specs
        Per-argument (per-output-tree) PartitionSpec / NamedSharding /
        matching pytrees; ``None`` entries mean unconstrained.
    donate_argnums : tuple
        Mirrors `jax.jit` — drives SC004 and the SC006 donated-buffer
        accounting.
    hbm_budget_gb : float, optional
        Per-device budget for SC006; defaults to the
        ``MXNET_SHARDCHECK_HBM_GB`` env knob (unset = no budget check).
    hot_path : bool
        Mark the program as a latency hot path (serve decode): any
        sizeable all-gather is flagged SC005, not just full-operand ones.
    mode : "warn" | "raise" | None
        Escalation applied before returning (trainers pass the
        ``MXNET_SHARDCHECK`` knob value).
    compile : bool
        ``False`` skips the simulated-mesh compile tier even when a real
        mesh is available (construction-time / dryrun-stamp use, where a
        second full XLA compile of the step would be too expensive).

    Returns
    -------
    ShardReport
    """
    import jax

    fn = fn_or_step
    name = name or getattr(fn, "__name__", None) or "<specs>"
    mv = _MeshView(mesh)
    report = ShardReport(name, mesh_axes=mv.sizes)
    report.tiers.append("spec")

    leaves = _flatten_with_specs(abstract_args, specs, name)
    report.n_leaves = len(leaves)
    per_device = 0
    full_sharded_bytes = set()     # full sizes of leaves that ARE sharded
    for label, aval, spec, argi in leaves:
        pd, factor = _check_leaf(report, label, aval, spec, mv,
                                 replicated_min_bytes)
        per_device += pd
        if factor > 1:
            full_sharded_bytes.add(_nbytes(aval))

    spec_errors = [f for f in report.findings if f.severity == "error"]

    # ---- tier 2: eval_shape + jaxpr collective scan + donation aliasing
    out_leaves = []
    if fn is not None:
        avals = tuple(jax.tree.map(_as_aval, a) for a in abstract_args)
        try:
            out_shape = jax.eval_shape(fn, *avals)
            report.tiers.append("eval_shape")
        except Exception as e:  # analysis must never crash the caller
            report.note("trace-failed",
                        f"eval_shape failed ({type(e).__name__}: {e}); "
                        f"spec-tier results only", severity="info")
            out_shape = None
        if out_shape is not None:
            # tuple-output programs (the trainer step) pair each output
            # entry with its spec entry, so one None can cover a whole
            # aux subtree; otherwise a single spec broadcasts.
            if (isinstance(out_shape, (tuple, list))
                    and isinstance(out_specs, (tuple, list))
                    and not _is_spec_leaf(out_specs)
                    and len(out_specs) == len(out_shape)):
                out_leaves = [
                    (lbl, aval, sp) for lbl, aval, sp, _ in
                    _flatten_with_specs(tuple(out_shape), tuple(out_specs),
                                        name, prefix="out")]
            else:
                o_leaves = jax.tree_util.tree_flatten_with_path(
                    out_shape)[0]
                o_specs = _spec_leaves_for(out_shape, out_specs, "output")
                out_leaves = [
                    (f"out{jax.tree_util.keystr(p)}", _as_aval(l), sp)
                    for (p, l), sp in zip(o_leaves, o_specs)]
            aliased, donated = _match_donations(
                report, leaves, out_leaves, donate_argnums)
            report.donated_bytes = donated
            # non-aliased outputs are NEW per-device buffers
            for j, (olabel, oaval, ospec) in enumerate(out_leaves):
                if j in aliased:
                    continue
                entries = _norm_entries(_as_spec(ospec), len(oaval.shape))
                factor = 1
                for dim, axes in enumerate(entries):
                    f = math.prod(mv.sizes.get(a, 1) for a in axes)
                    if f > 1 and oaval.shape[dim] % f == 0:
                        factor *= f
                per_device += -(-_nbytes(oaval) // factor)
            try:
                _scan_jaxpr(jax.make_jaxpr(fn)(*avals), report.collectives)
                report.tiers.append("jaxpr")
            except Exception as e:
                report.note("jaxpr-scan-failed",
                            f"jaxpr collective scan skipped "
                            f"({type(e).__name__}: {e})", severity="info")

    # ---- tier 3: compile under the simulated mesh, scan HLO collectives
    if compile and fn is not None and mv.real is not None and not spec_errors:
        try:
            _compile_tier(report, fn, abstract_args, specs, out_specs,
                          donate_argnums, mv)
        except Exception as e:
            report.note("compile-failed",
                        f"simulated-mesh compile failed "
                        f"({type(e).__name__}: {e}); spec/eval_shape "
                        f"tiers only", severity="info")

    # SC005: collectives that re-materialize a full sharded operand, or —
    # on a declared hot path — any collective moving >= the SC001 floor.
    for op, rec in report.collectives.items():
        per_op = rec["bytes"] // max(rec["count"], 1)
        hits_full = (op in ("all-gather", "all-to-all")
                     and per_op in full_sharded_bytes)
        if hits_full or (hot_path and rec["bytes"] >= replicated_min_bytes):
            where = "decode/step hot path" if hot_path else "step"
            report.add_rule(
                "SC005",
                f"{op} x{rec['count']} moves ~{rec['bytes'] / 2**20:.2f} "
                f"MiB per {where}"
                + (" — re-materializes a full sharded operand on every "
                   "device" if hits_full else ""),
                severity="warn", nbytes=rec["bytes"])

    # ---- SC006: per-device HBM estimate vs budget
    report.per_device_bytes = int(per_device)
    if hbm_budget_gb is None:
        hbm_budget_gb = util.env_float("MXNET_SHARDCHECK_HBM_GB", 0.0)
    if hbm_budget_gb:
        report.budget_bytes = int(hbm_budget_gb * 2**30)
        if report.per_device_bytes > report.budget_bytes:
            report.add_rule(
                "SC006",
                f"per-device estimate {report.per_device_bytes / 2**20:.1f}"
                f" MiB exceeds the {hbm_budget_gb:g} GiB budget "
                f"(MXNET_SHARDCHECK_HBM_GB) — this job OOMs before the "
                f"first step completes", severity="error",
                nbytes=report.per_device_bytes)

    _count_findings(report)
    _apply_mode(report, mode)
    return report


def _compile_tier(report, fn, args, specs, out_specs, donate_argnums, mv):
    """Lower + compile under the real (simulated) mesh and census the HLO
    collectives; also records XLA's own per-device memory analysis."""
    import jax

    NS = jax.sharding.NamedSharding
    P = jax.sharding.PartitionSpec

    def to_sharding(sp):
        sp = _as_spec(sp)
        return NS(mv.real, sp if sp is not None else P())

    if specs is None:
        specs = (None,) * len(args)
    in_sh = []
    for i, (arg, spec) in enumerate(zip(args, specs)):
        treedef = jax.tree_util.tree_structure(arg)
        spec_leaves = _spec_leaves_for(arg, spec, f"arg {i}")
        in_sh.append(jax.tree_util.tree_unflatten(
            treedef, [to_sharding(s) for s in spec_leaves]))
    kw = {"in_shardings": tuple(in_sh)}
    if out_specs is not None:
        # leave None entries unspecified (compiler-chosen) — forcing
        # replication there would manufacture collectives that the real
        # program never runs
        kw["out_shardings"] = jax.tree.map(
            lambda s: None if s is None else to_sharding(s), out_specs,
            is_leaf=_is_spec_leaf)
    avals = tuple(jax.tree.map(_as_aval, a) for a in args)
    # the analyzer compiles programs ABOUT programs (simulated mesh);
    # deliberately outside the compile ledger
    jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums or ()),
                     **kw)  # noqa: FL012
    compiled = jitted.lower(*avals).compile()
    _scan_hlo(compiled.as_text(), report.collectives)
    try:
        ma = compiled.memory_analysis()
        report.xla_memory = {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
        }
    except Exception as e:
        report.note("xla-memory-unavailable",
                    f"compiled.memory_analysis() unavailable on this "
                    f"backend ({type(e).__name__})", severity="info")
    report.tiers.append("compile")


def _count_findings(report):
    from ..telemetry import registry

    for f in report.findings:
        registry.counter("mx_shardcheck_findings_total",
                         "shardcheck findings by rule",
                         labels={"rule": f.kind}).inc()


def _apply_mode(report, mode):
    mode = (mode or "").strip().lower()
    if mode == "warn":
        for f in report.findings:
            _LOG.warning("MXNET_SHARDCHECK: %r", f)
    elif mode == "raise" and report.findings:
        raise MXNetError("MXNET_SHARDCHECK=raise\n" + report.summary())
