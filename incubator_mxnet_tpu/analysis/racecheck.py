"""Concurrency correctness analyzer for the host control plane.

PRs 8-14 made the host side genuinely concurrent — serve/gateway driver
threads, preemption, drain-free hot_swap, telemetry dump/memwatch
daemons, excepthook flight fanout — while the analysis subsystem only
audited *device programs*. This pass audits the threads that schedule
them, in the shardcheck mold: find the defect before the unlucky
interleaving does.

Two cooperating tiers (ANALYSIS.md has the full model):

**Static tier** (this module; pure AST over ``serve/ fault/ telemetry/
parallel/``). Not a line lint: it builds

- a *thread-entry map* — functions that run off the main thread
  (``threading.Thread(target=...)``, ``sys.excepthook``/signal/atexit
  handlers, pull-gauge/collector/flight-context probes) plus their
  intra-module call closure;
- a *shared-state map* — ``self._*`` attributes and module-level
  mutables reachable from more than one thread root;
- a *lock model* — which ``with <lock>`` scope guards each access,
  including a caller-holds-lock propagation (a ``_private`` function
  whose every call site holds lock L is guarded by L — iterated to a
  small fixpoint) and the documented contract escape (a class/function
  docstring saying the *caller holds its lock* is treated as a held
  contract lock, e.g. ``serve.Scheduler``);
- a *static lock-order graph* from nested acquisitions (one call level
  deep), whose cycles are potential deadlocks.

Rules:

- **RC001** unguarded shared write — mutation of shared state outside
  any lock scope;
- **RC002** read-check-act without the guarding lock — ``if
  self._free: self._free.pop()`` style test+mutate pairs that a peer
  thread can interleave;
- **RC003** static lock-order cycle (both witness paths named);
- **RC004** blocking call (``.join()``, queue ``.get()``, collective,
  ``time.sleep`` ≥ ``MXNET_RACECHECK_SLEEP_S``) while holding a lock.

**Runtime tier** (`telemetry/locks.py`): tracked locks witness the
acquisition orders that actually happen; a cycle in the runtime graph is
**RC005** even if nothing ever hung. `runtime_report()` folds those
witnesses into the same report shape.

Suppressions: ``# noqa: RC00x`` on the offending line (comment the
reason), or the docstring contract above. Every finding increments
``mx_racecheck_findings_total{rule=}``; ``MXNET_RACECHECK=warn|raise``
logs or raises on a dirty report (same semantics as MXNET_ANALYSIS).
"""
from __future__ import annotations

import ast
import logging
import os

from .. import util
from ..base import MXNetError
from .findings import RACE_RULES, RaceReport  # noqa: F401

__all__ = ["racecheck_report", "racecheck_paths", "racecheck_source",
           "runtime_report", "DEFAULT_SUBDIRS"]

_LOG = logging.getLogger("mxnet.analysis")

DEFAULT_SUBDIRS = ("serve", "fault", "telemetry", "parallel")

# threading factory names (raw or tracked) whose result is a lock object
_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "tracked_lock"}
# self-synchronized objects: mutating them needs no external lock
_SYNC_FACTORIES = {"Event", "Queue", "SimpleQueue", "LifoQueue",
                   "PriorityQueue", "Semaphore", "BoundedSemaphore",
                   "Barrier"}
# container methods that mutate the receiver in place
_MUTATORS = {"append", "appendleft", "add", "remove", "discard", "clear",
             "update", "extend", "insert", "pop", "popleft", "popitem",
             "setdefault", "rotate"}
# call names that are cross-host collectives (blocking by design)
_COLLECTIVES = {"barrier", "allreduce", "all_reduce", "allgather",
                "all_gather", "broadcast", "psum", "pmean", "all_to_all"}
# registrar calls whose function argument becomes a cross-thread probe
_PROBE_REGISTRARS = {"register_pull_gauge", "register_collector",
                     "register_flight_context"}

_CONTRACT_MARKERS = ("caller holds", "callers hold", "racecheck: "
                     "caller-holds-lock")


def _sleep_threshold_s():
    return util.env_float("MXNET_RACECHECK_SLEEP_S", 0.05)


# ---------------------------------------------------------------------------
# per-function facts
# ---------------------------------------------------------------------------

class _Func:
    """Everything the cross-function phase needs to know about one
    function: state accesses with the lexically-held lock set, lock
    acquisitions, resolvable calls, spawned entry points."""

    def __init__(self, qname, node, cls=None):
        self.qname = qname
        self.node = node
        self.cls = cls                  # enclosing class name or None
        self.accesses = set()           # state ids touched (read or write)
        self.writes = []                # (state, line, frozenset(held), how)
        self.rc002 = []                 # (state, line, frozenset(held))
        self.blocking = []              # (desc, line, frozenset(held), recv)
        self.acquires = []              # lock ids acquired anywhere in body
        self.edges = []                 # (lock_a, lock_b, line) lexical
        self.calls = []                 # (kind, name, frozenset(held), line)
        self.inherited = frozenset()    # caller-holds locks (fixpoint)
        self.contract = False           # docstring caller-holds-lock
        self.is_entry = False           # runs on a non-main thread
        self.roots = set()              # which thread roots reach it


def _docstring_contract(node):
    doc = ast.get_docstring(node) or ""
    low = doc.lower()
    return any(m in low for m in _CONTRACT_MARKERS)


def _const_store(value):
    """True for atomic flag publishes (= True/False/None/number/str):
    a single STORE_GLOBAL/STORE_ATTR of an immutable is not a data race
    under the GIL — read-check-act on it still is (RC002 covers that)."""
    return isinstance(value, ast.Constant)


def _dotted(expr):
    """Best-effort dotted-name text for receiver classification."""
    parts = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts))


class _ModuleFacts:
    """One analyzed source file: function index, lock table, globals."""

    def __init__(self, path, src):
        self.path = path
        self.base = os.path.splitext(os.path.basename(path))[0]
        self.src_lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.funcs = {}                 # qname -> _Func
        self.classes = {}               # cls -> [qnames]
        self.module_locks = set()       # global names bound to locks
        self.class_locks = {}           # cls -> set of self attr names
        self.module_sync = set()        # globals bound to Event/Queue/...
        self.class_sync = {}            # cls -> self-synchronized attrs
        self.mutable_globals = set()    # module-level mutable bindings
        self.rebound_globals = set()    # names rebound via `global`
        self.entry_names = []           # human-readable entry descriptions
        self.contract_classes = set()

    def noqa(self, line, rule):
        if 1 <= line <= len(self.src_lines):
            text = self.src_lines[line - 1]
            return (f"noqa: {rule}" in text or "racecheck: ok" in text)
        return False

    def lock_id(self, name_or_attr, cls=None):
        if cls is not None:
            return f"{self.base}.{cls}.{name_or_attr}"
        return f"{self.base}.{name_or_attr}"


def _call_name(call):
    f = call.func
    return f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)


def _is_lock_factory(call):
    return _call_name(call) in _LOCK_FACTORIES


def _is_sync_factory(call):
    return _call_name(call) in _SYNC_FACTORIES


def _index_module(path, src):
    """Phase A over one file: find functions, locks, globals."""
    m = _ModuleFacts(path, src)

    # module-level bindings
    for node in m.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            v = node.value
            if isinstance(v, ast.Call) and _is_lock_factory(v):
                m.module_locks.add(name)
            elif isinstance(v, ast.Call) and _is_sync_factory(v):
                m.module_sync.add(name)
            elif isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("list", "dict", "set", "deque",
                                      "defaultdict", "OrderedDict")):
                m.mutable_globals.add(name)

    def index_fn(node, qprefix, cls):
        qname = f"{qprefix}{node.name}"
        fn = _Func(qname, node, cls=cls)
        fn.contract = _docstring_contract(node) or (
            cls in m.contract_classes)
        m.funcs[qname] = fn
        if cls is not None:
            m.classes.setdefault(cls, []).append(qname)
        # nested defs (daemon loop bodies) get their own entries,
        # resolvable by bare name from the enclosing function
        for child in node.body:
            _index_nested(child, qname + ".", cls, fn)
        return fn

    def _index_nested(stmt, qprefix, cls, parent):
        for child in ast.walk(stmt):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{qprefix}{child.name}"
                if q not in m.funcs:
                    sub = _Func(q, child, cls=cls)
                    sub.contract = parent.contract
                    m.funcs[q] = sub

    for node in m.tree.body:
        if isinstance(node, ast.ClassDef):
            if _docstring_contract(node):
                m.contract_classes.add(node.name)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    index_fn(item, f"{node.name}.", node.name)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            index_fn(node, "", None)

    # instance locks: any `self.X = Lock()/tracked_lock()` in any method
    for fn in list(m.funcs.values()):
        if fn.cls is None:
            continue
        for child in ast.walk(fn.node):
            if isinstance(child, ast.Assign) and len(child.targets) == 1:
                t = child.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and isinstance(child.value, ast.Call):
                    if _is_lock_factory(child.value):
                        m.class_locks.setdefault(fn.cls, set()).add(t.attr)
                    elif _is_sync_factory(child.value):
                        m.class_sync.setdefault(fn.cls, set()).add(t.attr)
        for child in ast.walk(fn.node):
            if isinstance(child, ast.Global):
                m.rebound_globals.update(child.names)
    return m


# ---------------------------------------------------------------------------
# phase B: walk each function with a held-lock stack
# ---------------------------------------------------------------------------

class _FnWalker:
    def __init__(self, m, fn):
        self.m = m
        self.fn = fn
        self.held = []                  # lock-id stack (lexical)

    # -- lock identification ------------------------------------------------
    def _as_lock(self, expr):
        """Lock id for a with-context expression, else None."""
        m, fn = self.m, self.fn
        if isinstance(expr, ast.Call):   # with lock.acquire_timeout() etc
            return None
        if isinstance(expr, ast.Name):
            if expr.id in m.module_locks or "lock" in expr.id.lower() \
                    or expr.id in ("_G", "_CV"):
                return m.lock_id(expr.id)
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fn.cls is not None:
                known = m.class_locks.get(fn.cls, ())
                if expr.attr in known or "lock" in expr.attr.lower() \
                        or "cv" in expr.attr.lower() \
                        or "cond" in expr.attr.lower():
                    return m.lock_id(expr.attr, fn.cls)
                return None
            dotted = _dotted(expr)
            if "lock" in dotted.lower():
                # another object's lock (e.g. eng._lock): id by text
                return f"{m.base}.{dotted}"
        return None

    # -- state identification -----------------------------------------------
    def _as_state(self, expr):
        m, fn = self.m, self.fn
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fn.cls is not None:
            if expr.attr in m.class_locks.get(fn.cls, ()) \
                    or expr.attr in m.class_sync.get(fn.cls, ()):
                return None     # locks/Events/Queues sync themselves
            return f"{fn.cls}.{expr.attr}"
        if isinstance(expr, ast.Name):
            n = expr.id
            if n in m.module_locks or n in m.module_sync:
                return None
            if n in m.mutable_globals or n in m.rebound_globals:
                return f"g:{n}"
        return None

    # -- recording ------------------------------------------------------------
    def _heldset(self):
        return frozenset(self.held)

    def _note_access(self, state):
        self.fn.accesses.add(state)

    def _note_write(self, state, line, how):
        self._note_access(state)
        self.fn.writes.append((state, line, self._heldset(), how))

    # -- walking --------------------------------------------------------------
    def walk(self):
        node = self.fn.node
        for stmt in node.body:
            self._stmt(stmt)

    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # nested defs walked separately
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                lock = self._as_lock(item.context_expr)
                if lock is not None:
                    for h in self.held:
                        if h != lock:
                            self.fn.edges.append((h, lock, node.lineno))
                    self.held.append(lock)
                    acquired.append(lock)
                    self.fn.acquires.append(lock)
                else:
                    self._expr(item.context_expr)
            for s in node.body:
                self._stmt(s)
            for _ in acquired:
                self.held.pop()
            return
        if isinstance(node, ast.If):
            self._maybe_rc002(node)
            self._expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                self._target(t, node.value, node.lineno)
            self._expr(node.value)
            return
        if isinstance(node, ast.AugAssign):
            state = self._as_state(node.target) or (
                self._as_state(node.target.value)
                if isinstance(node.target, ast.Subscript) else None)
            if state:
                self._note_write(state, node.lineno, "augmented assignment")
            self._expr(node.value)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                state = self._as_state(base)
                if state:
                    self._note_write(state, node.lineno, "del")
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._expr(node.iter)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.While):
            self._expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.Try):
            for s in (node.body + node.orelse + node.finalbody):
                self._stmt(s)
            for h in node.handlers:
                for s in h.body:
                    self._stmt(s)
            return
        if isinstance(node, (ast.Expr, ast.Return)):
            if node.value is not None:
                self._expr(node.value)
            return
        # everything else: walk expressions generically
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.stmt):
                self._stmt(child)

    def _target(self, t, value, line):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, value, line)
            return
        if isinstance(t, ast.Subscript):
            state = self._as_state(t.value)
            if state:
                self._note_write(state, line, "item assignment")
            return
        state = self._as_state(t)
        if state is None:
            return
        if state.startswith("g:") and _const_store(value):
            self._note_access(state)     # atomic flag publish: not RC001
            return
        # rebinding self.X = <lock factory> in __init__ is construction
        if isinstance(value, ast.Call) and _is_lock_factory(value):
            return
        self._note_write(state, line, "assignment")

    def _maybe_rc002(self, node):
        """`if <reads S>: <mutates S>` outside a lock — the classic
        read-check-act window."""
        if self.held:
            return
        test_states = set()
        for child in ast.walk(node.test):
            s = self._as_state(child) if isinstance(
                child, (ast.Attribute, ast.Name)) else None
            if s:
                test_states.add(s)
        if not test_states:
            return
        for stmt in node.body:
            for child in ast.walk(stmt):
                s = None
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    tgt = (child.targets[0] if isinstance(child, ast.Assign)
                           else child.target)
                    base = tgt.value if isinstance(tgt, ast.Subscript) \
                        else tgt
                    s = self._as_state(base)
                elif isinstance(child, ast.Call) \
                        and isinstance(child.func, ast.Attribute) \
                        and child.func.attr in _MUTATORS:
                    s = self._as_state(child.func.value)
                if s and s in test_states:
                    self.fn.rc002.append((s, child.lineno,
                                          self._heldset()))
                    return

    def _expr(self, node):
        for child in ast.walk(node):
            if isinstance(child, ast.Call):
                self._call(child)
            elif isinstance(child, (ast.Attribute, ast.Name)) \
                    and isinstance(getattr(child, "ctx", None), ast.Load):
                s = self._as_state(child)
                if s:
                    self._note_access(s)

    def _call(self, call):
        fn, m = self.fn, self.m
        f = call.func
        held = self._heldset()
        # container mutation through a method call
        if isinstance(f, ast.Attribute):
            state = self._as_state(f.value)
            if state and f.attr in _MUTATORS:
                self._note_write(state, call.lineno, f".{f.attr}()")
            elif state:
                self._note_access(state)
        # spawned threads / registered handlers => entry points
        self._maybe_entry(call)
        # resolvable callees for the one-level propagation
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self":
            fn.calls.append(("method", f.attr, held, call.lineno))
        elif isinstance(f, ast.Name):
            fn.calls.append(("func", f.id, held, call.lineno))
        # blocking-while-locked candidates (RC004 raw events; filtered
        # against effective held sets in the cross-function phase)
        self._maybe_blocking(call, f, held)

    def _maybe_entry(self, call):
        m, fn = self.m, self.fn

        def mark(target_expr, why):
            q = None
            if isinstance(target_expr, ast.Attribute) \
                    and isinstance(target_expr.value, ast.Name) \
                    and target_expr.value.id == "self" and fn.cls:
                q = f"{fn.cls}.{target_expr.attr}"
            elif isinstance(target_expr, ast.Name):
                # nested def in this function shadows a module-level name
                nested = f"{fn.qname}.{target_expr.id}"
                q = nested if nested in m.funcs else target_expr.id
            elif isinstance(target_expr, ast.Lambda):
                # mark every self-method the lambda body calls
                for child in ast.walk(target_expr.body):
                    if isinstance(child, ast.Call):
                        self._maybe_entry_lambda(child, why)
                return
            if q and q in m.funcs:
                m.funcs[q].is_entry = True
                m.entry_names.append(f"{why}:{q}")

        f = call.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name == "Thread":
            for kw in call.keywords:
                if kw.arg == "target":
                    mark(kw.value, "thread")
        elif name in _PROBE_REGISTRARS:
            for arg in list(call.args) + [kw.value
                                          for kw in call.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute, ast.Lambda)):
                    mark(arg, "probe")
        elif name == "signal" and isinstance(f, ast.Attribute) \
                and len(call.args) == 2:
            mark(call.args[1], "signal")
        elif name == "register" and isinstance(f, ast.Attribute) \
                and _dotted(f.value) == "atexit" and call.args:
            mark(call.args[0], "atexit")
        elif name == "Timer" and len(call.args) >= 2:
            mark(call.args[1], "timer")

    def _maybe_entry_lambda(self, call, why):
        m, fn = self.m, self.fn
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and fn.cls:
            q = f"{fn.cls}.{f.attr}"
            if q in m.funcs:
                m.funcs[q].is_entry = True
                m.entry_names.append(f"{why}:{q}")

    def _maybe_blocking(self, call, f, held):
        fn = self.fn
        recv = None
        name = None
        if isinstance(f, ast.Attribute):
            name = f.attr
            recv = _dotted(f.value)
            if isinstance(f.value, ast.Constant):
                return                   # "sep".join(...)
        elif isinstance(f, ast.Name):
            name = f.id
        if name is None:
            return
        low = (recv or "").lower()
        if name == "join":
            if recv in ("os.path", "posixpath", "ntpath") or not recv:
                return
            fn.blocking.append((f"{recv}.join()", call.lineno, held, recv))
        elif name == "sleep" and low in ("time", ""):
            thr = _sleep_threshold_s()
            dur = None
            if call.args and isinstance(call.args[0], ast.Constant) \
                    and isinstance(call.args[0].value, (int, float)):
                dur = float(call.args[0].value)
            if dur is None or dur >= thr:
                amount = "variable" if dur is None else f"{dur:g}s"
                fn.blocking.append((f"time.sleep({amount})", call.lineno,
                                    held, recv))
        elif name == "get" and recv and (
                "queue" in low or low.endswith("_q") or low == "q"
                or any(kw.arg in ("block", "timeout")
                       for kw in call.keywords)):
            fn.blocking.append((f"{recv}.get()", call.lineno, held, recv))
        elif name == "wait" and recv:
            fn.blocking.append((f"{recv}.wait()", call.lineno, held, recv))
        elif name == "result" and recv and (
                "fut" in low or any(kw.arg == "timeout"
                                    for kw in call.keywords)):
            fn.blocking.append((f"{recv}.result()", call.lineno, held,
                                recv))
        elif name in _COLLECTIVES:
            fn.blocking.append((f"{name}()", call.lineno, held, recv))


# ---------------------------------------------------------------------------
# phase C: cross-function/global analysis + finding emission
# ---------------------------------------------------------------------------

def _resolve(m, fn, kind, name):
    """Resolve a recorded call to a _Func in the same module, or None."""
    if kind == "method" and fn.cls is not None:
        return m.funcs.get(f"{fn.cls}.{name}")
    if kind == "func":
        nested = f"{fn.qname}.{name}"
        return m.funcs.get(nested) or m.funcs.get(name)
    return None


def _thread_closure(m):
    """Mark everything reachable from an entry function (intra-module
    transitive closure) as thread-side; record per-function roots."""
    roots = [f for f in m.funcs.values() if f.is_entry]
    for root in roots:
        seen = set()
        frontier = [root]
        while frontier:
            cur = frontier.pop()
            if cur.qname in seen:
                continue
            seen.add(cur.qname)
            cur.roots.add(root.qname)
            for kind, name, _held, _line in cur.calls:
                callee = _resolve(m, cur, kind, name)
                if callee is not None and callee.qname not in seen:
                    frontier.append(callee)
    # every non-entry-reachable function is (potentially) main-thread
    for f in m.funcs.values():
        if not f.roots:
            f.roots.add("main")
        elif not f.is_entry and not f.qname.startswith("_"):
            # a public method reachable from a thread is also user-callable
            f.roots.add("main")


def _construction_only(m):
    """Methods only ever called from __init__ (pre-thread-start): their
    writes are constructor work, not shared mutation."""
    callers = {}                         # qname -> set(caller qnames)
    for f in m.funcs.values():
        for kind, name, _held, _line in f.calls:
            callee = _resolve(m, f, kind, name)
            if callee is not None:
                callers.setdefault(callee.qname, set()).add(f.qname)
    out = set()
    for q, cs in callers.items():
        fn = m.funcs[q]
        if fn.is_entry or not fn.node.name.startswith("_"):
            continue
        if cs and all(c.endswith(".__init__") or c.endswith("__new__")
                      for c in cs):
            out.add(q)
    return out


def _shared_states(m):
    """State ids reachable from >1 thread root within this module."""
    by_state = {}
    for f in m.funcs.values():
        if f.node.name in ("__init__", "__new__"):
            continue
        for s in f.accesses:
            by_state.setdefault(s, set()).update(f.roots)
    return {s for s, roots in by_state.items() if len(roots) > 1}


def _propagate_inherited(m):
    """Caller-holds-lock fixpoint: a ``_private`` function whose every
    intra-module call site holds lock L effectively runs under L.
    Iterated so guards flow through private helper chains (the gateway
    dispatch path is step -> _step -> _dispatch -> _do_dispatch ->
    _preempt_one, all under the lock `step` takes)."""
    for _round in range(8):
        changed = False
        sites = {}                       # qname -> [frozenset(eff held)]
        for f in m.funcs.values():
            contract = frozenset(
                {f"contract:{m.base}.{f.cls or f.qname}"}) \
                if f.contract else frozenset()
            for kind, name, held, _line in f.calls:
                callee = _resolve(m, f, kind, name)
                if callee is None:
                    continue
                eff = held | f.inherited | contract
                sites.setdefault(callee.qname, []).append(eff)
        for q, effs in sites.items():
            fn = m.funcs[q]
            if fn.is_entry or not fn.node.name.startswith("_"):
                continue                 # externally callable: no trust
            inter = frozenset.intersection(*effs) if effs else frozenset()
            if inter and inter != fn.inherited:
                fn.inherited = inter
                changed = True
        if not changed:
            break


def _effective(fn, held, m):
    eff = set(held) | set(fn.inherited)
    if fn.contract:
        eff.add(f"contract:{m.base}.{fn.cls or fn.qname}")
    return eff


def _guard_of(modules, state_full):
    """The lock most often held at guarded accesses of this state —
    named in RC001/RC002 messages as the attribute/lock pair."""
    votes = {}
    for m in modules:
        for f in m.funcs.values():
            for s, _line, held, _how in f.writes:
                if f"{m.base}.{s}" == state_full:
                    for lk in _effective(f, held, m):
                        votes[lk] = votes.get(lk, 0) + 1
    if not votes:
        return None
    return max(votes.items(), key=lambda kv: kv[1])[0]


def _rel(path):
    for marker in ("incubator_mxnet_tpu", "tools", "tests"):
        i = path.find(marker)
        if i >= 0:
            return path[i:]
    return os.path.basename(path)


def _analyze_modules(modules, report):
    """Emit RC001-RC004 over a list of _ModuleFacts into `report`."""
    lock_edges = {}                      # (a, b) -> witness string

    for m in modules:
        for f in m.funcs.values():
            w = _FnWalker(m, f)
            w.walk()
        _thread_closure(m)
        _propagate_inherited(m)
        report.n_files += 1
        report.n_entry_points += len(m.entry_names)

    for m in modules:
        shared = _shared_states(m)
        report.n_shared += len(shared)
        ctor_only = _construction_only(m)
        rel = _rel(m.path)

        rc002_lines = set()
        for f in m.funcs.values():
            in_ctor = (f.node.name in ("__init__", "__new__")
                       or f.qname in ctor_only)

            # RC002 first (more specific than RC001 at the same site)
            for s, line, held in f.rc002:
                if s not in shared or in_ctor:
                    continue
                if _effective(f, held, m):
                    continue
                if m.noqa(line, "RC002"):
                    continue
                guard = _guard_of([m], f"{m.base}.{s}")
                roots = sorted({r for fn2 in m.funcs.values()
                                if s in fn2.accesses for r in fn2.roots})
                report.add_rule(
                    "RC002",
                    f"read-check-act on {s} without "
                    f"{guard or 'its lock'} in {f.qname} "
                    f"({rel}:{line}): the test and the mutation can "
                    f"interleave with a peer thread",
                    site=f"{rel}:{line}", state=s, lock=guard,
                    witness=[f"thread roots: {', '.join(roots)}"])
                rc002_lines.add((s, line))

            # RC001 unguarded shared writes
            for s, line, held, how in f.writes:
                if s not in shared or in_ctor:
                    continue
                if _effective(f, held, m):
                    continue
                if (s, line) in rc002_lines:
                    continue
                if m.noqa(line, "RC001"):
                    continue
                guard = _guard_of([m], f"{m.base}.{s}")
                roots = sorted({r for fn2 in m.funcs.values()
                                if s in fn2.accesses for r in fn2.roots})
                report.add_rule(
                    "RC001",
                    f"unguarded write ({how}) to shared {s} in "
                    f"{f.qname} ({rel}:{line}); reachable from "
                    f"{', '.join(roots)}"
                    + (f" — guard with {guard}" if guard else ""),
                    site=f"{rel}:{line}", state=s, lock=guard,
                    witness=[f"thread roots: {', '.join(roots)}"])

            # RC004 blocking while holding a lock
            for desc, line, held, recv in f.blocking:
                eff = _effective(f, held, m)
                if not eff:
                    continue
                # waiting on a lock/condition you hold is the CV idiom
                if recv and any(lk.endswith(recv.split(".")[-1])
                                for lk in eff):
                    continue
                if m.noqa(line, "RC004"):
                    continue
                report.add_rule(
                    "RC004",
                    f"blocking call {desc} while holding "
                    f"{', '.join(sorted(eff))} in {f.qname} "
                    f"({rel}:{line}) — every peer thread stalls behind "
                    f"this critical section",
                    site=f"{rel}:{line}", lock=", ".join(sorted(eff)))

            # lexical lock-order edges
            for a, b, line in f.edges:
                lock_edges.setdefault(
                    (a, b), f"{rel}:{line} in {f.qname}")
            # one-level cross-function edges: call under L to a callee
            # that acquires M
            for kind, name, held, line in f.calls:
                if not held:
                    continue
                callee = _resolve(m, f, kind, name)
                if callee is None:
                    continue
                for lk in callee.acquires:
                    for h in held:
                        if h != lk:
                            lock_edges.setdefault(
                                (h, lk),
                                f"{rel}:{line} in {f.qname} -> "
                                f"{callee.qname}")

    report.lock_graph = dict(lock_edges)
    _emit_rc003(modules, lock_edges, report)


def _emit_rc003(modules, edges, report):
    """Cycles in the static lock-order graph, both witness paths named."""
    adj = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)

    # pairwise inversions first (the common real case), then longer
    # cycles via bounded DFS
    reported = set()
    for (a, b) in sorted(edges):
        if (b, a) in edges and frozenset((a, b)) not in reported:
            reported.add(frozenset((a, b)))
            report.add_rule(
                "RC003",
                f"lock-order cycle between {a} and {b}: "
                f"{a} -> {b} at {edges[(a, b)]} but "
                f"{b} -> {a} at {edges[(b, a)]} — two threads taking "
                f"these in opposite orders deadlock",
                lock=f"{a}<->{b}",
                witness=[f"{a} -> {b}: {edges[(a, b)]}",
                         f"{b} -> {a}: {edges[(b, a)]}"])

    def dfs_cycle(start):
        stack = [(start, (start,))]
        while stack:
            node, path = stack.pop()
            for nxt in adj.get(node, ()):
                if nxt == start and len(path) > 2:
                    return path + (start,)
                if nxt not in path and len(path) < 5:
                    stack.append((nxt, path + (nxt,)))
        return None

    for start in sorted(adj):
        cyc = dfs_cycle(start)
        if not cyc:
            continue
        key = frozenset(cyc)
        if key in reported or any(key >= r for r in reported):
            continue
        reported.add(key)
        hops = list(zip(cyc, cyc[1:]))
        report.add_rule(
            "RC003",
            "lock-order cycle " + " -> ".join(cyc)
            + " (each hop witnessed; see witness lines)",
            lock="<->".join(cyc[:-1]),
            witness=[f"{a} -> {b}: {edges[(a, b)]}" for a, b in hops])


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def racecheck_source(src, path="<fixture>.py", report=None):
    """Static tier over one source string (tests/fixtures)."""
    if report is None:   # not `or`: an empty report is len()==0 falsy
        report = RaceReport(os.path.basename(path))
    report.tiers = sorted(set(report.tiers) | {"static"})
    _analyze_modules([_index_module(path, src)], report)
    return report


def racecheck_paths(paths, target_name="paths"):
    """Static tier over a list of .py files (one shared lock graph)."""
    report = RaceReport(target_name)
    report.tiers = ["static"]
    modules = []
    for p in paths:
        with open(p, encoding="utf-8") as fh:
            modules.append(_index_module(p, fh.read()))
    _analyze_modules(modules, report)
    return report


def _tree_files(root, subdirs):
    out = []
    for sub in subdirs:
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for dirpath, _dirs, files in os.walk(d):
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return out


def runtime_report(target_name="runtime"):
    """Fold the `telemetry/locks.py` witness state into a RaceReport:
    every runtime-witnessed order inversion is an RC005 finding with
    both acquisition stacks attached."""
    from ..telemetry import locks

    report = RaceReport(target_name)
    report.tiers = ["runtime"]
    for inv in locks.inversions():
        fwd, rev = inv["witness_fwd"], inv["witness_rev"]
        report.add_rule(
            "RC005",
            f"witnessed lock-order inversion {inv['pair']}: "
            f"{fwd['order']} at {fwd['line']} (thread {fwd['thread']}) "
            f"vs {rev['order']} at {rev['line']} (thread "
            f"{rev['thread']}) — deadlock possible under preemption",
            lock=inv["pair"],
            witness=([f"fwd {fwd['order']} [{fwd['thread']}]"]
                     + [f"  {s}" for s in fwd["stack"]]
                     + [f"rev {rev['order']} [{rev['thread']}]"]
                     + [f"  {s}" for s in rev["stack"]]))
    report.lock_graph = {k: v["line"]
                         for k, v in locks.order_graph().items()}
    return report


def racecheck_report(root=None, subdirs=DEFAULT_SUBDIRS,
                     include_runtime=True, name=None):
    """Run the concurrency pass: static tier over the control-plane
    tree (+ the runtime witness state when any exists), increment
    ``mx_racecheck_findings_total{rule=}``, and honor
    ``MXNET_RACECHECK=warn|raise``. Returns the `RaceReport`."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    files = _tree_files(root, subdirs)
    report = RaceReport(name or "+".join(subdirs))
    report.tiers = ["static"]
    modules = []
    for p in files:
        with open(p, encoding="utf-8") as fh:
            modules.append(_index_module(p, fh.read()))
    _analyze_modules(modules, report)

    if include_runtime:
        from ..telemetry import locks

        if locks.inversions():
            report.tiers.append("runtime")
            rt = runtime_report()
            for f in rt._all:
                report.add(f)
            report.lock_graph.update(
                {k: v["line"] for k, v in locks.order_graph().items()})

    _count_findings(report)
    _maybe_escalate(report)
    return report


def _count_findings(report):
    from ..telemetry import registry

    for f in report.findings:
        registry.counter("mx_racecheck_findings_total",
                         "concurrency findings by rule (see ANALYSIS.md)",
                         labels={"rule": f.kind}).inc()


def _maybe_escalate(report):
    """Honor ``MXNET_RACECHECK``: ``warn`` logs every finding, ``raise``
    fails loudly; unset/other = report-only."""
    mode = (os.environ.get("MXNET_RACECHECK") or "").strip().lower()
    if report.findings and mode == "warn":
        for f in report.findings:
            _LOG.warning("MXNET_RACECHECK: %r", f)
    elif report.findings and mode == "raise":
        raise MXNetError("MXNET_RACECHECK=raise\n" + report.summary())
