"""Structured findings for the static-analysis subsystem.

The reference rejects bad programs in C++ static machinery (nnvm shape/
dtype inference passes, dmlc parameter checking) before anything runs;
this build's analogue reports *hazards* — programs that run but recompile,
host-sync, or promote dtypes away from the reference table — as structured
`Finding` records grouped in an `AuditReport`.

Severity contract:
- ``error``   — the program cannot compile as written (e.g. a definite
  host sync inside a traced region).
- ``warn``    — the program runs but violates a performance/semantics
  invariant (recompilation churn, promotion drift, buffer mutation).
- ``info``    — advisory notes (deny-listed eager ops, trace skips) that
  depend on global session state; not counted as findings.
"""
from __future__ import annotations

__all__ = ["Finding", "AuditReport", "HAZARD_KINDS",
           "ShardFinding", "ShardReport", "SHARD_RULES",
           "RaceFinding", "RaceReport", "RACE_RULES"]

# The hazard classes the auditor knows about (ANALYSIS.md documents each).
HAZARD_KINDS = (
    "host-sync",                 # __bool__/__int__/.item()/asnumpy in a
                                 # would-be-compiled region
    "recompile-python-scalar",   # python int/float arg baked into cache keys
    "recompile-weak-type",       # weak-typed input: cache misses on churn
    "recompile-unhashable-static",  # static kwarg that can't key a cache
    "recompile-cache-churn",     # one op holding many compiled variants
    "dtype-promotion-drift",     # jax result dtype != reference table
    "aliased-buffer-mutation",   # input/param rebound during the call
    "not-jittable",              # abstract trace failed (eager-only op)
    "eager-fallback",            # op deny-listed from the op-call jit cache
)


class Finding:
    """One hazard: (kind, message) plus where it was seen."""

    __slots__ = ("kind", "message", "severity", "op", "site")

    def __init__(self, kind, message, severity="warn", op=None, site=None):
        self.kind = kind
        self.message = message
        self.severity = severity
        self.op = op
        self.site = site

    def __repr__(self):
        where = f" [{self.op}]" if self.op else ""
        return f"<{self.severity}:{self.kind}{where} {self.message}>"

    def _key(self):
        return (self.kind, self.op, self.message)


class AuditReport:
    """Findings from one `audit()` call.

    ``findings`` (and iteration/len) cover warn+error severities — the
    contract a clean program must satisfy. ``notes`` carries info-severity
    advisories that depend on global session state (deny lists fill as the
    process runs) and therefore don't count against cleanliness.
    """

    def __init__(self, target_name):
        self.target_name = target_name
        self._all = []
        self._seen = set()
        self.jaxpr = None            # populated when the abstract trace ran

    # -- recording ----------------------------------------------------------
    def add(self, finding: Finding):
        k = finding._key()
        if k in self._seen:
            return
        self._seen.add(k)
        self._all.append(finding)

    def note(self, kind, message, severity="warn", op=None, site=None):
        self.add(Finding(kind, message, severity=severity, op=op, site=site))

    # -- reading ------------------------------------------------------------
    @property
    def findings(self):
        return [f for f in self._all if f.severity in ("warn", "error")]

    @property
    def notes(self):
        return [f for f in self._all if f.severity == "info"]

    def by_kind(self, kind):
        return [f for f in self._all if f.kind == kind]

    def __iter__(self):
        return iter(self.findings)

    def __len__(self):
        return len(self.findings)

    def summary(self):
        head = (f"audit({self.target_name}): {len(self.findings)} finding(s)"
                f", {len(self.notes)} note(s)")
        lines = [head]
        for f in self._all:
            lines.append(f"  {f!r}")
        return "\n".join(lines)

    __repr__ = summary


# ---------------------------------------------------------------------------
# Mesh-level findings (shardcheck — see analysis/shardcheck.py)
# ---------------------------------------------------------------------------

# Rule catalogue for the sharding pre-flight pass. ANALYSIS.md documents
# each with the seeded-defect fixture that demonstrates it.
SHARD_RULES = {
    "SC001": "unconstrained param: silently fully replicated on every device",
    "SC002": "shard-divisibility violation: dim % mesh-axis size != 0",
    "SC003": "spec names a mesh axis that does not exist",
    "SC004": "donation lost under sharding: donated arg's spec differs from "
             "the output it should alias (silent copy per step)",
    "SC005": "implicit cross-shard transfer: collective re-materializes a "
             "full sharded operand inside the step",
    "SC006": "per-device HBM estimate exceeds the budget",
}


class ShardFinding(Finding):
    """One sharding hazard: a Finding whose ``kind`` is an SC rule id,
    carrying the byte weight that ranks it in the report table."""

    __slots__ = ("nbytes",)

    def __init__(self, rule, message, severity="warn", site=None, nbytes=0):
        super().__init__(rule, message, severity=severity, site=site)
        self.nbytes = int(nbytes)

    @property
    def rule(self):
        return self.kind


class ShardReport(AuditReport):
    """Findings from one `shardcheck()` call, plus the mesh-level numbers
    the CLI table prints: per-device byte estimate, collective census, and
    the budget the estimate was judged against."""

    def __init__(self, target_name, mesh_axes=None):
        super().__init__(target_name)
        self.mesh_axes = dict(mesh_axes or {})   # axis name -> size
        self.per_device_bytes = 0      # static HBM estimate per device
        self.donated_bytes = 0         # bytes returned to XLA via aliasing
        self.budget_bytes = None       # MXNET_SHARDCHECK_HBM_GB (resolved)
        self.collectives = {}          # hlo op -> {"count": n, "bytes": b}
        self.n_leaves = 0
        self.tiers = []                # which analysis tiers actually ran

    def add_rule(self, rule, message, severity="warn", site=None, nbytes=0):
        assert rule in SHARD_RULES, rule
        self.add(ShardFinding(rule, message, severity=severity,
                              site=site, nbytes=nbytes))

    def by_rule(self, rule):
        return self.by_kind(rule)

    def stamp(self):
        """One-line machine-greppable summary — the multichip dryrun
        prints this into its metadata tail, and `tools/shardcheck.py
        --dryrun` emits the same line."""
        rules = ",".join(sorted({f.kind for f in self.findings})) or "none"
        cols = ",".join(f"{op}:{rec['count']}"
                        for op, rec in sorted(self.collectives.items())) \
            or "none"
        return (f"shardcheck[{self.target_name}] "
                f"findings={len(self.findings)} rules={rules} "
                f"per_device_mb={self.per_device_bytes / 2**20:.1f} "
                f"collectives={cols}")

    def summary(self):
        mesh = "x".join(f"{a}={s}" for a, s in self.mesh_axes.items()) or "-"
        head = (f"shardcheck({self.target_name}): {len(self.findings)} "
                f"finding(s) | mesh {mesh} | "
                f"per-device ~{self.per_device_bytes / 2**20:.1f} MiB"
                + (f" (budget {self.budget_bytes / 2**30:.2f} GiB)"
                   if self.budget_bytes else ""))
        lines = [head]
        for f in sorted(self._all, key=lambda f: -getattr(f, "nbytes", 0)):
            lines.append(f"  {f!r}")
        if self.collectives:
            lines.append("  collectives per step:")
            for op, rec in sorted(self.collectives.items()):
                lines.append(f"    {op:<20} x{rec['count']:<3} "
                             f"~{rec['bytes'] / 2**20:.2f} MiB moved")
        return "\n".join(lines)

    __repr__ = summary


# ---------------------------------------------------------------------------
# Concurrency findings (racecheck — see analysis/racecheck.py)
# ---------------------------------------------------------------------------

# Rule catalogue for the host-control-plane concurrency pass. RC001-RC004
# come from the static tier (AST dataflow over serve//fault//telemetry//
# parallel/); RC005 is witnessed at runtime by the telemetry/locks.py
# instrumented-lock registry. ANALYSIS.md documents each with its
# seeded-defect fixture.
RACE_RULES = {
    "RC001": "unguarded shared write: state reachable from >1 thread "
             "mutated outside any lock scope",
    "RC002": "read-check-act without the guarding lock: test and mutation "
             "of shared state can interleave with a peer thread",
    "RC003": "static lock-order cycle: two code paths acquire the same "
             "locks in opposite orders (potential deadlock)",
    "RC004": "blocking call (.join()/.get()/collective/long sleep) while "
             "holding a lock",
    "RC005": "runtime-witnessed lock-order inversion (cycle in the "
             "tracked-lock acquisition graph, even without a hang)",
}


class RaceFinding(Finding):
    """One concurrency hazard: a Finding whose ``kind`` is an RC rule id,
    carrying the attribute/lock pair and the witness path(s) that let a
    reader reproduce the interleaving."""

    __slots__ = ("state", "lock", "witness")

    def __init__(self, rule, message, severity="warn", site=None,
                 state=None, lock=None, witness=None):
        super().__init__(rule, message, severity=severity, site=site)
        self.state = state          # the attribute / global at stake
        self.lock = lock            # the lock (pair) involved, if any
        self.witness = tuple(witness or ())   # human-readable path lines

    @property
    def rule(self):
        return self.kind


class RaceReport(AuditReport):
    """Findings from one `racecheck_report()` call (static tier over a
    file set, plus any runtime-tier RC005 witnesses folded in)."""

    def __init__(self, target_name):
        super().__init__(target_name)
        self.n_files = 0
        self.n_entry_points = 0      # thread entry points discovered
        self.n_shared = 0            # shared attributes/globals mapped
        self.lock_graph = {}         # (lock_a, lock_b) -> witness line
        self.tiers = []              # which tiers contributed ("static",
                                     # "runtime")

    def add_rule(self, rule, message, severity="warn", site=None,
                 state=None, lock=None, witness=None):
        assert rule in RACE_RULES, rule
        self.add(RaceFinding(rule, message, severity=severity, site=site,
                             state=state, lock=lock, witness=witness))

    def by_rule(self, rule):
        return self.by_kind(rule)

    def stamp(self):
        """One-line machine-greppable summary (the dryrun meta-gate and
        `tools/racecheck.py --tree` both emit this)."""
        rules = ",".join(sorted({f.kind for f in self.findings})) or "none"
        return (f"racecheck[{self.target_name}] "
                f"findings={len(self.findings)} rules={rules} "
                f"files={self.n_files} shared={self.n_shared} "
                f"lock_edges={len(self.lock_graph)}")

    def summary(self):
        head = (f"racecheck({self.target_name}): {len(self.findings)} "
                f"finding(s) | {self.n_files} file(s), "
                f"{self.n_entry_points} thread entry point(s), "
                f"{self.n_shared} shared attr(s), "
                f"{len(self.lock_graph)} lock-order edge(s)"
                + (f" | tiers: {'+'.join(self.tiers)}" if self.tiers
                   else ""))
        lines = [head]
        for f in self._all:
            lines.append(f"  {f!r}")
            for w in getattr(f, "witness", ()):
                lines.append(f"      {w}")
        return "\n".join(lines)

    __repr__ = summary
