"""Structured findings for the static-analysis subsystem.

The reference rejects bad programs in C++ static machinery (nnvm shape/
dtype inference passes, dmlc parameter checking) before anything runs;
this build's analogue reports *hazards* — programs that run but recompile,
host-sync, or promote dtypes away from the reference table — as structured
`Finding` records grouped in an `AuditReport`.

Severity contract:
- ``error``   — the program cannot compile as written (e.g. a definite
  host sync inside a traced region).
- ``warn``    — the program runs but violates a performance/semantics
  invariant (recompilation churn, promotion drift, buffer mutation).
- ``info``    — advisory notes (deny-listed eager ops, trace skips) that
  depend on global session state; not counted as findings.
"""
from __future__ import annotations

__all__ = ["Finding", "AuditReport", "HAZARD_KINDS"]

# The hazard classes the auditor knows about (ANALYSIS.md documents each).
HAZARD_KINDS = (
    "host-sync",                 # __bool__/__int__/.item()/asnumpy in a
                                 # would-be-compiled region
    "recompile-python-scalar",   # python int/float arg baked into cache keys
    "recompile-weak-type",       # weak-typed input: cache misses on churn
    "recompile-unhashable-static",  # static kwarg that can't key a cache
    "recompile-cache-churn",     # one op holding many compiled variants
    "dtype-promotion-drift",     # jax result dtype != reference table
    "aliased-buffer-mutation",   # input/param rebound during the call
    "not-jittable",              # abstract trace failed (eager-only op)
    "eager-fallback",            # op deny-listed from the op-call jit cache
)


class Finding:
    """One hazard: (kind, message) plus where it was seen."""

    __slots__ = ("kind", "message", "severity", "op", "site")

    def __init__(self, kind, message, severity="warn", op=None, site=None):
        self.kind = kind
        self.message = message
        self.severity = severity
        self.op = op
        self.site = site

    def __repr__(self):
        where = f" [{self.op}]" if self.op else ""
        return f"<{self.severity}:{self.kind}{where} {self.message}>"

    def _key(self):
        return (self.kind, self.op, self.message)


class AuditReport:
    """Findings from one `audit()` call.

    ``findings`` (and iteration/len) cover warn+error severities — the
    contract a clean program must satisfy. ``notes`` carries info-severity
    advisories that depend on global session state (deny lists fill as the
    process runs) and therefore don't count against cleanliness.
    """

    def __init__(self, target_name):
        self.target_name = target_name
        self._all = []
        self._seen = set()
        self.jaxpr = None            # populated when the abstract trace ran

    # -- recording ----------------------------------------------------------
    def add(self, finding: Finding):
        k = finding._key()
        if k in self._seen:
            return
        self._seen.add(k)
        self._all.append(finding)

    def note(self, kind, message, severity="warn", op=None, site=None):
        self.add(Finding(kind, message, severity=severity, op=op, site=site))

    # -- reading ------------------------------------------------------------
    @property
    def findings(self):
        return [f for f in self._all if f.severity in ("warn", "error")]

    @property
    def notes(self):
        return [f for f in self._all if f.severity == "info"]

    def by_kind(self, kind):
        return [f for f in self._all if f.kind == kind]

    def __iter__(self):
        return iter(self.findings)

    def __len__(self):
        return len(self.findings)

    def summary(self):
        head = (f"audit({self.target_name}): {len(self.findings)} finding(s)"
                f", {len(self.notes)} note(s)")
        lines = [head]
        for f in self._all:
            lines.append(f"  {f!r}")
        return "\n".join(lines)

    __repr__ = summary
