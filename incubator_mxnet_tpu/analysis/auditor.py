"""Program auditor: hazard detection over imperative/jit-cached programs.

`audit(fn_or_block, *args)` runs the target twice:

1. an **instrumented eager pass** with the real inputs — NDArray host-sync
   entry points (`asnumpy`/`item`/`__bool__`/`__int__`/`__float__`/
   `__index__`) are patched to record call sites, the op funnel
   (`ndarray.apply_op` / `apply_op_flat`) feeds every executed op through
   `_observe_op` for dtype-promotion drift and cache-key hazards, and
   input/parameter buffer versions are compared before/after to catch
   in-place rebinds (`NDArray._set_data` mutation semantics);
2. an **abstract trace** (`jax.make_jaxpr`) of the same program — the
   definitive "reachable from a cached program" check: a host sync that
   survives the eager pass (because values were concrete) aborts the trace
   with a tracer error and is reported as an ``error`` finding. When the
   trace succeeds the jaxpr is attached to the report for inspection.

Call-signature hazards (python scalars baked into jit-cache keys, weak-typed
inputs, unhashable statics) are scanned statically from the arguments —
exactly what `ndarray._op_cache_key`/`jax.jit` would key on.

The `MXNET_ANALYSIS` env knob (see `util.env_knobs()`) escalates findings:
``warn`` logs each finding, ``raise`` raises `MXNetError` when any warn- or
error-severity finding survives. Unset/empty returns the report silently.
"""
from __future__ import annotations

import logging
import threading

from ..base import MXNetError
from .findings import AuditReport, Finding  # noqa: F401  (re-exported)

__all__ = ["audit", "jit_cache_report"]

_LOG = logging.getLogger("incubator_mxnet_tpu.analysis")

# NDArray entry points that force a device→host round trip. `item`,
# `asscalar`, `tolist`, `__bool__`, `__int__`, `__float__` all funnel into
# `asnumpy`; the depth counter below attributes the sync to the OUTERMOST
# entry point so one user-level sync yields one finding.
_SYNC_METHODS = ("asnumpy", "item", "asscalar", "tolist",
                 "__bool__", "__int__", "__float__", "__index__")

# Binary ops checked against the reference promotion table. The expected
# dtype is computed by running the same-named numpy function on 1-element
# operands — numpy IS the reference table (the reference's np namespace is
# numpy-official by contract, SURVEY §2).
_PROMO_OPS = frozenset({
    "add", "subtract", "multiply", "divide", "true_divide", "floor_divide",
    "mod", "remainder", "power", "maximum", "minimum", "hypot", "arctan2",
    "logaddexp", "logaddexp2", "matmul", "dot",
})

_EXPECTED_DTYPE_CACHE: dict = {}


def _expected_dtype(name, dt_a, dt_b):
    """Reference promotion result for `name(dt_a, dt_b)`, or None when the
    table has no opinion (exotic dtypes, numpy lacks the op)."""
    import numpy as onp

    key = (name, str(dt_a), str(dt_b))
    if key in _EXPECTED_DTYPE_CACHE:
        return _EXPECTED_DTYPE_CACHE[key]
    fn = getattr(onp, name, None)
    expected = None
    if fn is not None:
        try:
            if name in ("matmul", "dot"):
                a, b = onp.ones((1, 1), dt_a), onp.ones((1, 1), dt_b)
            else:
                a, b = onp.ones(1, dt_a), onp.ones(1, dt_b)
            with onp.errstate(all="ignore"):
                expected = fn(a, b).dtype
        except Exception:
            expected = None
    _EXPECTED_DTYPE_CACHE[key] = expected
    return expected


def _checkable_dtype(dt):
    import numpy as onp

    try:
        return onp.dtype(dt).kind in "biuf"
    except TypeError:
        return False    # bfloat16, float0, key dtypes: no numpy analogue


def _user_site():
    """file:line of the audited program's own frame (first caller outside
    the framework's ndarray/analysis internals)."""
    import traceback

    for frame in reversed(traceback.extract_stack()[:-2]):
        f = frame.filename.replace("\\", "/")
        if not (f.endswith("analysis/auditor.py")
                or "/ndarray/" in f or f.endswith("autograd.py")):
            return f"{frame.filename}:{frame.lineno}"
    return None


class _Recorder:
    """Collects findings during one audited run (sync hooks + op funnel)."""

    def __init__(self, report: AuditReport):
        self.report = report
        self._tls = threading.local()

    # -- host syncs ---------------------------------------------------------
    def enter_sync(self, method):
        depth = getattr(self._tls, "depth", 0)
        if depth == 0:
            site = _user_site()
            self.report.note(
                "host-sync",
                f"`{method}` forces a device->host sync inside the audited "
                f"program{f' at {site}' if site else ''}; under jit/hybridize "
                "this either fails to trace or silently fences the pipeline",
                severity="warn", op=method, site=site)
        self._tls.depth = depth + 1

    def exit_sync(self):
        self._tls.depth = getattr(self._tls, "depth", 1) - 1

    # -- op funnel ----------------------------------------------------------
    def observe_op(self, name, in_vals, out_vals, meta):
        if meta.get("uncacheable"):
            self.report.note(
                "recompile-unhashable-static",
                f"op `{name}` was called with unhashable static arguments; "
                "the op-call jit cache cannot key it and every call re-traces",
                op=name)
        if meta.get("denied"):
            self.report.note(
                "eager-fallback",
                f"op `{name}` is deny-listed from the op-call jit cache "
                "(dynamic shape or repeated compile failure); it runs "
                "eagerly on every call", severity="info", op=name)
        if name in _PROMO_OPS and len(in_vals) >= 2 and out_vals:
            dt_a, dt_b = in_vals[0].dtype, in_vals[1].dtype
            out_dt = out_vals[0].dtype
            if (_checkable_dtype(dt_a) and _checkable_dtype(dt_b)
                    and _checkable_dtype(out_dt)):
                expected = _expected_dtype(name, dt_a, dt_b)
                if expected is not None and expected != out_dt:
                    self.report.note(
                        "dtype-promotion-drift",
                        f"`{name}({dt_a}, {dt_b})` produced {out_dt} but the "
                        f"reference promotion table gives {expected} "
                        "(jax weak-type/x64 rules drifting from the "
                        "reference's numpy semantics)", op=name)


class _Instrumented:
    """Scope that patches NDArray sync entry points and installs the op
    funnel hook. Patching happens only while an audit is running — the hot
    paths carry a single `is not None` check otherwise."""

    def __init__(self, recorder):
        self.recorder = recorder
        self._saved = {}

    def __enter__(self):
        from ..ndarray import ndarray as nd_mod

        cls = nd_mod.NDArray
        rec = self.recorder
        for meth in _SYNC_METHODS:
            orig = cls.__dict__.get(meth)
            if orig is None:
                continue
            self._saved[meth] = orig

            def wrapper(self_, *a, _orig=orig, _meth=meth, **kw):
                rec.enter_sync(_meth)
                try:
                    return _orig(self_, *a, **kw)
                finally:
                    rec.exit_sync()

            wrapper.__name__ = meth
            setattr(cls, meth, wrapper)
        self._prev_hook = nd_mod._ANALYSIS_HOOK
        nd_mod._ANALYSIS_HOOK = rec.observe_op
        return self

    def __exit__(self, *exc):
        from ..ndarray import ndarray as nd_mod

        for meth, orig in self._saved.items():
            setattr(nd_mod.NDArray, meth, orig)
        nd_mod._ANALYSIS_HOOK = self._prev_hook
        return False


def _scan_signature(report, args, kwargs):
    """Static hazards visible from the call signature alone — the values
    `ndarray._op_cache_key` / `jax.jit` would bake into cache keys."""
    from ..ndarray.ndarray import NDArray

    def scan_one(label, a):
        if isinstance(a, bool):
            return              # mode flags: static by design
        if isinstance(a, (int, float)):
            report.note(
                "recompile-python-scalar",
                f"{label} is a python scalar ({a!r}); it is baked into the "
                "jit-cache key as a static value, so every distinct value "
                "compiles a separate program — pass a 0-d array for values "
                "that change per step")
            return
        if isinstance(a, NDArray):
            if getattr(a._data, "weak_type", False):
                report.note(
                    "recompile-weak-type",
                    f"{label} carries a weak-typed buffer; mixing weak and "
                    "strong types churns the jit cache (one recompile per "
                    "weak/strong flip) — canonicalize with jnp.asarray(x, "
                    "dtype)")
            return
        try:
            hash(a)
        except TypeError:
            report.note(
                "recompile-unhashable-static",
                f"{label} ({type(a).__name__}) is unhashable; it cannot key "
                "the op-call jit cache and forces eager re-tracing — pass a "
                "tuple or a hashable config object")

    for i, a in enumerate(args):
        scan_one(f"positional arg {i}", a)
    for k, v in kwargs.items():
        scan_one(f"keyword arg {k!r}", v)


def _run_eager(report, call, watched):
    """Instrumented eager pass; returns True when the program executed."""
    versions = [(label, arr, arr._version) for label, arr in watched]
    rec = _Recorder(report)
    try:
        with _Instrumented(rec):
            call()
    except Exception as e:  # noqa: BLE001 — auditing must not mask the error
        report.note(
            "not-jittable",
            f"audited program raised {type(e).__name__}: {e}",
            severity="error")
        return False
    for label, arr, v0 in versions:
        if arr._version != v0:
            report.note(
                "aliased-buffer-mutation",
                f"{label} was mutated in place during the audited call "
                f"(buffer rebind, version {v0} -> {arr._version}); a "
                "compiled/hybridized program would bake the stale buffer or "
                "invalidate donation — return new arrays instead")
    return True


def _run_trace(report, pure_fn, in_avals):
    """Abstract trace: the definitive in-trace host-sync check."""
    import jax

    sync_errors = tuple(
        e for e in (
            getattr(jax.errors, "TracerBoolConversionError", None),
            getattr(jax.errors, "TracerArrayConversionError", None),
            getattr(jax.errors, "TracerIntegerConversionError", None),
            getattr(jax.errors, "ConcretizationTypeError", None))
        if e is not None)
    rec = _Recorder(report)
    try:
        with _Instrumented(rec):
            report.jaxpr = jax.make_jaxpr(pure_fn)(*in_avals)
    except sync_errors as e:
        report.note(
            "host-sync",
            "definite in-trace host sync: abstract tracing aborted with "
            f"{type(e).__name__} — this program cannot compile as written",
            severity="error")
    except Exception as e:  # noqa: BLE001
        report.note(
            "not-jittable",
            f"abstract trace failed with {type(e).__name__}: {e}",
            severity="info")


def _is_block(target):
    try:
        from ..gluon.block import Block

        return isinstance(target, Block)
    except Exception:
        return False


def audit(fn_or_block, *args, train_mode=None, **kwargs):
    """Audit a callable or gluon Block for compile-time hazards.

    Runs the target eagerly with instrumentation, then traces it
    abstractly, and returns an :class:`AuditReport`. ``train_mode`` pins
    the autograd training flag for both passes (default: the current
    mode, i.e. eval outside `autograd.record()`). Remaining positional/
    keyword args are forwarded to the target.
    """
    import jax

    from .. import autograd, util
    from ..ndarray.ndarray import NDArray

    is_block = _is_block(fn_or_block)
    name = (type(fn_or_block).__name__ if is_block
            else getattr(fn_or_block, "__name__", repr(fn_or_block)))
    report = AuditReport(name)
    training = autograd.is_training() if train_mode is None else bool(train_mode)

    _scan_signature(report, args, kwargs)

    # -- build the eager call and the traceable pure function ---------------
    nd_pos = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    nd_args = [args[i] for i in nd_pos]
    watched = [(f"positional arg {i}", args[i]) for i in nd_pos]

    if is_block:
        from ..gluon.block import Block
        from ..random import next_key, trace_key_scope
        from ..utils.trace import TraceContext

        for pname, p in fn_or_block.collect_params().items():
            if p._data is not None:
                watched.append((f"parameter {pname!r}", p.data()))

        def call():
            with autograd._Scope(training=training):
                Block.__call__(fn_or_block, *args, **kwargs)

        def pure_fn(*vals):
            import jax.tree_util as jtu

            call_args = list(args)
            for i, v in zip(nd_pos, vals):
                call_args[i] = NDArray(v)
            with TraceContext() as tc, trace_key_scope(next_key()), \
                    autograd.pause(train_mode=training):
                out = fn_or_block.forward(*call_args, **kwargs)
            flat, _ = jtu.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            out_vals = tuple(o._data if isinstance(o, NDArray) else o
                             for o in flat)
            return out_vals + tuple(nv for _, nv in tc.updates.values())
    else:
        def call():
            with autograd._Scope(training=training):
                fn_or_block(*args, **kwargs)

        def pure_fn(*vals):
            import jax.tree_util as jtu

            call_args = list(args)
            for i, v in zip(nd_pos, vals):
                call_args[i] = NDArray(v)
            with autograd._Scope(training=training):
                out = fn_or_block(*call_args, **kwargs)
            flat, _ = jtu.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            return tuple(o._data if isinstance(o, NDArray) else o
                         for o in flat)

    ran = _run_eager(report, call, watched)
    if ran:
        in_avals = [jax.ShapeDtypeStruct(tuple(a._data.shape), a._data.dtype)
                    for a in nd_args]
        _run_trace(report, pure_fn, in_avals)

    _apply_mode(report, util.getenv("MXNET_ANALYSIS"))
    return report


def jit_cache_report(threshold=8):
    """Inspect the live op-call jit cache for recompile churn: one op
    holding `threshold`+ compiled variants means its static arguments (for
    scalars: their VALUES) keep changing — the silent-cache-miss pattern
    behind the eager-dispatch regression. Returns an AuditReport.

    When the compile observatory (`telemetry.compiles`) has ledger data,
    the report joins it: ``report.ledger`` maps each program family to
    ``{compiles, seconds, flops, bytes_accessed, peak_bytes, causes}``
    (XLA's own cost/memory accounting, not just cache sizes), and any
    family with recompiles past the first gets a `recompile-forensics`
    note naming the dominant cause."""
    from ..ndarray import ndarray as nd_mod

    report = AuditReport("jit-cache")
    report.ledger = {}
    try:
        from ..telemetry import compiles as _compiles

        report.ledger = _compiles.ledger_report()
    except Exception:  # noqa: FL006 — the ledger join is best-effort
        # garnish on the cache report; a telemetry import/shape problem
        # must not break the audit itself
        report.note("recompile-forensics",
                    "compile ledger unavailable (telemetry.compiles "
                    "failed to import or report)", severity="info")
    for fam, row in sorted(report.ledger.items()):
        if row["compiles"] <= 1 or not row["causes"]:
            continue
        cause = max(row["causes"].items(), key=lambda kv: kv[1])[0]
        secs = row["seconds"]
        report.note(
            "recompile-forensics",
            f"program `{fam}` compiled {row['compiles']}x "
            f"({secs:.2f}s total); dominant cause: {cause} "
            f"({row['causes']})",
            severity="info" if cause == "new_bucket" else "warn",
            op=fam)
    info = nd_mod.jit_cache_info()
    per_op: dict = {}
    for key in info["keys"]:
        jfn = key[0]
        per_op.setdefault(jfn, []).append(key)
    for jfn, keys in per_op.items():
        if len(keys) >= threshold:
            opname = getattr(jfn, "__name__", repr(jfn))
            report.note(
                "recompile-cache-churn",
                f"op `{opname}` holds {len(keys)} compiled variants in the "
                "op-call jit cache; a static argument is changing per call "
                "(python-scalar churn) — hoist it into a 0-d array",
                op=opname)
    for name in sorted(info["denied"]):
        report.note(
            "eager-fallback",
            f"op `{name}` is deny-listed (eager-only)", severity="info",
            op=name)
    from .. import autograd

    vinfo = autograd.vjp_cache_info()
    for key in sorted(vinfo["denied"], key=repr):
        # vjp keys are ("vjp", jfn, amp_mode, statics, kwargs)
        jfn = key[1] if isinstance(key, tuple) and len(key) > 1 else None
        opname = getattr(jfn, "__name__", repr(key))
        report.note(
            "eager-fallback",
            f"backward of `{opname}` is deny-listed from the vjp-applier "
            "cache (re-runs the forward eagerly every backward pass)",
            severity="info", op=str(opname))
    return report


def _apply_mode(report, mode):
    mode = (mode or "").strip().lower()
    if mode == "warn":
        for f in report.findings:
            _LOG.warning("MXNET_ANALYSIS: %r", f)
    elif mode == "raise" and report.findings:
        raise MXNetError("MXNET_ANALYSIS=raise\n" + report.summary())
