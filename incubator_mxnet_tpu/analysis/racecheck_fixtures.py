"""Seeded-defect fixtures for `analysis.racecheck` (rules RC001-RC005).

Each static rule gets a pair of source fixtures: ``RCxxx_BAD`` (fires —
a minimal control-plane module seeded with exactly that defect) and
``RCxxx_OK`` (the corrected twin — must analyze clean). The runtime
rule RC005 gets `run_abba()`: a REAL two-thread ABBA acquisition
inversion, Event-sequenced so the two critical sections never overlap —
the witness must report the cycle with both stacks *without* the demo
ever deadlocking. `tools/racecheck.py --demo` and
`tests/test_racecheck.py` consume the same fixtures, so what the docs
cite is what the gates run.

Fixture paths are passed as ``serve/<name>.py`` so the sources are
analyzed under the control-plane scoping rules.
"""
from __future__ import annotations

import threading

__all__ = [
    "RC001_BAD", "RC001_OK", "RC002_BAD", "RC002_OK",
    "RC003_BAD", "RC003_OK", "RC004_BAD", "RC004_OK",
    "STATIC_FIXTURES", "run_abba",
]

# --------------------------------------------------------------------------
# RC001 — unguarded shared write: `Pump._worker` (a thread target)
# appends to `self._items` without `self._lock`, while the main-thread
# `push` path mutates the same list under the lock.
# --------------------------------------------------------------------------

RC001_BAD = '''\
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def push(self, item):
        with self._lock:
            self._items.append(item)

    def _worker(self):
        while True:
            self._items.append(object())   # seeded RC001: no self._lock
'''

RC001_OK = RC001_BAD.replace(
    """        while True:
            self._items.append(object())   # seeded RC001: no self._lock
""",
    """        while True:
            with self._lock:
                self._items.append(object())
""")

# --------------------------------------------------------------------------
# RC002 — read-check-act without the lock: `Alloc.take` checks
# `self._free` then pops it outside `self._lock`, though every other
# access holds the lock (classic TOCTOU on the free list).
# --------------------------------------------------------------------------

RC002_BAD = '''\
import threading


class Alloc:
    def __init__(self):
        self._lock = threading.Lock()
        self._free = [1, 2, 3]
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._reaper, daemon=True)
        self._thread.start()

    def give(self, page):
        with self._lock:
            self._free.append(page)

    def take(self):
        if self._free:                    # seeded RC002: check ...
            return self._free.pop()       # ... then act, lock-free
        return None

    def _reaper(self):
        while True:
            with self._lock:
                self._free.append(0)
'''

RC002_OK = RC002_BAD.replace(
    """        if self._free:                    # seeded RC002: check ...
            return self._free.pop()       # ... then act, lock-free
        return None
""",
    """        with self._lock:
            if self._free:
                return self._free.pop()
        return None
""")

# --------------------------------------------------------------------------
# RC003 — static lock-order inversion: `swap` nests a->b while `route`
# nests b->a; both orders are reachable, so the pair can deadlock.
# --------------------------------------------------------------------------

RC003_BAD = '''\
import threading


class Router:
    def __init__(self):
        self._table_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._table = {}
        self._stats = {}

    def swap(self, table):
        with self._table_lock:
            with self._stats_lock:        # seeded RC003: a -> b
                self._table = table
                self._stats.clear()

    def route(self, key):
        with self._stats_lock:
            with self._table_lock:        # seeded RC003: b -> a
                self._stats[key] = self._stats.get(key, 0) + 1
                return self._table.get(key)
'''

RC003_OK = RC003_BAD.replace(
    """        with self._stats_lock:
            with self._table_lock:        # seeded RC003: b -> a
                self._stats[key] = self._stats.get(key, 0) + 1
                return self._table.get(key)
""",
    """        with self._table_lock:
            with self._stats_lock:
                self._stats[key] = self._stats.get(key, 0) + 1
                return self._table.get(key)
""")

# --------------------------------------------------------------------------
# RC004 — blocking call while holding a lock: `drain` joins the worker
# thread inside `with self._lock`, starving every other path that needs
# the lock for the worker's full lifetime.
# --------------------------------------------------------------------------

RC004_BAD = '''\
import threading


class Drainer:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def drain(self):
        with self._lock:
            self._thread.join()           # seeded RC004: join under lock

    def _run(self):
        pass
'''

RC004_OK = RC004_BAD.replace(
    """        with self._lock:
            self._thread.join()           # seeded RC004: join under lock
""",
    """        with self._lock:
            t = self._thread
        t.join()
""")

#: rule -> (firing fixture, clean twin) — the CLI demo and the tests
#: iterate this table so every static rule keeps both halves.
STATIC_FIXTURES = {
    "RC001": (RC001_BAD, RC001_OK),
    "RC002": (RC002_BAD, RC002_OK),
    "RC003": (RC003_BAD, RC003_OK),
    "RC004": (RC004_BAD, RC004_OK),
}


# --------------------------------------------------------------------------
# RC005 — runtime ABBA witnessed without a deadlock
# --------------------------------------------------------------------------

def run_abba(prefix="demo.abba"):
    """Run a REAL two-thread ABBA inversion against two tracked locks.

    Thread 1 acquires A then B and fully releases; only then (Event-
    sequenced) does thread 2 acquire B then A — the critical sections
    never overlap, so the demo cannot deadlock, but the witness has now
    seen both orders and must report the RC005 cycle with both stacks.

    Returns ``(lock_a_name, lock_b_name)``. Caller arms the witness
    (`locks.enable()` / ``MXNET_TELEMETRY=1``) before calling and reads
    `locks.inversions()` / `analysis.runtime_report()` after.
    """
    from ..telemetry import locks

    a = locks.tracked_lock(f"{prefix}.a", kind="lock")
    b = locks.tracked_lock(f"{prefix}.b", kind="lock")
    first_done = threading.Event()

    def order_ab():
        with a:
            with b:
                pass
        first_done.set()

    def order_ba():
        first_done.wait(timeout=5.0)
        with b:
            with a:
                pass

    t1 = threading.Thread(target=order_ab, daemon=True)
    t2 = threading.Thread(target=order_ba, daemon=True)
    t1.start()
    t2.start()
    t1.join(timeout=5.0)
    t2.join(timeout=5.0)
    if t1.is_alive() or t2.is_alive():
        raise RuntimeError("ABBA demo threads did not finish — the "
                           "Event sequencing should make this impossible")
    return (getattr(a, "_tl_name", f"{prefix}.a"),
            getattr(b, "_tl_name", f"{prefix}.b"))
