"""Static-analysis subsystem: program auditor + hazard findings.

The reference stack rejects bad programs in C++ static machinery (nnvm
graph passes, shape/dtype inference, dmlc parameter checking) before they
run; the TPU-native analogue is this module — `mx.analysis.audit` inspects
a program the way the op-call jit cache / `hybridize()` will see it and
reports recompilation, host-sync, promotion-drift and buffer-aliasing
hazards as structured findings (see ANALYSIS.md).

The companion *framework lint* (`tools/framework_lint.py`) statically
checks the framework source itself for invariants learned from real bugs;
it is pure-AST and lives in tools/ so it can run without importing jax.

The mesh-level companion is `mx.analysis.shardcheck` — a static
sharding/partition-spec pre-flight (rules SC001-SC006) that validates a
program's PartitionSpec layout against a simulated mesh before any pod
job launches (see analysis/shardcheck.py and ANALYSIS.md).

The concurrency companion is `mx.analysis.racecheck_report` — a static
lock/shared-state pass (rules RC001-RC004) over the host control plane
(serve/ fault/ telemetry/ parallel/) plus the runtime lock-order witness
in `telemetry/locks.py` (RC005); see analysis/racecheck.py and
ANALYSIS.md.

Env knobs: ``MXNET_ANALYSIS=warn|raise``, ``MXNET_SHARDCHECK=warn|raise``,
``MXNET_SHARDCHECK_HBM_GB``, ``MXNET_RACECHECK=warn|raise`` (see
`util.env_knobs()`).
"""
from .auditor import audit, jit_cache_report  # noqa: F401
from .findings import (HAZARD_KINDS, RACE_RULES, SHARD_RULES,  # noqa: F401
                       AuditReport, Finding, RaceFinding, RaceReport,
                       ShardFinding, ShardReport)
from .racecheck import (racecheck_paths, racecheck_report,  # noqa: F401
                        racecheck_source, runtime_report)
from .shardcheck import shardcheck  # noqa: F401

__all__ = ["audit", "jit_cache_report", "AuditReport", "Finding",
           "HAZARD_KINDS", "shardcheck", "ShardReport", "ShardFinding",
           "SHARD_RULES", "racecheck_report", "racecheck_paths",
           "racecheck_source", "runtime_report", "RaceReport",
           "RaceFinding", "RACE_RULES"]
