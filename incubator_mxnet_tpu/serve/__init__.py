"""TPU-native continuous-batching inference serving (see SERVING.md).

The framework's decode story before this subsystem was a single-job
loop: one fixed batch, all requests starting and stopping together
(`bench_gpt_decode`). Real serving is the opposite — requests arrive and
finish at different times — and the known technique is continuous
(iteration-level) batching with slot-based KV-cache management (Orca,
OSDI '22; vLLM/PagedAttention, SOSP '23), adapted here to the TPU
constraint that XLA programs are fixed-shape: instead of dynamic
tensors, ONE compiled decode program stays alive and requests swap in
and out of static batch slots.

Three connected parts:

- `engine`    — :class:`SlotDecoder`: the persistent device-side
  ``(L, max_slots, H, max_len, d)`` KV cache and the two compiled
  program families against it (bucketed prefill-into-slot, batched
  masked single-step decode), both with donated cache buffers — zero
  steady-state recompiles and no per-step allocation;
- `scheduler` — :class:`Scheduler`: bounded admission queue (FIFO or
  shortest-prompt-first), loud :class:`QueueFull` backpressure,
  per-request deadlines (:class:`DeadlineExceeded`, retryable under
  `fault.retry.classify_exception`), and the ``step()`` loop that
  interleaves prefill of waiting requests with decode of running slots,
  retiring slots on EOS/length mid-flight;
- `api`       — :class:`ServeEngine`: thread-safe blocking
  ``generate``, streaming ``submit``/``iter_tokens``, batch
  ``generate_many``, background driver thread, graceful
  ``shutdown(drain=True)``.

Observability and chaos ride the existing subsystems: the registry
carries ``mx_serve_ttft_seconds``, ``mx_serve_tokens_total``,
``mx_serve_queue_depth``, ``mx_serve_slot_occupancy`` and
``mx_serve_evictions_total``; `MXNET_FAULT_INJECT` gained a
``serve_step`` seam. Env knobs: ``MXNET_SERVE_MAX_QUEUE``,
``MXNET_SERVE_POLICY``, ``MXNET_SERVE_DEADLINE_S``.

Typical use::

    import incubator_mxnet_tpu as mx

    engine = mx.serve.ServeEngine(model, max_slots=8).start()
    h = engine.submit(prompt_ids, max_new_tokens=128)
    for tok in engine.iter_tokens(h):
        ...
    engine.shutdown(drain=True)
"""
from __future__ import annotations

from . import api  # noqa: F401
from . import engine  # noqa: F401
from . import scheduler  # noqa: F401
from .api import ServeEngine  # noqa: F401
from .engine import SlotDecoder  # noqa: F401
from .scheduler import (DeadlineExceeded, EngineClosed,  # noqa: F401
                        QueueFull, Request, Scheduler)

__all__ = ["ServeEngine", "SlotDecoder", "Scheduler", "Request",
           "QueueFull", "DeadlineExceeded", "EngineClosed",
           "api", "engine", "scheduler"]
