"""TPU-native continuous-batching inference serving (see SERVING.md).

The framework's decode story before this subsystem was a single-job
loop: one fixed batch, all requests starting and stopping together
(`bench_gpt_decode`). Real serving is the opposite — requests arrive and
finish at different times — and the known technique is continuous
(iteration-level) batching with paged KV-cache management (Orca,
OSDI '22; vLLM/PagedAttention, SOSP '23), adapted here to the TPU
constraint that XLA programs are fixed-shape: instead of dynamic
tensors, ONE compiled decode program stays alive and requests map their
token ranges onto pool pages through a static-shape page table.

Three connected parts:

- `engine`    — :class:`SlotDecoder`: the persistent paged device pool
  ``(L, n_pages, H, page_tokens, d)``, the host-side
  :class:`PageAllocator` (refcounts, loud :class:`PagePoolExhausted`)
  and :class:`PrefixCache` (shared system prompts prefilled once), and
  the two compiled program families against the pool (page-aligned
  chunked prefill, batched gather-by-page-table decode), both with
  donated buffers — zero steady-state recompiles and no per-step
  allocation. Optional int8 KV storage
  (``MXNET_SERVE_KV_DTYPE=int8``) halves resident KV bytes per slot;
- `scheduler` — :class:`Scheduler`: bounded admission queue (FIFO or
  remaining-chunk SJF), loud :class:`QueueFull` backpressure,
  per-request deadlines (:class:`DeadlineExceeded`, retryable under
  `fault.retry.classify_exception`), and the ``step()`` loop that
  interleaves prefill CHUNKS of waiting requests with decode of running
  slots, retiring slots on EOS/length mid-flight;
- `api`       — :class:`ServeEngine`: thread-safe blocking
  ``generate``, streaming ``submit``/``iter_tokens``, batch
  ``generate_many``, background driver thread, graceful
  ``shutdown(drain=True)``;
- `tenancy` + `gateway` — the multi-tenant front door:
  :class:`ModelRegistry` (co-resident models sharing one HBM page
  budget) behind :class:`Gateway` — priority-tiered admission (higher
  tiers preempt lower-tier running slots, preempted work resumes warm
  off its cached KV pages), per-tenant token-rate quotas and weighted
  deficit-round-robin fairness (`TokenBucket`, `WDRRQueue`), driven
  against recorded traces by `tools/loadgen.py`;
- `sharded` + `router` — pod-scale: :class:`ServeLayout` partition
  rules place a :class:`ShardedSlotDecoder`'s params and per-layer KV
  pools onto a device mesh (heads-sharded attention pools, Megatron
  fsdp×tp matmuls, every single-chip invariant preserved), and
  ``ModelRegistry.add(..., replicas=N, mesh=...)`` fronts N replica
  engines behind :class:`ReplicaRouter` least-loaded + prefix-affinity
  dispatch with drain-free `Gateway.hot_swap` weight rolls
  (SERVING.md §pod-scale);
- `disagg`    — disaggregated prefill/decode serving (SERVING.md
  §disaggregation): ``ModelRegistry.add(..., prefill_replicas=,
  decode_replicas=)`` splits a pod into compute-bound prefill replicas
  and bandwidth-bound decode replicas; a finished prefill's KV pages
  migrate as a content-addressed `PrefixCache` fill (refcounts handed
  off, ``mx_serve_page_migration_{pages,bytes}_total`` accounted) and
  the request is adopted mid-decode on the far side — decode replicas
  never compile a prefill program (compile-ledger gated), with
  rollback to co-located serving when the handoff faults
  (``page_migration`` seam) or the decode side is page-exhausted;
- `elastic`   — the closed loop over the capacity observatory:
  :class:`ReplicaSetController` (armed by ``MXNET_ELASTIC_SERVE``)
  consumes `AutoscaleAdvisor` recommendations and resizes the LIVE
  replica set — scale-up spawns, warms (both program families, zero
  cold compiles on the request path) and publishes a new replica on a
  rebalanced page budget; scale-down drains and retires; a replica
  killed by the ``replica_crash`` chaos seam is replaced with its
  in-flight work re-queued (zero failed requests); a fault mid-spawn
  (``replica_spawn`` seam) rolls back to exactly N replicas
  (SERVING.md §elastic replicas, RESILIENCE.md §8).

Observability and chaos ride the existing subsystems: the registry
carries ``mx_serve_ttft_seconds``, ``mx_serve_tokens_total``,
``mx_serve_queue_depth``, ``mx_serve_slot_occupancy``,
``mx_serve_page_occupancy``, ``mx_serve_prefix_hits_total``,
``mx_serve_prefill_chunks_total``, ``mx_serve_evictions_total``
(``reason="preempted"`` included), the gateway's ``model``/``tenant``/
``priority``-labeled views of TTFT and tokens, and
``mx_gateway_queue_depth{priority=}``; `MXNET_FAULT_INJECT` has the
``serve_step`` and ``gateway_step`` seams. Env knobs:
``MXNET_SERVE_MAX_QUEUE``, ``MXNET_SERVE_POLICY``,
``MXNET_SERVE_DEADLINE_S``, ``MXNET_SERVE_PAGE_TOKENS``,
``MXNET_SERVE_PREFILL_CHUNK``, ``MXNET_SERVE_KV_DTYPE``,
``MXNET_SERVE_PRIORITY_TIERS``, ``MXNET_SERVE_TENANT_QUOTA``,
``MXNET_GATEWAY_MAX_QUEUE``, ``MXNET_GATEWAY_QUANTUM``,
``MXNET_GATEWAY_PREEMPT``, ``MXNET_SERVE_MESH``,
``MXNET_SERVE_REPLICAS``, ``MXNET_SERVE_AFFINITY``.

Typical use::

    import incubator_mxnet_tpu as mx

    engine = mx.serve.ServeEngine(model, max_slots=8).start()
    h = engine.submit(prompt_ids, max_new_tokens=128)
    for tok in engine.iter_tokens(h):
        ...
    engine.shutdown(drain=True)
"""
from __future__ import annotations

from . import api  # noqa: F401
from . import disagg  # noqa: F401
from . import elastic  # noqa: F401
from . import engine  # noqa: F401
from . import gateway  # noqa: F401
from . import router  # noqa: F401
from . import scheduler  # noqa: F401
from . import sharded  # noqa: F401
from . import tenancy  # noqa: F401
from .api import ServeEngine  # noqa: F401
from .disagg import MigrationAborted  # noqa: F401
from .elastic import ReplicaScaleError, ReplicaSetController  # noqa: F401
from .engine import (PageAllocator, PagePoolExhausted,  # noqa: F401
                     PrefixCache, SlotDecoder)
from .gateway import Gateway, GatewayRequest, ModelRegistry  # noqa: F401
from .router import ReplicaRouter, replica_meshes  # noqa: F401
from .scheduler import (DeadlineExceeded, EngineClosed,  # noqa: F401
                        QueueFull, Request, Scheduler)
from .sharded import (ServeLayout, ShardedSlotDecoder,  # noqa: F401
                      serve_mesh)
from .tenancy import Tenant, TokenBucket, WDRRQueue  # noqa: F401

__all__ = ["ServeEngine", "SlotDecoder", "Scheduler", "Request",
           "PageAllocator", "PrefixCache", "PagePoolExhausted",
           "QueueFull", "DeadlineExceeded", "EngineClosed",
           "Gateway", "GatewayRequest", "ModelRegistry",
           "ServeLayout", "ShardedSlotDecoder", "ReplicaRouter",
           "serve_mesh", "replica_meshes",
           "ReplicaSetController", "ReplicaScaleError",
           "MigrationAborted",
           "Tenant", "TokenBucket", "WDRRQueue",
           "api", "disagg", "elastic", "engine", "gateway", "router",
           "scheduler", "sharded", "tenancy"]
