"""Multi-tenant serving gateway: many models, many tenants, three
priority tiers, one front door (SERVING.md §gateway).

`ServeEngine` serves ONE model for ONE implicit tenant at ONE priority.
Production traffic is none of those things — this module is the
missing multiplexing layer, in the spirit of model-co-residence serving
systems (AlpaServe) and predictable-SLO schedulers (Clockwork):

- :class:`ModelRegistry` — co-resident models. Each entry builds its
  own `SlotDecoder` + `Scheduler` pair (its own two compiled program
  families — the per-engine zero-steady-state-recompile guarantee is
  untouched), but the HBM page budget is ONE number split across the
  per-model pools proportional to each entry's ``share``.

- :class:`Gateway` — ``submit(model, prompt, max_new, tenant=...,
  priority=...)``. Requests land in one WDRR queue per priority tier
  (`serve.tenancy`); every ``step()`` expires deadlines, dispatches
  tier-by-tier (highest first, weighted deficit round robin across
  tenants inside a tier, token-rate quotas deferring over-quota
  tenants), steps every engine once, and pumps generated tokens back
  into the gateway-level handles.

- **preemption** — when a higher-tier request cannot dispatch because
  its model's slots are full, the lowest-tier / least-progressed
  running request is PREEMPTED via `Scheduler.preempt`: its page-
  aligned resident KV pages are registered in the prefix cache (kept
  while the page budget allows), and the request re-enters the gateway
  queue as *remaining-chunk work* — the resumed segment's prompt is
  ``original prompt + tokens so far``, so the cached pages re-attach
  and only the unaligned tail re-prefills. Preempted work is never
  silently dropped: it finishes later, or fails LOUDLY (deadline while
  re-queued ⇒ `DeadlineExceeded`, retryable — never an eviction error).

Observability: gateway spans join the per-request trace
(``gateway.request`` → ``gateway.admit`` → ``serve.request``), the
flight recorder snapshots gateway queue state on crash
(`tracing.register_flight_context`), `mx_serve_ttft_seconds` /
`mx_serve_tokens_total` gain ``model``/``priority``/``tenant``-labeled
series, evictions gain ``reason="preempted"``, and
``mx_gateway_queue_depth{priority=}`` is a pull gauge over the live
queues. Chaos rides the ``gateway_step`` fault seam. Knobs:
``MXNET_SERVE_PRIORITY_TIERS``, ``MXNET_SERVE_TENANT_QUOTA``,
``MXNET_GATEWAY_MAX_QUEUE``, ``MXNET_GATEWAY_QUANTUM``,
``MXNET_GATEWAY_PREEMPT``.

Pod-scale: ``add(..., replicas=N, mesh=...)`` fronts a model with N
independent engines (optionally mesh-sharded via
`serve.sharded.ShardedSlotDecoder`) behind least-loaded +
prefix-affinity routing (`serve.router.ReplicaRouter`;
``MXNET_SERVE_REPLICAS`` / ``MXNET_SERVE_MESH`` /
``MXNET_SERVE_AFFINITY``), with `Gateway.hot_swap` rolling refreshed
weights one replica at a time, drain-free — SERVING.md §pod-scale.
"""
from __future__ import annotations

import os
import queue as _queue
import threading
import time
import weakref

import numpy as onp

from ..telemetry import anatomy, capacity, registry, tracing
from ..telemetry.locks import tracked_lock
from ..util import env_int as _env_int
from . import disagg, tenancy
from .engine import PagePoolExhausted, SlotDecoder
from .scheduler import (_DONE, _NULL, DeadlineExceeded, EngineClosed,
                        QueueFull, Scheduler)

__all__ = ["ModelRegistry", "Gateway", "GatewayRequest"]

_IDLE_SLEEP_S = 0.002
_DRIVER_MAX_CONSECUTIVE_FAILURES = 3
_FLIGHT_QUEUE_SAMPLE = 64     # queued requests snapshotted per dump
# disaggregated page split: prefill replicas hold only transient prompt
# pages, so they share this fraction of a model's page cut and decode
# replicas get the rest (ModelRegistry.rebalance_pages_disagg)
_PREFILL_PAGE_FRAC = 0.25


def _q_help():
    return ("gateway admission-queue depth per priority tier "
            "(pull gauge over the live WDRR queues)")


class _Replica:
    """One serving engine instance: a SlotDecoder (possibly a mesh-
    sharded `serve.sharded.ShardedSlotDecoder`) + Scheduler pair, plus
    the gateway-side list of live (dispatched) requests. ``label`` is
    the metric/census identity — ``"<model>"`` for a single-replica
    model (the pre-replica series names), ``"<model>#<i>"`` otherwise.
    ``draining`` marks a replica the elastic controller is retiring:
    the router stops dispatching to it while its in-flight work
    finishes (`serve/elastic.py` owns the flag and the replica list).
    ``role`` is the disaggregation assignment (SERVING.md
    §disaggregation): ``"both"`` (homogeneous default) serves the full
    request; ``"prefill"`` runs only chunked prefill and hands finished
    segments to the migration plane; ``"decode"`` only ever receives
    already-prefilled requests via `Scheduler.adopt` and never compiles
    a prefill program."""

    __slots__ = ("model", "index", "label", "slots", "sched", "live",
                 "draining", "role")

    def __init__(self, model, index, label, slots, sched, role="both"):
        self.model = model
        self.index = index
        self.label = label
        self.slots = slots
        self.sched = sched
        self.live = []                    # dispatched GatewayRequests
        self.draining = False
        self.role = role                  # "prefill" | "decode" | "both"
        # residency identity for the anatomy ledger: the scheduler's
        # compute seams charge this replica's role-residency series
        sched.anatomy_replica = (label, role)


class _Model:
    """One co-resident model: N replica engines behind one
    `serve.router.ReplicaRouter`. The single-replica accessors
    (``slots``/``sched``/``live`` → replica 0) keep the pre-replica
    surface working for introspection and config reads — every replica
    of a model is built with identical engine kwargs."""

    __slots__ = ("name", "replicas", "share", "router")

    def __init__(self, name, replicas, share, router):
        self.name = name
        self.replicas = replicas
        self.share = share
        self.router = router

    @property
    def slots(self):
        return self.replicas[0].slots

    @property
    def sched(self):
        return self.replicas[0].sched

    @property
    def live(self):
        return self.replicas[0].live

    @property
    def disagg(self):
        """True when the pod is role-split — the gateway then runs
        two-stage dispatch and the migration pump for this model."""
        return any(getattr(r, "role", "both") != "both"
                   for r in self.replicas)

    def role_replicas(self, *roles):
        return [r for r in self.replicas
                if getattr(r, "role", "both") in roles]


class ModelRegistry:
    """Declares the co-resident model set and splits one HBM page
    budget across their pools.

    ``total_pages`` is the SHARED budget (pool pages, incl. each pool's
    reserved trash page); each model gets
    ``max(4, floor(total * share / sum_shares))`` pages. With
    ``total_pages=None`` every engine sizes its own pool (the
    single-model `SlotDecoder` default) — co-residence without a joint
    budget."""

    def __init__(self, total_pages=None):
        self.total_pages = None if total_pages is None else int(total_pages)
        self._specs = {}

    def add(self, name, block_or_decoder, share=1.0, replicas=None,
            mesh=None, prefill_replicas=None, decode_replicas=None,
            **engine_kwargs):
        """Register `name` → model. ``share`` weights this model's cut
        of the page budget; ``engine_kwargs`` forward to `SlotDecoder`
        (max_slots, max_len, page_tokens, kv_dtype, ...).

        ``replicas`` fronts the model with N independent engines behind
        least-loaded + prefix-affinity routing (default: the
        ``MXNET_SERVE_REPLICAS`` knob, else 1); the model's page cut is
        split evenly across them. ``mesh`` makes each replica a
        mesh-sharded `ShardedSlotDecoder`: a spec (``"tp=4"`` / dict /
        int) is carved into disjoint per-replica device slices via
        `serve.router.replica_meshes`; a list supplies one prebuilt
        mesh per replica. A list of pre-built decoders is also accepted
        as ``block_or_decoder`` (one per replica).

        ``prefill_replicas``/``decode_replicas`` make the pod
        DISAGGREGATED (SERVING.md §disaggregation): the first
        ``prefill_replicas`` engines take role ``"prefill"`` (chunked
        prefill only, ~25% of the model's page cut between them), the
        next ``decode_replicas`` take role ``"decode"`` (adopt-only;
        the remaining pages). Mutually exclusive with ``replicas``.
        Under a truthy ``MXNET_DISAGG`` every freshly-built model
        defaults to disaggregation with ``MXNET_SERVE_PREFILL_REPLICAS``
        / ``MXNET_SERVE_DECODE_REPLICAS`` (1/1) roles."""
        name = str(name)
        if name in self._specs:
            raise ValueError(f"model {name!r} already registered")
        share = float(share)
        if share <= 0:
            raise ValueError(
                f"model {name!r}: share must be > 0, got {share}")
        if replicas is not None and int(replicas) < 1:
            raise ValueError(
                f"model {name!r}: replicas must be >= 1, got {replicas}")
        n_p = None if prefill_replicas is None else int(prefill_replicas)
        n_d = None if decode_replicas is None else int(decode_replicas)
        if (n_p is None) != (n_d is None):
            raise ValueError(
                f"model {name!r}: prefill_replicas and decode_replicas "
                "come as a pair — pass both or neither")
        if n_p is not None:
            if replicas is not None:
                raise ValueError(
                    f"model {name!r}: replicas= is mutually exclusive "
                    "with prefill_replicas=/decode_replicas= (the role "
                    "split IS the replica count)")
            if n_p < 1 or n_d < 1:
                raise ValueError(
                    f"model {name!r}: a disaggregated pod needs >= 1 "
                    f"replica of each role, got prefill={n_p} "
                    f"decode={n_d}")
        self._specs[name] = (block_or_decoder, share, dict(engine_kwargs),
                             None if replicas is None else int(replicas),
                             mesh, n_p, n_d)
        return self

    def __len__(self):
        return len(self._specs)

    def __contains__(self, name):
        return name in self._specs

    def names(self):
        return list(self._specs)

    @staticmethod
    def _is_engine(obj):
        return hasattr(obj, "prefill_chunk_step") \
            and hasattr(obj, "allocator")

    def rebalance_pages(self, name, n_replicas):
        """THE page-budget split: per-replica page count for model
        `name` at `n_replicas` replicas — used both at construction
        (`_build`) and by `serve.elastic.ReplicaSetController` every
        time the replica count changes, so the two can never disagree.
        Returns None when there is no joint budget (``total_pages``
        unset). Raises `PagePoolExhausted` LOUDLY when the model's cut
        cannot fund that many replicas (< 4 pages each) — a replica the
        budget cannot pay for must be refused, never silently
        over-committed."""
        if self.total_pages is None:
            return None
        spec = self._specs.get(name)
        if spec is None:
            raise ValueError(f"unknown model {name!r} (registered: "
                             f"{', '.join(sorted(self._specs))})")
        total_share = sum(s[1] for s in self._specs.values())
        cut = int(self.total_pages * spec[1] / total_share)
        per = cut // max(1, int(n_replicas))
        if per < 4:
            raise PagePoolExhausted(
                f"model {name!r}: {n_replicas} replica(s) cannot be "
                f"funded from its {cut}-page cut of the "
                f"{self.total_pages}-page budget (every replica needs "
                ">= 4 pages) — lower the replica count, raise "
                "total_pages, or raise the model's share")
        return per

    def rebalance_pages_disagg(self, name, n_prefill, n_decode):
        """The DISAGGREGATED page split: ``(per_prefill, per_decode)``
        pages for model `name`. Prefill replicas hold only transient
        prompt pages (a handoff segment releases them the moment its
        pages migrate), so they share a `_PREFILL_PAGE_FRAC` sliver of
        the model's cut and the decode side gets everything else — the
        tilt that buys disaggregation's higher resident decode slot
        count at equal hardware. Returns ``(None, None)`` without a
        joint budget; raises `PagePoolExhausted` when either role
        cannot be funded (>= 4 pages per replica)."""
        if self.total_pages is None:
            return None, None
        spec = self._specs.get(name)
        if spec is None:
            raise ValueError(f"unknown model {name!r} (registered: "
                             f"{', '.join(sorted(self._specs))})")
        n_prefill = max(1, int(n_prefill))
        n_decode = max(1, int(n_decode))
        total_share = sum(s[1] for s in self._specs.values())
        cut = int(self.total_pages * spec[1] / total_share)
        per_p = max(4, int(cut * _PREFILL_PAGE_FRAC) // n_prefill)
        per_d = (cut - per_p * n_prefill) // n_decode
        if per_d < 4:
            raise PagePoolExhausted(
                f"model {name!r}: a {n_prefill}-prefill/{n_decode}-"
                f"decode pod cannot be funded from its {cut}-page cut "
                f"of the {self.total_pages}-page budget (every replica "
                f">= 4 pages; decode side got {per_d}) — lower the "
                "replica counts, raise total_pages, or raise the "
                "model's share")
        return per_p, per_d

    def build_engine(self, name, mesh=None, n_pages=None):
        """Construct ONE fresh engine for `name` from its registered
        spec — the elastic controller's scale-up path (the construction
        path is `_build`). Pre-built-decoder entries carry no recipe to
        rebuild from; scaling those needs a factory passed to the
        controller."""
        spec = self._specs.get(name)
        if spec is None:
            raise ValueError(f"unknown model {name!r} (registered: "
                             f"{', '.join(sorted(self._specs))})")
        block, _share, kw = spec[0], spec[1], spec[2]
        if self._is_engine(block) or (
                isinstance(block, (list, tuple))
                and all(self._is_engine(b) for b in block)):
            raise ValueError(
                f"model {name!r} was registered with pre-built "
                "decoder(s) — there is no recipe to build another; "
                "pass factories={...} to the elastic controller")
        rkw = dict(kw)
        if n_pages is not None:
            rkw["n_pages"] = int(n_pages)
        if mesh is not None:
            from .sharded import ShardedSlotDecoder

            return ShardedSlotDecoder(block, mesh=mesh, **rkw)
        return SlotDecoder(block, **rkw)

    def _build(self, policy, max_queue, default_deadline, eos_id, seed):
        from .router import ReplicaRouter, replica_meshes

        if not self._specs:
            raise ValueError("ModelRegistry is empty — add() a model "
                             "before constructing the Gateway")
        models = {}
        for i, (name, (block, share, kw, n_rep, mesh,
                       n_p, n_d)) in enumerate(self._specs.items()):
            prebuilt = None
            if isinstance(block, (list, tuple)) \
                    and all(self._is_engine(b) for b in block):
                prebuilt = list(block)   # one pre-built engine per replica
                if n_rep is not None and n_rep != len(prebuilt):
                    raise ValueError(
                        f"model {name!r}: replicas={n_rep} but "
                        f"{len(prebuilt)} pre-built decoders were given")
                if n_p is not None and n_p + n_d != len(prebuilt):
                    raise ValueError(
                        f"model {name!r}: prefill_replicas={n_p} + "
                        f"decode_replicas={n_d} but {len(prebuilt)} "
                        "pre-built decoders were given (first "
                        "prefill_replicas are the prefill side)")
                n_rep = len(prebuilt)
            elif self._is_engine(block):
                prebuilt = [block]       # pre-built SlotDecoder / stub
                if n_rep is not None and n_rep != 1:
                    raise ValueError(
                        f"model {name!r}: replicas={n_rep} needs a list "
                        "of pre-built decoders (one per replica)")
                if n_p is not None:
                    raise ValueError(
                        f"model {name!r}: a disaggregated pod needs a "
                        "list of pre-built decoders (one per replica), "
                        "or a block to build them from")
                n_rep = 1
            if n_p is None and n_rep is None and prebuilt is None \
                    and _env_int("MXNET_DISAGG", 0):
                # opt-in default: every freshly-built model splits into
                # dedicated prefill/decode replicas (SERVING.md)
                n_p = max(1, _env_int("MXNET_SERVE_PREFILL_REPLICAS", 1))
                n_d = max(1, _env_int("MXNET_SERVE_DECODE_REPLICAS", 1))
            if n_p is not None:
                n_rep = n_p + n_d
            if n_rep is None:
                n_rep = max(1, _env_int("MXNET_SERVE_REPLICAS", 1))
            if prebuilt is not None and kw:
                raise ValueError(
                    f"model {name!r}: engine kwargs {sorted(kw)} "
                    "cannot apply to a pre-built decoder — configure "
                    "it at construction instead")
            if mesh is None:
                meshes = [None] * n_rep
            elif isinstance(mesh, (list, tuple)):
                if len(mesh) != n_rep:
                    raise ValueError(
                        f"model {name!r}: {len(mesh)} meshes for "
                        f"{n_rep} replicas")
                meshes = list(mesh)
            elif hasattr(mesh, "devices") and hasattr(mesh, "shape"):
                meshes = [mesh] * n_rep  # one shared mesh: caller's call
            else:
                meshes = replica_meshes(mesh, n_rep)
            if n_p is not None:
                per_role_pages = self.rebalance_pages_disagg(name, n_p,
                                                             n_d)
            replicas = []
            for j in range(n_rep):
                role = "both" if n_p is None \
                    else ("prefill" if j < n_p else "decode")
                if prebuilt is not None:
                    slots = prebuilt[j]
                else:
                    rkw = dict(kw)
                    if self.total_pages is not None \
                            and "n_pages" not in rkw:
                        if n_p is not None:
                            rkw["n_pages"] = per_role_pages[
                                0 if role == "prefill" else 1]
                        else:
                            rkw["n_pages"] = self.rebalance_pages(name,
                                                                  n_rep)
                    if meshes[j] is not None:
                        from .sharded import ShardedSlotDecoder

                        slots = ShardedSlotDecoder(block, mesh=meshes[j],
                                                   **rkw)
                    else:
                        slots = SlotDecoder(block, **rkw)
                label = name if n_rep == 1 else f"{name}#{j}"
                # compile-ledger families and HBM-census owners carry
                # the replica label (serve:<model>#<j>.prefill, …)
                if hasattr(slots, "census_name"):
                    slots.census_name = f"serve:{label}"
                # replica 0 keeps the pre-replica seed stream so
                # single-replica traces stay reproducible round-over-round
                sched = Scheduler(slots, max_queue=max_queue,
                                  policy=policy,
                                  default_deadline=default_deadline,
                                  eos_id=eos_id, seed=seed + i + 997 * j)
                sched.capacity_model = name   # cost-ledger attribution
                replicas.append(_Replica(name, j, label, slots, sched,
                                         role=role))
            models[name] = _Model(name, replicas, share, ReplicaRouter())
        return models


class GatewayRequest:
    """The tenant-facing handle: same surface as the engine `Request`
    (``done`` / ``ttft`` / ``wait`` / ``result`` / token stream) but
    survives preemption — tokens accumulate across engine segments."""

    __slots__ = ("id", "model", "tenant", "priority", "tier", "prompt",
                 "max_new", "temperature", "eos_id", "deadline",
                 "submit_t", "first_token_t", "finish_t", "tokens",
                 "state", "error", "error_class", "preemptions",
                 "est_cost", "trace_id", "replica", "_spans", "_segment",
                 "_resume_prompt", "_remaining", "_charged", "_anatomy",
                 "_stream", "_done")

    def __init__(self, rid, model, tenant, priority, tier, prompt,
                 max_new, temperature, eos_id, deadline):
        self.id = rid
        self.model = model
        self.tenant = tenant
        self.priority = priority          # tier NAME
        self.tier = tier                  # tier INDEX (0 = highest)
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.eos_id = eos_id
        self.deadline = deadline          # absolute monotonic, or None
        self.submit_t = None
        self.first_token_t = None
        self.finish_t = None
        self.tokens = []
        self.state = "queued"             # queued|dispatched|done|failed
        self.error = None
        self.error_class = None
        self.preemptions = 0
        self.replica = None               # replica label once dispatched
        self.est_cost = int(prompt.size) + int(max_new)
        self._segment = None              # live engine Request, or None
        self._resume_prompt = None        # set after a preemption
        self._remaining = int(max_new)
        self._charged = False             # quota debited once, ever
        self._anatomy = None              # latency-anatomy record, or None
        root = tracing.open_span("gateway.request", lane=f"greq {rid}",
                                 request=rid, model=model, tenant=tenant,
                                 priority=priority,
                                 prompt_len=int(prompt.size),
                                 max_new=max_new)
        self.trace_id = root.trace_id
        self._spans = {"request": root,
                       "admit": tracing.open_span("gateway.admit",
                                                  parent=root)}
        # bounded by max_new tokens + one sentinel per request
        self._stream = _queue.Queue()   # noqa: FL011
        self._done = threading.Event()

    # -- handle surface ----------------------------------------------------

    @property
    def done(self):
        return self._done.is_set()

    @property
    def ttft(self):
        """Seconds from GATEWAY submit to first token (queue wait at the
        gateway + engine admission + prefill)."""
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def wait(self, timeout=None):
        return self._done.wait(timeout)

    def result(self):
        if not self._done.is_set():
            raise RuntimeError(
                f"gateway request {self.id} not finished "
                f"(state={self.state}); wait() on it or drive the gateway")
        if self.error is not None:
            raise self.error
        return list(self.tokens)

    # -- gateway side ------------------------------------------------------

    def _emit(self, tok, now):
        if self.first_token_t is None:
            self.first_token_t = now
            ttft = now - self.submit_t
            # one labeled VIEW per dimension (this registry has no
            # query-time aggregation, so {priority=} and {model=} are
            # separate series — slo.gateway_ttft reads the tier view;
            # the {replica=} view shows routing skew across replicas)
            views = [{"priority": self.priority}, {"model": self.model}]
            if self.replica is not None and self.replica != self.model:
                views.append({"replica": self.replica})
            for labels in views:
                registry.histogram(
                    "mx_serve_ttft_seconds",
                    "time-to-first-token: submit() to the final prefill "
                    "chunk's sampled token",
                    labels=labels).observe(ttft)
        self.tokens.append(tok)
        self._stream.put(tok)
        capacity.charge_tokens(self.tenant, self.model)
        views = [{"tenant": self.tenant}, {"model": self.model}]
        if self.replica is not None and self.replica != self.model:
            views.append({"replica": self.replica})
        for labels in views:
            registry.counter(
                "mx_serve_tokens_total",
                "tokens generated by the serving engine",
                labels=labels).inc()

    def _close_spans(self, error=None):
        self._spans.pop("admit", _NULL).close(error=error)
        self._spans.pop("request", _NULL).annotate(
            tokens=len(self.tokens), state=self.state,
            preemptions=self.preemptions).close(error=error)

    def _finish(self, now):
        self.state = "done"
        self.finish_t = now
        if self._anatomy is not None:
            anatomy.complete(self._anatomy, now, "ok",
                             tokens=len(self.tokens))
        self._close_spans()
        self._stream.put(_DONE)
        self._done.set()

    def _fail(self, exc, now):
        from ..fault.retry import classify_exception

        self.state = "failed"
        self.error = exc
        self.error_class = classify_exception(exc)
        self.finish_t = now
        if self._anatomy is not None:
            anatomy.complete(
                self._anatomy, now,
                "expired" if isinstance(exc, DeadlineExceeded)
                else "failed",
                tokens=len(self.tokens))
        self._close_spans(error=exc)
        self._stream.put(_DONE)
        self._done.set()


class Gateway:
    """The multi-tenant front door over a `ModelRegistry`.

    Parameters
    ----------
    models : ModelRegistry
        The co-resident model set (page budget already declared there).
    tiers : str | sequence, optional
        Priority tier names, highest first (default
        ``MXNET_SERVE_PRIORITY_TIERS`` or ``high,normal,low``).
    tenants : dict, optional
        ``{name: {"weight": w, "rate": r, "burst": b}}`` profiles.
        Unknown tenants are auto-created at first submit with weight 1
        and the default quota.
    quota : (rate, burst), optional
        Default per-tenant token-rate quota (``MXNET_SERVE_TENANT_QUOTA``
        fallback; None = unmetered).
    quantum : float, optional
        WDRR quantum in tokens (``MXNET_GATEWAY_QUANTUM`` or 256).
    max_queue : int, optional
        Gateway admission bound across all tiers
        (``MXNET_GATEWAY_MAX_QUEUE`` or 256); full ⇒ `QueueFull`.
    preempt : bool, optional
        Allow higher-tier arrivals to preempt lower-tier running slots
        (``MXNET_GATEWAY_PREEMPT``, default on).
    policy / engine_max_queue / deadline_s / eos_id / seed
        Forwarded to each per-model `Scheduler`.
    """

    def __init__(self, models, tiers=None, tenants=None, quota=None,
                 quantum=None, max_queue=None, preempt=None, policy="fifo",
                 engine_max_queue=64, deadline_s=None, eos_id=None,
                 seed=0):
        if not isinstance(models, ModelRegistry):
            raise TypeError("Gateway takes a ModelRegistry (got "
                            f"{type(models).__name__})")
        if tiers is None:
            tiers = os.environ.get("MXNET_SERVE_PRIORITY_TIERS")
        self.tiers = tenancy.parse_tiers(
            tiers if tiers is None or isinstance(tiers, str)
            else ",".join(tiers))
        if quota is None:
            quota = tenancy.parse_quota(
                os.environ.get("MXNET_SERVE_TENANT_QUOTA"))
        self._default_rate, self._default_burst = quota
        if quantum is None:
            quantum = _env_int("MXNET_GATEWAY_QUANTUM", 256)
        if max_queue is None:
            max_queue = _env_int("MXNET_GATEWAY_MAX_QUEUE", 256)
        self.max_queue = int(max_queue)
        if preempt is None:
            preempt = bool(_env_int("MXNET_GATEWAY_PREEMPT", 1))
        self.preempt_enabled = bool(preempt)
        self._registry = models
        # the controller rebuilds schedulers for spawned replicas with
        # the same knobs the construction path used
        self._build_params = {"policy": policy,
                              "max_queue": engine_max_queue,
                              "default_deadline": deadline_s,
                              "eos_id": eos_id, "seed": seed}
        self._models = models._build(policy, engine_max_queue, deadline_s,
                                     eos_id, seed)
        self._queues = {t: tenancy.WDRRQueue(quantum) for t in self.tiers}
        self._tenants = {}
        for name, prof in (tenants or {}).items():
            prof = dict(prof)
            self._tenants[name] = tenancy.Tenant(
                name, weight=prof.get("weight", 1.0),
                rate=prof.get("rate", self._default_rate),
                burst=prof.get("burst", self._default_burst))
        self._next_id = 0
        self.closed = False
        self._lock = tracked_lock("serve.gateway")
        self._driver = None
        self._stop = threading.Event()
        self.preemptions_total = 0
        self._advisors = {}
        self._advisor_period = None
        self._advisor_next_t = None
        adv = os.environ.get("MXNET_ADVISOR", "")
        if adv not in ("", "0"):
            self._arm_advisor(5.0 if adv == "1" else float(adv))
        self._elastic = None
        es = os.environ.get("MXNET_ELASTIC_SERVE", "")
        if es not in ("", "0"):
            self.enable_elastic()
        self._arm_probes()

    def enable_elastic(self, **kwargs):
        """Arm the `serve.elastic.ReplicaSetController` (the
        ``MXNET_ELASTIC_SERVE=1`` path does this automatically): the
        controller is ticked from every `step()` and acts on advisor
        recommendations, drains/spawns replicas, and replaces dead
        ones. kwargs forward to the controller ctor (min_replicas,
        max_replicas, factories, warm_lens...). Returns the
        controller."""
        from .elastic import ReplicaSetController

        ctl = ReplicaSetController(self, **kwargs)
        with self._lock:
            self._elastic = ctl
        return ctl

    def _arm_advisor(self, period_s):
        """One observe-only `serve.advisor.AutoscaleAdvisor` per model,
        evaluated every ``period_s`` seconds on the driver thread
        (``MXNET_ADVISOR``). Arms the timeseries history layer if the
        caller hasn't — the advisor is blind without it."""
        from ..telemetry import timeseries
        from .advisor import AutoscaleAdvisor

        if not timeseries.is_enabled():
            timeseries.enable()
        self._advisor_period = float(period_s)
        self._advisor_next_t = None
        for name in self._models:
            self._advisors[name] = AutoscaleAdvisor(name)

    def _advise(self, now):
        """Periodic advisor tick (driver loop / manual step cadence)."""
        if not self._advisors:
            return
        if self._advisor_next_t is not None \
                and now < self._advisor_next_t:
            return
        self._advisor_next_t = now + self._advisor_period
        for adv in self._advisors.values():
            adv.evaluate()

    def advisor_log(self, tail=None):
        """Merged advisor decision log across models (time-ordered)."""
        recs = [r for adv in self._advisors.values()
                for r in adv.decision_log()]
        recs.sort(key=lambda r: r["t"])
        return recs if tail is None else recs[-int(tail):]

    # -- observability probes (weakly bound: a collected gateway drops
    # -- its series instead of being kept alive by the registry) ----------

    def _arm_probes(self):
        ref = weakref.ref(self)
        for tier in self.tiers:
            def _probe(tier=tier, ref=ref):
                gw = ref()
                if gw is None:
                    return None
                return len(gw._queues[tier])
            registry.register_pull_gauge(
                "mx_gateway_queue_depth", _probe, _q_help(),
                labels={"priority": tier})

        for m in self._models.values():
            for rep in m.replicas:
                self._arm_replica_probe(rep)

        for name in self._models:
            def _nrep(name=name, ref=ref):
                gw = ref()
                if gw is None:
                    return None
                m = gw._models.get(name)
                return None if m is None else len(m.replicas)
            registry.register_pull_gauge(
                "mx_serve_replicas", _nrep,
                "live replica count per served model (moves when the "
                "elastic controller scales/replaces)",
                labels={"model": name})

        def _flight(ref=ref):
            gw = ref()
            return None if gw is None else gw._flight_state()
        tracing.register_flight_context("gateway", _flight)

    def _arm_replica_probe(self, rep):
        """Per-replica free-page pull gauge — also called by the
        elastic controller for every replica it spawns."""
        sref = weakref.ref(rep.slots)

        def _free(sref=sref):
            s = sref()
            alloc = None if s is None \
                else getattr(s, "allocator", None)
            if alloc is None:
                return None
            return alloc.free_pages
        registry.register_pull_gauge(
            "mx_serve_replica_free_pages", _free,
            "free KV pool pages per serving replica (the "
            "router's least-loaded signal)",
            labels={"replica": rep.label})

    def _flight_state(self):
        """Queue/slot snapshot for the flight recorder: what was queued
        where, and what each model was running, at crash time."""
        queued = []
        for tier in self.tiers:
            for r in self._queues[tier].items()[:_FLIGHT_QUEUE_SAMPLE]:
                queued.append({
                    "id": r.id, "model": r.model, "tenant": r.tenant,
                    "priority": r.priority, "state": r.state,
                    "preemptions": r.preemptions,
                    "tokens": len(r.tokens)})
        return {
            "tiers": {t: len(self._queues[t]) for t in self.tiers},
            "queued": queued,
            "live": {rep.label: [
                {"id": r.id, "tenant": r.tenant, "priority": r.priority,
                 "tokens": len(r.tokens),
                 "segment_state": None if r._segment is None
                 else r._segment.state}
                for r in rep.live]
                for m in self._models.values() for rep in m.replicas},
            "preemptions_total": self.preemptions_total,
            "spec": {rep.label: rep.slots.spec_stats()
                     for m in self._models.values()
                     for rep in m.replicas
                     if getattr(rep.slots, "spec_k", 0)},
            "closed": self.closed,
        }

    # -- introspection ------------------------------------------------------

    def models(self):
        return list(self._models)

    def tenant(self, name):
        """The (auto-created) tenant record — counters, quota bucket."""
        with self._lock:
            return self._get_tenant(name)

    @property
    def queue_depth(self):
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def queue_depths(self):
        """Per-tier gateway queue depth {tier: n}."""
        with self._lock:
            return {t: len(self._queues[t]) for t in self.tiers}

    def xla_program_counts(self, per_replica=False):
        """Live compiled-program count per model (summed across its
        replicas; ``per_replica=True`` keys by replica label) — the
        per-engine zero-steady-state-recompile gate, gateway edition."""
        with self._lock:
            if per_replica:
                return {rep.label: rep.slots.xla_program_count()
                        for m in self._models.values()
                        for rep in m.replicas}
            return {n: sum(rep.slots.xla_program_count()
                           for rep in m.replicas)
                    for n, m in self._models.items()}

    # -- admission ----------------------------------------------------------

    def _get_tenant(self, name):
        t = self._tenants.get(name)
        if t is None:
            t = tenancy.Tenant(name, rate=self._default_rate,
                               burst=self._default_burst)
            self._tenants[name] = t
        return t

    def submit(self, model, prompt_ids, max_new_tokens, tenant="default",
               priority=None, temperature=1.0, eos_id=None,
               deadline_s=None):
        """Enqueue one request for `model` on behalf of `tenant` at
        `priority` (a tier name; default = the middle tier). Returns a
        `GatewayRequest` handle.

        Loud rejections: unknown model/priority (`ValueError`), gateway
        at capacity (`QueueFull`), a request that could never fit the
        model's page pool (`PagePoolExhausted`), shutdown
        (`EngineClosed`)."""
        with self._lock:
            if self.closed:
                raise EngineClosed("gateway is shut down; new work is "
                                   "rejected")
            m = self._models.get(model)
            if m is None:
                raise ValueError(
                    f"unknown model {model!r} (registered: "
                    f"{', '.join(sorted(self._models))})")
            if priority is None:
                priority = self.tiers[len(self.tiers) // 2]
            if priority not in self.tiers:
                raise ValueError(
                    f"unknown priority {priority!r} (tiers, highest "
                    f"first: {', '.join(self.tiers)})")
            prompt = onp.asarray(prompt_ids, onp.int32).reshape(-1)
            if prompt.size == 0:
                raise ValueError("empty prompt")
            max_new = int(max_new_tokens)
            if max_new < 1:
                raise ValueError(
                    f"max_new_tokens must be >= 1, got {max_new}")
            if prompt.size + max_new > m.slots.max_len:
                raise ValueError(
                    f"prompt ({prompt.size}) + max_new_tokens ({max_new}) "
                    f"exceeds model {model!r}'s max_len "
                    f"({m.slots.max_len})")
            pt = m.slots.page_tokens
            need = -(-(prompt.size + max_new - 1) // pt)
            if m.disagg:
                # the footprint splits across roles: the prompt's pages
                # must fit some prefill-capable pool, the full decode
                # budget some decode-capable pool (replica 0 is a
                # prefill replica with a deliberately small pool — it
                # is NOT the viability bar)
                p_need = -(-prompt.size // pt)
                p_max = max((r.slots.allocator.usable_pages
                             for r in m.role_replicas("prefill", "both")),
                            default=0)
                d_max = max((r.slots.allocator.usable_pages
                             for r in m.role_replicas("decode", "both")),
                            default=0)
                if p_need > p_max or need > d_max:
                    raise PagePoolExhausted(
                        f"request needs {p_need} prefill / {need} decode "
                        f"KV pages but model {model!r}'s largest pools "
                        f"hold {p_max} / {d_max} — raise its share/"
                        "total_pages or shrink the request")
            elif need > m.slots.allocator.usable_pages:
                raise PagePoolExhausted(
                    f"request needs {need} KV pages but model {model!r}'s "
                    f"pool only has {m.slots.allocator.usable_pages} — "
                    "raise its share/total_pages or shrink the request")
            if sum(len(q) for q in self._queues.values()) >= self.max_queue:
                raise QueueFull(
                    f"gateway admission queue at capacity "
                    f"({self.max_queue} waiting) — shed load, raise "
                    "MXNET_GATEWAY_MAX_QUEUE, or retry with backoff")
            now = time.monotonic()
            tier = self.tiers.index(priority)
            req = GatewayRequest(
                self._next_id, model, str(tenant), priority, tier, prompt,
                max_new, float(temperature), eos_id,
                None if deadline_s is None else now + float(deadline_s))
            self._next_id += 1
            req.submit_t = now
            req._anatomy = anatomy.begin(req.id, req.tenant, model,
                                         priority, now,
                                         deadline=req.deadline)
            self._get_tenant(req.tenant)
            self._queues[priority].push(req.tenant, req)
            return req

    # -- the step loop ------------------------------------------------------

    def step(self):
        """One gateway iteration: expire → dispatch (tier order, WDRR,
        quotas, preemption) → one engine step per model → pump tokens.
        Returns True if any progress was made. A crash leaves a flight-
        recorder dump carrying the gateway queue snapshot."""
        try:
            with self._lock:
                return self._step()
        except Exception as e:
            from ..telemetry import hbm

            if hbm.maybe_oom_postmortem("gateway_step", e) is None:
                tracing.maybe_flight_dump("gateway_step", e)
            raise

    def _step(self):
        from ..fault.injection import inject_at

        with tracing.span("gateway.step", queued=self.queue_depth):
            inject_at("gateway_step")
            now = time.monotonic()
            expired = self._expire(now)
            dispatched = self._dispatch(now)
            stepped = False
            for m in self._models.values():
                for rep in m.replicas:
                    if rep.live or not rep.sched.idle:
                        stepped |= bool(rep.sched.step())
            # disaggregation: move freshly-prefilled segments to decode
            # replicas before pumping (the pump would otherwise see a
            # segment with no live stream progress)
            for m in self._models.values():
                if m.disagg:
                    stepped |= bool(
                        disagg.pump_migrations(self, m,
                                               time.monotonic()))
            pumped = self._pump(time.monotonic())
            self._advise(now)
            scaled = (self._elastic.tick(now)
                      if self._elastic is not None else 0)
        return bool(expired or dispatched or stepped or pumped or scaled)

    def _expire(self, now):
        """Fail gateway-queued requests past their deadline — INCLUDING
        preempted ones waiting to resume: a deadline that passes while
        re-queued is `DeadlineExceeded` (retryable), never an eviction
        error."""
        n = 0
        for tier in self.tiers:
            q = self._queues[tier]
            for req in [r for r in q.items()
                        if r.deadline is not None and now > r.deadline]:
                q.remove(req)
                req._fail(DeadlineExceeded(
                    f"gateway request {req.id} expired after "
                    f"{now - req.submit_t:.3f}s "
                    f"({req.preemptions} preemption(s), "
                    f"{len(req.tokens)}/{req.max_new} tokens)"), now)
                n += 1
        return n

    def _rep_capacity(self, rep):
        """Slots this replica can still absorb this step: free slots
        minus work already staged in its engine queue (the engine
        admits those first). A draining replica absorbs nothing — the
        router must never dispatch to it."""
        if rep.draining:
            return 0
        return rep.sched.free_slots - rep.sched.queue_depth

    def _dispatch_reps(self, m):
        """Replicas a fresh (or resumed) submit may land on: everything
        for a homogeneous model, prefill-capable replicas for a
        disaggregated one — decode replicas only ever receive work via
        `Scheduler.adopt` (the migration plane), which keeps their
        compile ledger prefill-free."""
        if not m.disagg:
            return m.replicas
        return m.role_replicas("prefill", "both")

    def _capacity(self, m):
        """Best replica headroom for `m` (the model can dispatch if ANY
        replica can). ``default=0``: a model transiently at zero
        replicas (a crash whose replacement spawn failed) queues its
        work instead of crashing the step loop."""
        return max((self._rep_capacity(rep)
                    for rep in self._dispatch_reps(m)), default=0)

    def _pick_victim(self, m, tier):
        """Lowest-priority / least-progressed running request across
        `m`'s replicas with a tier strictly below `tier`, as
        ``(replica, request)`` — ``(None, None)`` when nothing is
        preemptable. Scoped to dispatch-capable replicas: preempting on
        a decode replica would push the arrival's prefill onto it."""
        best = None
        for rep in self._dispatch_reps(m):
            for r in rep.live:
                seg = r._segment
                if seg is None or seg.slot is None or r.tier <= tier:
                    continue
                key = (-r.tier, len(r.tokens), -r.id)
                if best is None or key < best[0]:
                    best = (key, rep, r)
        return (None, None) if best is None else (best[1], best[2])

    def _can_dispatch(self, req, now):
        m = self._models[req.model]
        if self._capacity(m) <= 0:
            if not (self.preempt_enabled
                    and self._pick_victim(m, req.tier)[1] is not None):
                return False
        if not req._charged:
            t = self._tenants[req.tenant]
            lvl = t.bucket.level(now)
            if lvl is not None and lvl < req.est_cost:
                return False              # over quota: defer, never drop
        return True

    def _dispatch(self, now):
        weights = {n: t.weight for n, t in self._tenants.items()}
        n = 0
        for tier_idx, tier in enumerate(self.tiers):
            q = self._queues[tier]
            while len(q):
                req = q.pop_next(weights, lambda r: r.est_cost,
                                 lambda r: self._can_dispatch(r, now))
                if req is None:
                    break
                self._do_dispatch(req, tier_idx, now)
                n += 1
        return n

    def _do_dispatch(self, req, tier_idx, now):
        m = self._models[req.model]
        prompt = req.prompt if req._resume_prompt is None \
            else req._resume_prompt
        # route: affinity (warm prefix pages — a resumed preemptee's
        # registered KV naturally pulls it back to its old replica),
        # then least-loaded among replicas with capacity. Disaggregated
        # models dispatch stage 1 only: least chunk-backlog among
        # prefill-capable replicas; the migration plane places stage 2.
        if m.disagg:
            rep = m.router.pick_prefill(
                m.replicas, viable=lambda r: self._rep_capacity(r) > 0)
        else:
            rep = m.router.pick(m.replicas, prompt=prompt,
                                tenant=req.tenant,
                                viable=lambda r:
                                self._rep_capacity(r) > 0)
        if rep is None and self.preempt_enabled:
            vrep, victim = self._pick_victim(m, tier_idx)
            if victim is not None:
                self._preempt_one(vrep, victim, now)
                rep = vrep
        if rep is None:               # _can_dispatch said yes; be loud
            raise RuntimeError(
                f"gateway: no dispatchable replica for model "
                f"{req.model!r} (this is a bug — please report)")
        t = self._tenants[req.tenant]
        if not req._charged:
            t.bucket.try_debit(req.est_cost, now)   # checked in _can_dispatch
            req._charged = True
        if req._resume_prompt is None and req.submit_t is not None:
            # first dispatch only — resumed segments would double-count
            # the wait (their delay is preemption, not admission)
            wait = max(now - req.submit_t, 0.0)
            registry.histogram(
                "mx_serve_queue_wait_seconds",
                "gateway admission-queue wait: submit() to first "
                "dispatch into an engine",
                labels={"tenant": req.tenant}).observe(wait)
            capacity.charge_queue_wait(req.tenant, req.model, wait)
        deadline_s = None if req.deadline is None \
            else max(req.deadline - now, 1e-6)
        seg = rep.sched.submit(prompt, req._remaining,
                               temperature=req.temperature,
                               eos_id=req.eos_id, deadline_s=deadline_s,
                               parent_span=req._spans.get("request", _NULL),
                               tenant=req.tenant,
                               prefill_only=m.disagg)
        req._segment = seg
        req.replica = rep.label
        req.state = "dispatched"
        if req._anatomy is not None:
            # closes queue_wait on first dispatch, `preempted` on a
            # resumed one (satellite: re-queued wall is attributed to
            # the preempted state, never dropped)
            req._anatomy.dispatched(now, rep.label)
            seg.anatomy = req._anatomy
        req._spans.pop("admit", _NULL).annotate(
            engine_request=seg.id, replica=rep.label,
            resumed=req._resume_prompt is not None,
            preemptions=req.preemptions).close()
        rep.live.append(req)
        t.dispatched += 1
        registry.counter(
            "mx_gateway_dispatch_total",
            "requests handed to a model engine (resumed segments "
            "included)",
            labels={"model": req.model, "priority": req.priority}).inc()

    def _preempt_one(self, rep, victim, now):
        """Evict `victim`'s slot (on replica `rep`) for a higher-tier
        arrival and re-queue its remaining work (tokens survive;
        resident page-aligned KV stays warm in THAT replica's prefix
        cache — prefix affinity later resumes it there)."""
        seg = victim._segment
        self._drain_segment(victim, seg, now)
        rep.sched.preempt(seg.slot, now)
        rep.live.remove(victim)
        victim._segment = None
        gen = onp.asarray(victim.tokens, onp.int32)
        victim._resume_prompt = onp.concatenate([victim.prompt, gen])
        victim._remaining = victim.max_new - len(victim.tokens)
        victim.preemptions += 1
        victim.state = "queued"
        victim.replica = None
        if victim._anatomy is not None:
            victim._anatomy.requeued(now, "preempted")
        self.preemptions_total += 1
        self._tenants[victim.tenant].preempted += 1
        tracing.event("gateway.preempt", request=victim.id,
                      model=rep.model, replica=rep.label,
                      tenant=victim.tenant,
                      priority=victim.priority,
                      preemptions=victim.preemptions,
                      tokens_kept=len(victim.tokens))
        victim._spans["admit"] = tracing.open_span(
            "gateway.admit", parent=victim._spans.get("request", _NULL),
            resumed=True, preemptions=victim.preemptions)
        self._queues[victim.priority].push(victim.tenant, victim)

    def _drain_segment(self, req, seg, now):
        """Forward every token the engine segment has produced so far
        into the gateway handle (idempotent; `_DONE` is left to the
        finish/fail paths)."""
        moved = 0
        while True:
            try:
                item = seg._stream.get_nowait()
            except _queue.Empty:
                return moved
            if item is _DONE:
                return moved
            req._emit(item, now)
            self._tenants[req.tenant].tokens_out += 1
            moved += 1

    def _pump(self, now):
        """Move tokens from engine segments into gateway handles and
        fold finished segments (done → done, failed → failed — engine
        errors propagate with their own class)."""
        moved = 0
        for m in self._models.values():
            for rep in m.replicas:
                for req in list(rep.live):
                    seg = req._segment
                    if seg is None:
                        rep.live.remove(req)
                        continue
                    moved += self._drain_segment(req, seg, now)
                    if not seg.done:
                        continue
                    rep.live.remove(req)
                    req._segment = None
                    t = self._tenants[req.tenant]
                    if seg.error is not None:
                        req._fail(seg.error, now)
                    else:
                        t.bucket.credit(req.est_cost
                                        - int(req.prompt.size)
                                        - len(req.tokens))
                        req._finish(now)
                    moved += 1
        return moved

    # -- driving ------------------------------------------------------------

    def _driver_running(self):
        d = self._driver
        return d is not None and d.is_alive()

    def _drive_until(self, reqs, timeout=None):
        t_end = None if timeout is None else time.monotonic() + timeout
        for req in reqs:
            while not req.done:
                if t_end is not None and time.monotonic() > t_end:
                    raise TimeoutError(
                        f"gateway request {req.id} still {req.state} "
                        f"after {timeout}s")
                if self._driver_running():
                    req.wait(0.05)
                else:
                    progressed = self.step()
                    if not progressed and not req.done:
                        raise RuntimeError(
                            f"gateway stalled: request {req.id} is "
                            f"{req.state} but nothing is progressing "
                            "(this is a bug — please report)")

    def generate(self, model, prompt_ids, max_new_tokens, tenant="default",
                 priority=None, temperature=1.0, eos_id=None,
                 deadline_s=None, timeout=None):
        """Blocking convenience: submit + drive; returns the FULL
        sequence (prompt + generated) as 1D int32 numpy."""
        req = self.submit(model, prompt_ids, max_new_tokens, tenant=tenant,
                          priority=priority, temperature=temperature,
                          eos_id=eos_id, deadline_s=deadline_s)
        self._drive_until([req], timeout=timeout)
        toks = req.result()
        return onp.concatenate([onp.asarray(req.prompt, onp.int32),
                                onp.asarray(toks, onp.int32)])

    def iter_tokens(self, handle, timeout=30.0):
        """Stream `handle`'s tokens (across preemptions — the handle's
        stream is continuous even when the slot moves)."""
        while True:
            try:
                item = handle._stream.get_nowait()
            except _queue.Empty:
                if self._driver_running() or handle.done:
                    try:
                        item = handle._stream.get(timeout=timeout)
                    except _queue.Empty:
                        raise TimeoutError(
                            f"no token from gateway request {handle.id} "
                            f"in {timeout}s (state={handle.state})") \
                            from None
                else:
                    self.step()
                    continue
            if item is _DONE:
                if handle.error is not None:
                    raise handle.error
                return
            yield item

    # -- driver thread -------------------------------------------------------

    def start(self):
        """Background driver thread owning the step loop. Idempotent."""
        if self._driver_running():
            return self
        self._stop.clear()

        def _loop():
            import logging

            log = logging.getLogger("incubator_mxnet_tpu.serve")
            failures = 0
            while not self._stop.is_set():
                try:
                    progressed = self.step()
                    failures = 0
                except Exception as e:
                    failures += 1
                    log.error(
                        "gateway driver: step failed (%d consecutive): "
                        "%s: %s", failures, type(e).__name__, e)
                    if failures >= _DRIVER_MAX_CONSECUTIVE_FAILURES:
                        log.error(
                            "gateway driver: stopping after %d "
                            "consecutive step failures — drive manually "
                            "after the cause is fixed", failures)
                        break
                    time.sleep(_IDLE_SLEEP_S)
                    continue
                if not progressed:
                    time.sleep(_IDLE_SLEEP_S)

        self._driver = threading.Thread(target=_loop,
                                        name="mx-gateway-driver",
                                        daemon=True)
        self._driver.start()
        return self

    def stop(self):
        self._stop.set()
        d = self._driver
        if d is not None:
            d.join(timeout=5.0)
        self._driver = None

    # -- lifecycle ----------------------------------------------------------

    def hot_swap(self, model=None):
        """Roll refreshed weights across serving replicas ONE AT A
        TIME, drain-free.

        After the source block's parameters are updated in place
        (``set_data`` / an optimizer step), each engine's
        param-fingerprint auto-refresh would pick the change up lazily
        at its next program entry; this makes the roll explicit and
        STAGGERED: the gateway lock is taken per replica and released
        between them, so the driver keeps stepping the other replicas
        while one re-reads (and, for sharded engines, re-places onto
        its mesh) its weights. In-flight requests keep their slots and
        KV — decode simply continues under the new weights. Returns
        ``{replica_label: changed}``."""
        with self._lock:
            if model is not None and model not in self._models:
                raise ValueError(
                    f"unknown model {model!r} (registered: "
                    f"{', '.join(sorted(self._models))})")
            groups = [self._models[model]] if model is not None \
                else list(self._models.values())
            reps = [rep for g in groups for rep in g.replicas]
        out = {}
        for rep in reps:
            with self._lock:
                slots = rep.slots
                dec = getattr(slots, "_dec", None)
                before = getattr(dec, "_param_ids", None)
                if hasattr(slots, "_refresh_params"):
                    slots._refresh_params()
                changed = (dec is not None
                           and getattr(dec, "_param_ids", None) != before)
                out[rep.label] = changed
            tracing.event("gateway.hot_swap", replica=rep.label,
                          changed=changed)
        return out

    def shutdown(self, drain=True, timeout=None):
        """Stop the gateway. ``drain=True`` finishes dispatched work;
        gateway-queued (never-dispatched) requests fail with
        `EngineClosed` either way — loudly, never silently dropped."""
        with self._lock:
            self.closed = True
            now = time.monotonic()
            for tier in self.tiers:
                q = self._queues[tier]
                for req in q.items():
                    q.remove(req)
                    req._fail(EngineClosed(
                        f"gateway shut down before request {req.id} was "
                        "dispatched"), now)
            for m in self._models.values():
                for rep in m.replicas:
                    rep.sched.close(drain=drain)
            self._pump(now)
        if drain:
            t_end = None if timeout is None else time.monotonic() + timeout
            while True:
                with self._lock:
                    busy = any(rep.sched.n_active
                               for m in self._models.values()
                               for rep in m.replicas)
                    if busy:
                        if not self._driver_running():
                            for m in self._models.values():
                                for rep in m.replicas:
                                    if rep.sched.n_active:
                                        rep.sched.step()
                            self._pump(time.monotonic())
                if not busy:
                    break
                if t_end is not None and time.monotonic() > t_end:
                    raise TimeoutError(
                        f"gateway drain did not finish in {timeout}s")
                if self._driver_running():
                    time.sleep(0.01)
        self.stop()
        with self._lock:
            self._pump(time.monotonic())
            for m in self._models.values():
                for rep in m.replicas:
                    rep.sched.slots.prefix_cache.clear()
                    rep.sched.slots.release()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)
