"""Slot-cache compiled decode programs (the device half of `mx.serve`).

XLA programs are fixed-shape, so continuous batching cannot grow or
shrink tensors as requests come and go. Instead this module keeps ONE
persistent KV cache of static shape ``(L, max_slots, H, max_len, d)`` on
the device and compiles exactly two program families against it:

- **prefill** — one causal pass over a single request's prompt (padded
  to a power-of-two length bucket, `models.decoding.bucket_prompt`) that
  writes the prompt's K/V into an assigned slot via one
  ``dynamic_update_slice`` and samples the request's first token. One
  program per bucket length — a small, bounded set.
- **decode** — ONE step for ALL slots at once: every slot advances one
  token against its own cache rows at its own position (per-slot
  ``vmap`` scatter + an ``arange <= pos`` validity mask); a per-slot
  ``active`` mask keeps retired/free slots from contributing anything.
  One program, ever.

Both programs donate the cache buffers (``donate_argnums``) so XLA
updates them in place — steady-state serving allocates nothing and never
recompiles: slot insert/evict is pure device-side index arithmetic, and
the host merely rebinds the donated outputs.

Correctness of slot reuse: a freed slot's stale K/V (from the previous
occupant or from bucket padding) is never attended, because position
``p`` only enters the attention mask once the slot's ``pos`` reaches
``p`` — and the decode step writes the new token's K/V at ``p`` in the
same program before attending. The per-request token stream is therefore
bit-identical to a one-at-a-time `GPTDecoder.generate` (asserted by
`tests/test_serve.py`).
"""
from __future__ import annotations

import math

from ..models.decoding import (GPTDecoder, PROMPT_BUCKETS, _dense, _ln,
                               _split_qkv, bucket_prompt)
from ..telemetry import tracing

__all__ = ["SlotDecoder"]


def _j():
    import jax

    return jax


class SlotDecoder:
    """Persistent slot-cache decoder over a `GPTDecoder` (or the
    `GPTModel`-shaped Block it wraps).

    Parameters
    ----------
    source : GPTDecoder or Block
        The model to serve. A Block is wrapped in a `GPTDecoder`
        (zero-copy parameter references, auto-refreshed on update).
    max_slots : int
        Static batch width of the decode program — the number of
        requests that can be in flight simultaneously.
    max_len : int
        Static sequence capacity of every slot (prompt + generated).
        Defaults to the model's position-embedding length and may not
        exceed it.
    do_sample / top_k : sampling mode, STATIC per engine (baked into the
        compiled programs — per-request values would recompile).
        Temperature stays a runtime argument and may vary per request.
    """

    def __init__(self, source, max_slots=8, max_len=None,
                 buckets=PROMPT_BUCKETS, do_sample=False, top_k=None):
        if isinstance(source, GPTDecoder):
            self._dec = source
        elif hasattr(source, "blocks") and hasattr(source, "position_embed"):
            self._dec = GPTDecoder(source)
        else:
            raise TypeError(
                "SlotDecoder needs a GPTDecoder or a GPT-shaped Block "
                f"(blocks + position_embed), got {type(source).__name__}")
        model_max = self._dec._max_length
        self.max_len = int(max_len) if max_len is not None else model_max
        if self.max_len > model_max:
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's position "
                f"table ({model_max})")
        self.max_slots = int(max_slots)
        if self.max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        # always top out at max_len so every admissible prompt has a
        # bucket — the program count stays bounded by len(buckets)
        self.buckets = tuple(sorted(
            {b for b in buckets if b < self.max_len} | {self.max_len}))
        self._do_sample = bool(do_sample)
        self._top_k = None if top_k is None else int(top_k)
        self._ck = self._cv = None
        self._prefill_jit = None
        self._decode_jit = None

    # -- cache --------------------------------------------------------------

    def _ensure_cache(self):
        if self._ck is not None:
            return
        jnp = _j().numpy
        params = self._dec._params
        layers = params["layers"]
        L = layers["ln1_g"].shape[0]
        H = self._dec._n_heads
        d = self._dec._units // H
        dtype = layers["qkv_w"].dtype
        shape = (L, self.max_slots, H, self.max_len, d)
        self._ck = jnp.zeros(shape, dtype)
        self._cv = jnp.zeros(shape, dtype)

    def release(self):
        """Drop the device cache (shutdown); the next prefill reallocates."""
        self._ck = self._cv = None

    @property
    def cache_bytes(self):
        """Device bytes held by the persistent KV cache (0 if released)."""
        if self._ck is None:
            return 0
        return 2 * self._ck.size * self._ck.dtype.itemsize

    # -- compiled programs --------------------------------------------------

    def _build_prefill(self):
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax
        dec = self._dec

        def prefill(params, ck, cv, tokens, slot, t0, key, temperature,
                    *, top_k, do_sample):
            B = tokens.shape[1]
            x = params["embed"][tokens] + params["pos"][:B]

            def pre_layer(x, lp):
                x, k, v = dec._prefill_layer(x, lp, B)
                return x, (k, v)

            x, (k, v) = lax.scan(pre_layer, x, params["layers"])
            # k/v: (L, 1, H, B, d) — one write drops the whole prompt
            # into the slot's rows [0, B)
            ck = lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                          (0, slot, 0, 0, 0))
            cv = lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                          (0, slot, 0, 0, 0))
            # last REAL token (bucket padding sits beyond t0-1 and is
            # causally invisible to it)
            h_last = lax.dynamic_slice_in_dim(x, t0 - 1, 1, axis=1)[:, 0]
            logits = dec._logits(params, h_last)                  # (1, V)
            first = dec._sample(logits, key, temperature, top_k, do_sample)
            return ck, cv, first[0]

        return jax.jit(prefill, static_argnames=("top_k", "do_sample"),
                       donate_argnums=(1, 2))

    def _slot_decode_layer(self, x, lp, ck, cv, pos):
        """One-token forward for every slot against its own cache rows.

        Unlike `GPTDecoder._decode_layer` (one shared scalar position),
        each slot writes and masks at its OWN ``pos[s]`` — the whole
        point of continuous batching.
        """
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax

        H = self._dec._n_heads
        h = _ln(x, lp["ln1_g"], lp["ln1_b"])
        q, k, v = _split_qkv(_dense(h, lp["qkv_w"], lp["qkv_b"]), H)
        d = q.shape[-1]
        # per-slot scatter of this token's k/v at the slot's position
        write = jax.vmap(
            lambda c, u, p: lax.dynamic_update_slice(c, u, (0, p, 0)))
        ck = write(ck, k.astype(ck.dtype), pos)
        cv = write(cv, v.astype(cv.dtype), pos)
        s = jnp.einsum("shqd,shkd->shqk", q, ck,
                       preferred_element_type=jnp.float32)
        s = s / math.sqrt(d)
        # each slot attends to its own 0..pos[s]; everything beyond is
        # stale (previous occupant / bucket padding) and masked out
        mask = jnp.arange(ck.shape[2])[None, :] <= pos[:, None]
        s = jnp.where(mask[:, None, None, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(cv.dtype)
        o = jnp.einsum("shqk,shkd->shqd", p, cv)
        S = x.shape[0]
        o = jnp.transpose(o, (0, 2, 1, 3)).reshape(S, 1, H * d)
        x = x + _dense(o, lp["proj_w"], lp["proj_b"])
        h = _ln(x, lp["ln2_g"], lp["ln2_b"])
        ffn = _dense(jax.nn.gelu(_dense(h, lp["ffn1_w"], lp["ffn1_b"])),
                     lp["ffn2_w"], lp["ffn2_b"])
        return x + ffn, ck, cv

    def _sample_slots(self, logits, key, temperature, top_k, do_sample):
        """`GPTDecoder._sample` with a PER-SLOT temperature vector."""
        jax = _j()
        jnp = jax.numpy
        if not do_sample:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits.astype(jnp.float32) / temperature[:, None]
        if top_k is not None:
            vals, idx = jax.lax.top_k(logits, top_k)
            choice = jax.random.categorical(key, vals, axis=-1)
            return jnp.take_along_axis(
                idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    def _build_decode(self):
        jax = _j()
        jnp = jax.numpy
        lax = jax.lax
        dec = self._dec

        def decode(params, ck, cv, last_tok, pos, active, key, temperature,
                   *, top_k, do_sample):
            x = (params["embed"][last_tok][:, None, :]
                 + params["pos"][pos][:, None, :])        # (S, 1, C)

            def dec_layer(x, layer):
                lp, ck_l, cv_l = layer
                x, ck_l, cv_l = self._slot_decode_layer(x, lp, ck_l, cv_l,
                                                        pos)
                return x, (ck_l, cv_l)

            x, (ck, cv) = lax.scan(dec_layer, x,
                                   (params["layers"], ck, cv))
            logits = dec._logits(params, x[:, 0])          # (S, V)
            nxt = self._sample_slots(logits, key, temperature, top_k,
                                     do_sample)
            # free/retired slots carry their last token forward — the
            # host never reads them, but a defined value keeps the
            # program deterministic
            nxt = jnp.where(active, nxt, last_tok)
            return ck, cv, nxt

        return jax.jit(decode, static_argnames=("top_k", "do_sample"),
                       donate_argnums=(1, 2))

    # -- host-facing steps --------------------------------------------------

    def prefill(self, slot, prompt_ids, key, temperature=1.0):
        """Prefill `prompt_ids` (1D int32) into `slot`; returns the
        request's first sampled token (host int)."""
        jnp = _j().numpy
        self._dec._auto_refresh()
        self._ensure_cache()
        if self._prefill_jit is None:
            self._prefill_jit = self._build_prefill()
        ids = jnp.asarray(prompt_ids, jnp.int32)[None, :]
        padded, t0 = bucket_prompt(ids, buckets=self.buckets,
                                   max_len=self.max_len)
        # host-side annotation onto the scheduler's serve.prefill span:
        # which compiled bucket program served this prompt
        tracing.annotate(bucket=int(padded.shape[1]),
                         pad_tokens=int(padded.shape[1]) - int(t0))
        self._ck, self._cv, first = self._prefill_jit(
            self._dec._params, self._ck, self._cv, padded,
            jnp.int32(slot), jnp.int32(t0), key,
            jnp.float32(max(float(temperature), 1e-6)),
            top_k=self._top_k, do_sample=self._do_sample)
        return int(first)

    def decode_step(self, last_tok, pos, active, key, temperature):
        """One decode step for every slot. `last_tok`/`pos`/`active`/
        `temperature` are HOST arrays (shape ``(max_slots,)``) — the
        scheduler owns them, so the step loop never branches on device
        values. Returns the next token per slot as a host numpy array
        (the one host sync per step; the tokens go back to clients
        anyway)."""
        import numpy as onp

        jnp = _j().numpy
        self._dec._auto_refresh()
        self._ensure_cache()
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        self._ck, self._cv, nxt = self._decode_jit(
            self._dec._params, self._ck, self._cv,
            jnp.asarray(last_tok, jnp.int32),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(active, bool),
            key,
            jnp.asarray(temperature, jnp.float32),
            top_k=self._top_k, do_sample=self._do_sample)
        return onp.asarray(nxt)

    def xla_program_count(self):
        """Number of compiled programs across the prefill family (one
        per bucket actually seen) and the decode program — the
        recompile-count gate of `tests/test_serve.py` asserts this stays
        constant in steady state."""
        n = 0
        for f in (self._prefill_jit, self._decode_jit):
            if f is None:
                continue
            size = getattr(f, "_cache_size", None)
            if size is not None:
                n += int(size())
        return n
